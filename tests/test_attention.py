"""Attention + sequence-parallel correctness on the virtual 8-device mesh.

Strategy (SURVEY.md section 4 "multi-device without a cluster"): the dense
``full_attention`` is the semantic reference; ring and Ulysses sequence-
parallel implementations must match it allclose with the token axis sharded
8 ways. The ViT model trains a few steps and must be finite/learning.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from pytorch_distributed_mnist_tpu.ops.attention import (
    full_attention,
    online_softmax_block,
    online_softmax_finish,
    online_softmax_init,
)
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.parallel.ring import ring_attention
from pytorch_distributed_mnist_tpu.parallel.ulysses import ulysses_attention


B, T, H, D = 2, 64, 8, 16


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.key(0), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(("seq",))


def _naive(q, k, v, causal=False):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_full_attention_matches_naive(qkv, causal):
    q, k, v = qkv
    np.testing.assert_allclose(
        full_attention(q, k, v, causal=causal), _naive(q, k, v, causal),
        rtol=1e-5, atol=1e-5,
    )


def test_online_softmax_blockwise_matches_dense(qkv):
    """Folding K/V in 8 blocks through the online recurrence == dense."""
    q, k, v = qkv
    state = online_softmax_init(q)
    for blk in range(8):
        sl = slice(blk * T // 8, (blk + 1) * T // 8)
        state = online_softmax_block(state, q, k[:, sl], v[:, sl])
    np.testing.assert_allclose(
        online_softmax_finish(state), _naive(q, k, v), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(qkv, seq_mesh, causal):
    q, k, v = qkv
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=seq_mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(out, _naive(q, k, v, causal), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(qkv, seq_mesh, causal):
    q, k, v = qkv
    out = jax.jit(
        lambda a, b, c: ulysses_attention(a, b, c, mesh=seq_mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(out, _naive(q, k, v, causal), rtol=1e-5, atol=1e-5)


def test_ring_attention_uneven_heads_ok(seq_mesh):
    """Ring has no head-divisibility constraint (unlike Ulysses)."""
    ks = jax.random.split(jax.random.key(1), 3)
    q, k, v = (jax.random.normal(kk, (1, 16, 3, 8), jnp.float32) for kk in ks)
    out = ring_attention(q, k, v, mesh=seq_mesh)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    ks = jax.random.split(jax.random.key(2), 3)
    q, k, v = (jax.random.normal(kk, (1, 16, 3, 8), jnp.float32) for kk in ks)
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q, k, v, mesh=seq_mesh)


def test_ulysses_with_flash_local_matches_dense():
    """Ulysses + Pallas flash as the per-device local attention: the
    composition the CLI exposes as --sequence-parallel-impl ulysses
    --attention flash. Must match single-device dense attention."""
    from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention
    from pytorch_distributed_mnist_tpu.parallel.ulysses import (
        ulysses_attention,
    )

    mesh = make_mesh(("data", "seq"), shape=(2, 4))
    b, t, h, d = 2, 32, 8, 16
    k1, k2, k3 = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, h, d), jnp.float32)

    for causal in (False, True):
        want = full_attention(q, k, v, causal=causal)
        got = ulysses_attention(
            q, k, v, mesh=mesh, axis="seq", batch_axis="data",
            causal=causal, local_attention=flash_attention,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_sharded_flash_matches_dense_on_tp_mesh():
    """sharded_flash_attention on a data x model mesh: batch and heads
    sharded, kernel runs per-device, output equals dense attention."""
    from pytorch_distributed_mnist_tpu.ops.pallas.flash import (
        sharded_flash_attention,
    )

    mesh = make_mesh(("data", "model"), shape=(2, 4))
    b, t, h, d = 2, 32, 8, 16
    k1, k2, k3 = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, h, d), jnp.float32)
    for causal in (False, True):
        want = full_attention(q, k, v, causal=causal)
        got = sharded_flash_attention(
            q, k, v, mesh=mesh, batch_axis="data", head_axis="model",
            causal=causal,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
