"""Compile-latency subsystem tests: shared persistent-cache wiring
(utils/compile_cache.py), compile observability (utils/profiling.py
CompileLog), and the trainer's AOT precompile (train/steps.py +
train/trainer.py).

The persistent cache is deliberately NEVER enabled inside this pytest
process (see tests/conftest.py: in-process write-then-deserialize is
unsound on this jaxlib). Everything cache-ON runs in fresh subprocesses —
exactly the safe production patterns (cold run writes, warm fresh process
reads).
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pytorch_distributed_mnist_tpu.utils import compile_cache  # noqa: E402
from pytorch_distributed_mnist_tpu.utils.profiling import (  # noqa: E402
    CompileLog,
    compile_log,
)


@pytest.fixture
def cache_module_state():
    """Snapshot/restore compile_cache's module globals and the jax cache
    config so precedence tests can't leak into the suite (where the
    harness pinned 'no cache')."""
    saved = (compile_cache._ambient, compile_cache._pinned)
    saved_cfg = (
        jax.config.jax_compilation_cache_dir,
        jax.config.jax_persistent_cache_min_compile_time_secs,
        jax.config.jax_persistent_cache_min_entry_size_bytes,
    )
    yield
    compile_cache._ambient, compile_cache._pinned = saved
    jax.config.update("jax_compilation_cache_dir", saved_cfg[0])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      saved_cfg[1])
    jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                      saved_cfg[2])


# -- resolution precedence --------------------------------------------------


def test_flag_beats_env_and_default(cache_module_state, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_VAR, "/env/dir")
    assert compile_cache.resolve_cache_dir("/flag/dir") == "/flag/dir"
    # Empty flag = explicit disable, even with the env set.
    assert compile_cache.resolve_cache_dir("") is None


def test_env_beats_default(cache_module_state, monkeypatch):
    monkeypatch.setattr(compile_cache, "_pinned", False)
    monkeypatch.setattr(compile_cache, "_ambient", None)
    monkeypatch.setenv(compile_cache.ENV_VAR, "/env/dir")
    assert compile_cache.resolve_cache_dir(None) == "/env/dir"
    monkeypatch.setenv(compile_cache.ENV_VAR, "")
    assert compile_cache.resolve_cache_dir(None) is None


def test_default_is_repo_xla_cache(cache_module_state, monkeypatch):
    monkeypatch.setattr(compile_cache, "_pinned", False)
    monkeypatch.setattr(compile_cache, "_ambient", None)
    monkeypatch.delenv(compile_cache.ENV_VAR, raising=False)
    assert compile_cache.resolve_cache_dir(None) \
        == os.path.join(REPO, ".xla_cache")


def test_pinned_ambient_followed_by_flagless(cache_module_state, monkeypatch):
    """The harness's pin wins over the repo default for flag-less runs —
    including a pinned 'no cache' (what this very suite relies on)."""
    monkeypatch.delenv(compile_cache.ENV_VAR, raising=False)
    monkeypatch.setattr(compile_cache, "_pinned", True)
    monkeypatch.setattr(compile_cache, "_ambient", ("/pinned/dir", 1.0, 2))
    assert compile_cache.resolve_cache_dir(None) == "/pinned/dir"
    monkeypatch.setattr(compile_cache, "_ambient", (None, 1.0, 2))
    assert compile_cache.resolve_cache_dir(None) is None
    # An explicit flag still overrides the pin.
    assert compile_cache.resolve_cache_dir("/flag/dir") == "/flag/dir"


def test_configure_creates_dir_once(cache_module_state, tmp_path):
    target = tmp_path / "cache"
    assert not target.exists()
    got = compile_cache.configure(str(target))
    assert got == str(target) and target.is_dir()
    assert compile_cache.active_cache_dir() == str(target)
    # Idempotent: same dir again is a no-op (no reset, no error).
    assert compile_cache.configure(str(target)) == str(target)
    # Explicit disable turns it off entirely.
    assert compile_cache.configure("") is None
    assert compile_cache.active_cache_dir() is None


# -- CompileLog -------------------------------------------------------------


def test_compile_log_counts_backend_compiles():
    log = CompileLog()
    with log.measure("tiny"):
        jax.jit(lambda x: x * 2 + 1).lower(
            jax.ShapeDtypeStruct((4,), np.float32)).compile()
    log.close()
    rec = log.stats()["programs"]["tiny"]
    assert rec["backend_compiles"] >= 1
    assert rec["backend_compile_ms"] > 0
    assert rec["wall_ms"] >= rec["backend_compile_ms"] * 0.5
    # Persistent cache is off in-process: hit/miss must be None, not False.
    assert rec["persistent_cache_hit"] is None


def test_compile_log_thread_attribution():
    """Concurrent measures must not misfile each other's compiles: the
    listener attributes to the measuring THREAD's open record."""
    import threading

    log = CompileLog()
    done = []

    def work(name, k):
        with log.measure(name):
            jax.jit(lambda x, k=k: x + k).lower(
                jax.ShapeDtypeStruct((8, k + 1), np.float32)).compile()
        done.append(name)

    threads = [threading.Thread(target=work, args=(f"prog{k}", k))
               for k in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    stats = log.stats()["programs"]
    assert sorted(done) == ["prog0", "prog1", "prog2"]
    for k in range(3):
        assert stats[f"prog{k}"]["backend_compiles"] >= 1
    total = log.stats()["totals"]["backend_compiles"]
    assert total == sum(stats[f"prog{k}"]["backend_compiles"]
                        for k in range(3))


def test_compile_log_hit_miss_counters_subprocess(tmp_path):
    """Cache hit/miss counters against a REAL persistent cache — in a
    fresh child per phase (cold writes, warm reads: the safe patterns)."""
    code = """
import os, json
os.environ["JAX_PLATFORMS"] = "cpu"
import jax, numpy as np
jax.config.update("jax_platforms", "cpu")
import sys; sys.path.insert(0, {repo!r})
from pytorch_distributed_mnist_tpu.utils import compile_cache
from pytorch_distributed_mnist_tpu.utils.profiling import CompileLog
compile_cache.configure({cache!r})
log = CompileLog()
with log.measure("p"):
    jax.jit(lambda x: x @ x.T).lower(
        jax.ShapeDtypeStruct((16, 16), np.float32)).compile()
print("STATS=" + json.dumps(log.stats()["programs"]["p"]))
""".format(repo=REPO, cache=str(tmp_path / "cache"))
    out = []
    for phase in ("cold", "warm"):
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("STATS=")][-1]
        out.append(json.loads(line[len("STATS="):]))
    cold, warm = out
    assert cold["cache_misses"] >= 1 and cold["persistent_cache_hit"] is False
    assert warm["cache_misses"] == 0 and warm["cache_hits"] >= 1
    assert warm["persistent_cache_hit"] is True


# -- AOT precompile ---------------------------------------------------------


def _build_trainer(mode="scan", gather="host", seed=0):
    from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
    from pytorch_distributed_mnist_tpu.data.mnist import (
        normalize_images,
        synthetic_dataset,
    )
    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.trainer import Trainer

    images, labels = synthetic_dataset(256, seed=7)
    x = normalize_images(images)
    y = labels.astype(np.int32)
    train = MNISTDataLoader(x, y, batch_size=64, train=True, seed=seed)
    test = MNISTDataLoader(x[:128], y[:128], batch_size=64, train=False,
                           seed=seed)
    state = create_train_state(get_model("linear"), jax.random.key(seed))
    return Trainer(state, train, test, mesh=make_mesh(("data",)),
                   mode=mode, epoch_gather=gather)


def _count_backend_compiles(fn):
    """Backend-compile events fired while ``fn()`` runs on THIS thread."""
    from jax._src import monitoring

    events = []

    def listener(name, secs, **kw):
        if "backend_compile" in name:
            events.append(name)

    monitoring.register_event_duration_secs_listener(listener)
    try:
        fn()
    finally:
        monitoring._unregister_event_duration_listener_by_callback(listener)
    return len(events)


@pytest.mark.parametrize("mode,gather", [
    ("scan", "host"), ("scan", "device"), ("stepwise", "host"),
    ("explicit", "host"),
])
def test_precompile_first_step_compiles_nothing(mode, gather):
    """The acceptance hook: after precompile(wait=True), the first real
    train+eval pass triggers ZERO further XLA compiles of the trainer's
    programs — the precompiled executable IS the one the step uses.

    (A one-time scalar-add helper for stepwise metric accumulation is
    compiled at most once per process; it is warmed here before
    measuring so the assertion pins the trainer's programs alone.)"""
    compile_log.reset()
    tr = _build_trainer(mode, gather)
    tr.precompile(wait=True)
    # Warm the scalar f32 add the stepwise meter accumulation uses: the
    # MetricState leaves are f32[] REPLICATED ON THE MESH (program
    # outputs), and that one-per-process helper program is outside what
    # precompile covers (it is not a trainer program).
    from jax.sharding import NamedSharding, PartitionSpec as P

    _z = jax.device_put(jax.numpy.zeros((), jax.numpy.float32),
                        NamedSharding(tr.mesh, P()))
    float(_z + _z)
    assert len(tr._precompiled) == 2  # both programs really built

    def first_epoch():
        tr.train()
        tr.evaluate()

    assert _count_backend_compiles(first_epoch) == 0
    # Every program the mode runs was logged with a real compile.
    programs = compile_log.stats()["programs"]
    assert all(rec["backend_compiles"] >= 1 for rec in programs.values())
    assert len(programs) == 2


def test_precompile_trajectory_identical_to_lazy():
    """Background precompile racing the host staging must not change a
    single bit of the trajectory vs the lazy path."""
    a = _build_trainer()
    a.precompile()  # background threads; train() overlaps staging + joins
    b = _build_trainer()
    rows = []
    for tr in (a, b):
        hist = []
        for epoch in range(2):
            tr.train_loader.set_sample_epoch(epoch)
            l, acc = tr.train()
            el, ea = tr.evaluate()
            hist.append((l.average, acc.accuracy, el.average, ea.accuracy))
        rows.append(hist)
    assert rows[0] == rows[1]
    pa = jax.tree_util.tree_leaves(a.state.params)
    pb = jax.tree_util.tree_leaves(b.state.params)
    for la, lb in zip(pa, pb):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


def test_precompile_signature_mismatch_falls_back(capsys):
    """A loader swap after precompile must degrade to lazy compilation,
    not crash: the stale executable is dropped and jit recompiles."""
    tr = _build_trainer()
    tr.precompile(wait=True)
    # Change the epoch length out from under the precompiled program.
    from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
    from pytorch_distributed_mnist_tpu.data.mnist import (
        normalize_images,
        synthetic_dataset,
    )

    images, labels = synthetic_dataset(128, seed=9)
    tr.train_loader = MNISTDataLoader(
        normalize_images(images), labels.astype(np.int32),
        batch_size=64, train=True, seed=0)
    loss, acc = tr.train()  # steps_per_epoch changed: 4 -> 2
    assert acc.count == 128
    assert "no longer matches" in capsys.readouterr().err


def test_precompile_specs_match_staging():
    """The loader's spec methods must mirror exactly what staging
    produces — this is what makes AOT lowering hit the same program."""
    tr = _build_trainer()
    staged = tr.train_loader.stacked_epoch()
    spec = tr.train_loader.epoch_spec()
    assert set(staged) == set(spec)
    for k, v in staged.items():
        assert spec[k].shape == v.shape, k
        assert spec[k].dtype == v.dtype, k
    idx, mask = tr.train_loader.epoch_ticks()
    tspec = tr.train_loader.ticks_spec()
    assert tspec["idx"].shape == idx.shape
    assert tspec["mask"].shape == mask.shape


# -- shared wiring across entry points --------------------------------------


def test_cli_and_bench_share_cache_wiring(cache_module_state, monkeypatch,
                                          tmp_path):
    """Acceptance: cli.run() and bench.py use the SAME persistent-cache
    wiring — both route through utils/compile_cache.configure, no
    duplicated config-update code.

    configure is stubbed to RECORD without applying: actually enabling
    the persistent cache inside the pytest process is the exact
    read-after-write hazard conftest disables it for (an earlier version
    of this test applied it for real and planted a heap corruption that
    detonated two test files later). The application side is covered by
    test_configure_creates_dir_once (no jit compiles while enabled) and
    the subprocess tests below."""
    calls = []
    monkeypatch.setattr(compile_cache, "configure",
                        lambda flag=None: calls.append(flag) or flag)

    # bench side: configure_jax is the prologue every bench child runs.
    import bench

    monkeypatch.setenv("BENCH_COMPILE_CACHE", str(tmp_path / "bench"))
    bench.configure_jax(jax, force_cpu=True)
    assert calls == [str(tmp_path / "bench")]

    # cli side: run() passes its --compile-cache flag to the same function.
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    calls.clear()
    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear",
        "--batch-size", "64", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64", "--seed", "0", "--epochs", "0",
        "--compile-cache", str(tmp_path / "cli"),
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    run(args)
    assert calls == [str(tmp_path / "cli")]


def test_cli_summary_carries_compile_stats(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    summary = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear",
        "--batch-size", "64", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64", "--seed", "0", "--epochs", "1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ]))
    programs = summary["compile_stats"]["programs"]
    assert "train_epoch" in programs and "eval_epoch" in programs
    assert programs["train_epoch"]["backend_compiles"] >= 1


# -- warm second run (the acceptance criterion) -----------------------------


_WARM_RUN_CODE = """
import os, json
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import sys; sys.path.insert(0, {repo!r})
from pytorch_distributed_mnist_tpu.cli import build_parser, run
summary = run(build_parser().parse_args([
    "--dataset", "synthetic", "--model", "linear",
    "--batch-size", "64", "--synthetic-train-size", "128",
    "--synthetic-test-size", "64", "--seed", "0", "--epochs", "1",
    "--checkpoint-dir", {ckpt!r}, "--compile-cache", {cache!r},
]))
print("TOTALS=" + json.dumps(summary["compile_stats"]["totals"]))
"""


def test_warm_second_run_recompiles_zero_programs(tmp_path):
    """Acceptance: with the persistent cache, a warm second run on CPU
    recompiles ZERO programs — every XLA compile request is a cache hit
    (compile-count hook == 0 misses after precompile + cache)."""
    cache = str(tmp_path / "cache")
    totals = []
    for phase in ("cold", "warm"):
        code = _WARM_RUN_CODE.format(
            repo=REPO, cache=cache, ckpt=str(tmp_path / ("ck_" + phase)))
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("TOTALS=")][-1]
        totals.append(json.loads(line[len("TOTALS="):]))
    cold, warm = totals
    assert cold["cache_misses"] >= 2  # train + eval programs really compiled
    assert warm["cache_misses"] == 0  # the criterion: zero recompiles
    assert warm["cache_hits"] >= 2


def test_compile_report_renders_stats(tmp_path, capsys):
    """tools/compile_report.py renders the compile_stats of bench-style
    artifacts (top-level and watcher-captured) and exits nonzero when no
    block exists."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import compile_report

    stats = {"programs": {"train_epoch": {
        "wall_ms": 1234.0, "backend_compiles": 1,
        "backend_compile_ms": 900.0, "cache_hits": 0, "cache_misses": 1,
        "persistent_cache_hit": False}},
        "totals": {"cache_hits": 0, "cache_misses": 1,
                   "backend_compiles": 1, "backend_compile_ms": 900.0}}
    direct = tmp_path / "bench.json"
    direct.write_text(json.dumps({
        "metric": "m", "backend": "tpu", "compile_stats": stats}) + "\n")
    nested = tmp_path / "watcher.json"
    nested.write_text(json.dumps({
        "captured": {"compile_stats": stats}, "backend": "cpu"}) + "\n")
    empty = tmp_path / "old.json"
    empty.write_text(json.dumps({"metric": "m", "value": 1.0}) + "\n")

    assert compile_report.main([str(direct), str(nested)]) == 0
    out = capsys.readouterr().out
    assert out.count("train_epoch") == 2
    assert "miss" in out
    assert compile_report.main([str(empty)]) == 1


def test_bench_output_contains_compile_stats_block(tmp_path):
    """Acceptance: bench.py child output carries the compile_stats block
    with per-program compile ms and cache hit/miss."""
    env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_PROBE="1",
               BENCH_COMPILE_CACHE=str(tmp_path / "cache"))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--child", "1", "1"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    result = json.loads(line)
    assert result["ok"], result
    stats = result["compile_stats"]
    rec = stats["programs"]["train_step"]
    assert rec["wall_ms"] > 0
    assert rec["persistent_cache_hit"] in (True, False)
