"""End-to-end smoke of ``bench.py --mode input`` on the CPU backend: the
report must carry the ``input_pipeline`` block — feed-only throughput,
the pipelined-vs-synchronous paired speedup, the native-vs-NumPy
preprocess deltas, and BOTH zero-recompile verdicts — so the input-plane
BENCH schema can't silently rot while CI only exercises the in-process
pieces."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def test_bench_input_reports_pipeline_and_native_fields():
    env = os.environ.copy()
    env.update({
        "JAX_PLATFORMS": "cpu",
        # Small drives: this asserts SCHEMA, not throughput. The compile
        # cache stays off — the bench both writes and re-reads entries
        # in one process, the exact pattern DESIGN.md 6c bans.
        "BENCH_INPUT_STEPS": "4",
        "BENCH_INPUT_BATCH": "256",
        "BENCH_INPUT_REPS": "3",
        "BENCH_COMPILE_CACHE": "",
        "TPUMNIST_COMPILE_CACHE": "",
    })
    env.pop("XLA_FLAGS", None)  # let the bench pick its own isolation
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mode", "input"],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])

    assert report["metric"] == "mnist_input_pipeline_feed_images_per_sec"
    assert report.get("error") is None
    assert report["value"] > 0
    # CPU-fallback labeling, the --mode serve convention: the line says
    # what backend it measured.
    assert report["backend"] == "cpu"

    ip = report["input_pipeline"]
    # Feed-only throughput and its decomposition.
    assert ip["feed_images_per_sec"] > 0
    assert ip["feed_host_ms"] >= 0 and ip["feed_h2d_ms"] >= 0
    assert ip["feed_steps"] == 4 and ip["global_batch"] == 256

    # Pipelined vs synchronous epochs: positive walls, a positive median
    # speedup, and one paired ratio per rep (the ABBA methodology).
    assert ip["pipelined_epoch_ms"] > 0
    assert ip["synchronous_epoch_ms"] > 0
    assert isinstance(ip["pipelined_feed_speedup"], (int, float))
    assert ip["pipelined_feed_speedup"] > 0
    assert len(ip["pipeline_pairs"]) == 3
    assert ip["feed_window"] == 2
    assert 0.0 <= ip["overlap_fraction"] <= 1.0

    # Native-vs-NumPy on the serve dispatch path. With the library built
    # the speedups are numbers with one pair per rep; without it they
    # are labelled null — never fabricated.
    if ip["native_available"]:
        assert ip["native_preprocess_speedup"] > 0
        assert ip["native_pad_speedup"] > 0
        assert len(ip["native_preprocess_pairs"]) == 3
        assert len(ip["native_pad_pairs"]) == 3
    else:
        assert ip["native_preprocess_speedup"] is None
        assert ip["native_pad_speedup"] is None

    # The acceptance invariants: zero steady-state recompiles on BOTH
    # sides of the data plane.
    assert ip["zero_steady_state_recompiles_train"] is True
    assert ip["zero_steady_state_recompiles_serve"] is True
    assert isinstance(ip["cpu_compute_isolated"], bool)

    # vs_baseline is the pipelined-feed speedup (the headline ratio).
    assert report["vs_baseline"] == ip["pipelined_feed_speedup"]


def test_bench_input_numpy_fallback_labelled():
    """TPUMNIST_NATIVE=0: the same line runs fallback-only and must say
    so (native_available false, null speedups) instead of inventing a
    comparison it could not measure."""
    env = os.environ.copy()
    env.update({
        "JAX_PLATFORMS": "cpu",
        "TPUMNIST_NATIVE": "0",
        "BENCH_INPUT_STEPS": "2",
        "BENCH_INPUT_BATCH": "128",
        "BENCH_INPUT_REPS": "2",
        "BENCH_COMPILE_CACHE": "",
        "TPUMNIST_COMPILE_CACHE": "",
    })
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mode", "input"],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    ip = report["input_pipeline"]
    assert ip["native_available"] is False
    assert ip["native_preprocess_speedup"] is None
    assert ip["native_pad_speedup"] is None
    assert report.get("error") is None
