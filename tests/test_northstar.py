"""tools/northstar.py producer smoke (hermetic, CPU).

The north-star runner is a watcher-capture producer: a latent bug in it
surfaces only during a rare chip-recovery window and burns the capture
(the round-3 kernels postmortem class). These tests pin its JSON-line
contract, the honest dataset labelling, and the round-5 --epoch-gather
flag plumbing (host default, device selectable, identical trajectory)
on tiny CPU shapes so the on-chip run only ever measures.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NORTHSTAR = os.path.join(REPO, "tools", "northstar.py")

_TINY = [
    "--dataset", "synthetic", "--epochs", "2", "--batch-size", "64",
    "--synthetic-train-size", "256", "--synthetic-test-size", "128",
    "--target", "0.99", "--seed", "0",
]


def _run(tmp_path, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_COMPILE_CACHE="")
    proc = subprocess.run(
        [sys.executable, _NORTHSTAR, "--root", str(tmp_path / "data")]
        + _TINY + list(extra),
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    return json.loads(line)


@pytest.mark.slow
def test_northstar_json_contract_and_labelling(tmp_path):
    out = _run(tmp_path)
    # The fields BASELINE.md transcription and the watcher gates rely on.
    assert out["target_acc"] == 0.99
    assert isinstance(out["reached"], bool)
    assert out["backend"] == "cpu"
    assert out["n_chips"] >= 1
    assert out["seconds_total"] > 0
    # Honest labelling: an explicit synthetic run says synthetic.
    assert out["dataset"] == "synthetic"
    assert len(out["epoch_log"]) >= 1
    row = out["epoch_log"][0]
    assert set(row) == {"epoch", "seconds", "test_acc", "train_loss"}
    # Cumulative seconds are monotone (the compile-vs-train split the
    # cold/warm captures read off this log).
    secs = [r["seconds"] for r in out["epoch_log"]]
    assert secs == sorted(secs)


@pytest.mark.slow
def test_northstar_epoch_gather_flag(tmp_path):
    """Round-5: host is the default; device stays selectable and must be
    trajectory-identical (same programs modulo the gather path — the
    equivalence tests/test_device_gather.py pins at step level)."""
    host = _run(tmp_path)
    dev = _run(tmp_path, ["--epoch-gather", "device"])
    assert [r["test_acc"] for r in dev["epoch_log"]] == \
        [r["test_acc"] for r in host["epoch_log"]]
    assert [r["train_loss"] for r in dev["epoch_log"]] == \
        [r["train_loss"] for r in host["epoch_log"]]
