"""Fixture suite: the thread-lifecycle checker + the real spawn sites.

Firing fixtures pin the two incident shapes (the PR 6 feeder leak and
the PR 10 orphaned loadgen); the reversion tests re-introduce the
shipped bugs into the REAL files and assert the checker reproduces a
file:line finding — the acceptance contract for analyzer v2.
"""

import os
import pathlib

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src, filename="snippet.py"):
    return analyze_snippet(src, checkers=["thread-lifecycle"],
                           filename=filename)


# -- firing ------------------------------------------------------------------


def test_fires_on_unjoined_attribute_thread():
    """The PR 6 feeder-leak shape: an attribute handle with no join
    anywhere in the class — daemon=True does not excuse it."""
    src = """
import threading

class Conduit:
    def __init__(self, m):
        self._thread = threading.Thread(
            target=self._feed, args=(m,), daemon=True)
        self._thread.start()

    def _feed(self, m):
        for row in m:
            self.stage(row)
"""
    (f,) = _findings(src)
    assert f.symbol == "Conduit.__init__"
    assert "self._thread" in f.message and "PR 6" in f.message


def test_fires_on_happy_path_only_popen_reap():
    """The PR 10 orphaned-loadgen shape: communicate(timeout=) whose
    expiry raises past the only reap."""
    src = """
import subprocess

def run_twin(argv, timeout):
    lg = subprocess.Popen(argv, stdout=subprocess.PIPE)
    out, _ = lg.communicate(timeout=timeout)
    return out
"""
    (f,) = _findings(src)
    assert "happy path" in f.message and "PR 10" in f.message
    assert f.line == 5


def test_fires_on_anonymous_nondaemon_thread():
    src = """
import threading

def go(fn):
    threading.Thread(target=fn).start()
"""
    (f,) = _findings(src)
    assert "anonymous" in f.message


def test_fires_on_container_of_popens_without_protected_reap():
    """The elastic.py shape before the fix: the reap loop existed but
    only on one branch, unprotected — an exception mid-wait orphaned
    every rank."""
    src = """
import subprocess

def run_generation(cmds):
    procs = []
    for cmd in cmds:
        procs.append(subprocess.Popen(cmd))
    while True:
        if all(p.poll() is not None for p in procs):
            break
    for p in procs:
        p.wait()
"""
    (f,) = _findings(src)
    assert "'procs'" in f.message


def test_fires_on_constructed_and_discarded_popen():
    src = """
import subprocess

def fire_and_forget(cmd):
    subprocess.Popen(cmd)
"""
    (f,) = _findings(src)
    assert "discarded" in f.message


# -- non-firing --------------------------------------------------------------


def test_clean_on_joined_local_thread():
    src = """
import threading

def run(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()
"""
    assert _findings(src) == []


def test_clean_on_popen_context_manager():
    src = """
import subprocess

def run(cmd):
    with subprocess.Popen(cmd) as p:
        return p.wait()
"""
    assert _findings(src) == []


def test_clean_on_protected_communicate():
    """The chaos.py fix shape: any failure kills and waits before
    propagating."""
    src = """
import subprocess

def run_twin(argv, timeout):
    lg = subprocess.Popen(argv, stdout=subprocess.PIPE)
    try:
        out, _ = lg.communicate(timeout=timeout)
    except BaseException:
        lg.kill()
        lg.wait()
        raise
    return out
"""
    assert _findings(src) == []


def test_clean_on_daemon_thread_with_sentinel_loop():
    src = """
import threading

def serve(interval):
    stop = threading.Event()

    def periodic():
        while not stop.wait(interval):
            tick()

    t = threading.Thread(target=periodic, daemon=True)
    t.start()
    return stop
"""
    assert _findings(src) == []


def test_clean_on_daemon_timer():
    """The watchdog hard-exit shape: a daemon Timer self-terminates."""
    src = """
import threading

def arm(deadline, fn):
    t = threading.Timer(deadline, fn)
    t.daemon = True
    t.start()
"""
    assert _findings(src) == []


def test_clean_on_comprehension_container_joined_in_loop():
    """The bench/loadgen drive shape: a list comprehension of threads
    reaped by a for loop over the container."""
    src = """
import threading

def drive(worker, n):
    threads = [threading.Thread(target=worker) for _ in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
"""
    assert _findings(src) == []


def test_clean_on_container_with_protected_reap():
    """The elastic.py fixed shape: the sweep lives in a finally."""
    src = """
import subprocess

def run_generation(cmds):
    procs = []
    for cmd in cmds:
        procs.append(subprocess.Popen(cmd))
    try:
        poll_until_done(procs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()
"""
    assert _findings(src) == []


def test_clean_on_handle_handed_off():
    """Escapes — returned, or passed to a call (positionally or by
    keyword) — transfer lifecycle ownership to the recipient."""
    src = """
import subprocess, threading

def spawn(cmd):
    return subprocess.Popen(cmd)

def register(fleet, cmd):
    fleet.add(proc=subprocess.Popen(cmd))

def track(registry, fn):
    t = threading.Thread(target=fn)
    t.start()
    registry.watch(t)
"""
    assert _findings(src) == []


def test_method_use_of_handle_is_not_an_escape():
    """`out, _ = lg.communicate(...)` reads the handle's method — the
    suppressed-finding bug class this checker's escape rule had to
    dodge: a use is not a handoff."""
    src = """
import subprocess

def run(cmd, timeout):
    lg = subprocess.Popen(cmd)
    out, _ = lg.communicate(timeout=timeout)
    code = lg.returncode
    return out, code
"""
    (f,) = _findings(src)  # still fires: the reap is unprotected
    assert "happy path" in f.message


# -- reversion: re-introduce the shipped bugs into the REAL files ------------


_STAGING = pathlib.Path(_REPO) / "pytorch_distributed_mnist_tpu" / \
    "data" / "staging.py"
_CHAOS = pathlib.Path(_REPO) / "tools" / "chaos.py"


def test_removing_the_feeder_join_fails_the_gate():
    """Drop close()'s `self._thread.join()` — the exact PR 6 bug — and
    the checker must flag the feeder spawn with file:line."""
    source = _STAGING.read_text()
    assert "self._thread.join()" in source
    broken = source.replace("self._thread.join()",
                            "self._thread.is_alive()", 1)
    findings = _findings(broken, filename="staging.py")
    assert findings, "unjoined feeder thread was not flagged"
    f = findings[0]
    assert f.path == "staging.py" and f.line > 0
    assert "self._thread" in f.message


def test_pristine_staging_is_clean():
    assert _findings(_STAGING.read_text(), filename="staging.py") == []


def test_unprotecting_a_chaos_communicate_fails_the_gate():
    """Swap the cache-storm `_communicate_reaped(storm, ...)` back to
    the bare `storm.communicate(timeout=...)` — the exact PR 10 orphan
    — and the checker must flag that spawn site."""
    source = _CHAOS.read_text()
    old = "out, _ = _communicate_reaped(storm, args.timeout)"
    assert old in source
    broken = source.replace(
        old, "out, _ = storm.communicate(timeout=args.timeout)", 1)
    findings = _findings(broken, filename="chaos.py")
    assert findings, "unprotected communicate was not flagged"
    assert any("'storm'" in f.message and "PR 10" in f.message
               for f in findings)


def test_pristine_chaos_is_clean():
    assert _findings(_CHAOS.read_text(), filename="chaos.py") == []
