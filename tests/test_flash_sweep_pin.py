"""Pin ``_block_sizes``'s heuristic to the measured flash sweep.

Round-4 VERDICT #2: the T=4096 flash block decision must be made by
measurement (tools/sweep_flash.py, captured by the watcher to
tools/captured/flash_sweep.json) and then PINNED so the shipped
heuristic can't silently drift from what the chip said. This test is
that pin, placed in the hermetic suite so it runs on every bar (not
just the rare on-chip windows): it SKIPS while no valid capture exists,
and activates permanently the moment the watcher commits one — from
then on, a heuristic choice measurably worse than the best swept block
fails the suite until ``_block_sizes`` is updated to match the
evidence.
"""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SWEEP = os.path.join(REPO, "tools", "captured", "flash_sweep.json")

# The heuristic's pick may be this much slower than the best swept block
# before the pin fails — covers rep-to-rep noise without letting a real
# regression (the hypothesized 128-vs-512 gap at T=4096) through.
_TOLERANCE = 1.10


def _load_valid_sweep():
    if not os.path.exists(_SWEEP):
        pytest.skip("no flash_sweep.json captured yet (chip-gated)")
    try:
        with open(_SWEEP) as f:
            sweep = json.loads(f.read().strip().splitlines()[-1])
    except (OSError, IndexError, UnicodeDecodeError,
            json.JSONDecodeError) as exc:
        # An empty/truncated/corrupt file is not evidence; it must not
        # turn every hermetic run into an ERROR either.
        pytest.skip(f"flash_sweep.json unreadable ({exc!r}); not valid "
                    "evidence, pin stays unarmed")
    if not isinstance(sweep, dict):
        # 'null'/'[]'/'42' parse as JSON but are not a capture.
        pytest.skip("flash_sweep.json last line is not a JSON object; "
                    "not valid evidence, pin stays unarmed")
    # The same validity gates the watcher's rc check enforces, re-checked
    # here so a hand-copied or invalidated file can never arm the pin.
    if sweep.get("invalid"):
        pytest.skip(f"captured sweep marked invalid: {sweep['invalid']}")
    if sweep.get("backend") != "tpu" or sweep.get("quick") \
            or sweep.get("fake_bounds"):
        pytest.skip("captured sweep is not a real-TPU full-shape run")
    if sweep.get("sync") != "host_read":
        pytest.skip("captured sweep lacks the host_read sync marker "
                    "(pre-round-4 harness; not valid evidence)")
    if not sweep.get("rows"):
        pytest.skip("captured sweep has no rows")
    return sweep


def test_block_heuristic_matches_measured_sweep():
    from pytorch_distributed_mnist_tpu.ops.pallas.flash import _block_sizes

    sweep = _load_valid_sweep()
    for row in sweep["rows"]:
        t = row["seq_len"]
        chosen, _ = _block_sizes(t)
        times = {
            int(key[len("flash_b"):-len("_ms")]): row[key]
            for key in row
            if key.startswith("flash_b") and key.endswith("_ms")
        }
        if not times:
            continue
        assert chosen in times, (
            f"T={t}: heuristic picked block {chosen}, which the sweep "
            f"never measured ({sorted(times)}) — extend the sweep or fix "
            f"the heuristic")
        best_block = min(times, key=times.get)
        assert times[chosen] <= times[best_block] * _TOLERANCE, (
            f"T={t}: heuristic block {chosen} measured {times[chosen]}ms "
            f"but block {best_block} measured {times[best_block]}ms "
            f"(>{_TOLERANCE}x) — update _block_sizes to the measured "
            f"choice (tools/captured/flash_sweep.json)")
