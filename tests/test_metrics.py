"""Unit tests for ops/metrics.py — parity with reference Average/Accuracy
(``/root/reference/multi_proc_single_gpu.py:28-65``)."""

import jax.numpy as jnp
import numpy as np

from pytorch_distributed_mnist_tpu.ops.metrics import (
    Accuracy,
    Average,
    metrics_init,
    metrics_merge,
    metrics_update,
)


def test_average_weighted_mean_and_format():
    m = Average()
    m.update(2.0, 3)  # sum=6, count=3
    m.update(4.0, 1)  # sum=10, count=4
    assert m.average == 2.5
    assert str(m) == "2.500000"  # 6-decimal format parity (:34-35)


def test_average_empty_is_zero():
    assert Average().average == 0.0


def test_accuracy_percent_format():
    a = Accuracy()
    a.update(3, 4)
    assert a.accuracy == 0.75
    assert str(a) == "75.00%"  # percent 2-decimal parity (:52-53)


def test_metric_state_update_matches_host_math():
    ms = metrics_init()
    logits = jnp.array([[2.0, 0.0], [0.0, 2.0], [2.0, 0.0]])
    labels = jnp.array([0, 1, 1])  # preds: 0,1,0 -> 2 correct
    ms = metrics_update(ms, jnp.asarray(0.5), logits, labels)
    assert float(ms.count) == 3
    assert float(ms.correct) == 2
    np.testing.assert_allclose(float(ms.loss_sum), 1.5)


def test_metrics_merge_adds():
    a = metrics_update(metrics_init(), jnp.asarray(1.0), jnp.ones((2, 3)), jnp.zeros(2, jnp.int32))
    b = metrics_update(metrics_init(), jnp.asarray(2.0), jnp.ones((4, 3)), jnp.zeros(4, jnp.int32))
    m = metrics_merge(a, b)
    assert float(m.count) == 6
    np.testing.assert_allclose(float(m.loss_sum), 1.0 * 2 + 2.0 * 4)


def test_accuracy_from_state():
    ms = metrics_update(
        metrics_init(),
        jnp.asarray(0.0),
        jnp.array([[1.0, 0.0], [1.0, 0.0]]),
        jnp.array([0, 1]),
    )
    a = Accuracy()
    a.update_from_state(ms)
    assert a.accuracy == 0.5
