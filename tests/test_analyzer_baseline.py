"""Baseline round-trip and JSON output schema stability.

Round-trip: a finding appears -> a baseline entry suppresses it (run
goes green) -> the code is fixed -> the now-stale entry fails the run.
Plus: entries without justifications are config errors, and the JSON
schema the CI/report consumers parse is pinned key-for-key.
"""

import json

import pytest


from tools.analyzer import (  # noqa: E402
    SCHEMA_VERSION,
    load_baseline,
    run_analysis,
)

pytestmark = pytest.mark.lint

_VIOLATION = """\
import threading, jax

class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def swap(self, params):
        with self._lock:
            self._params = jax.device_put(params)
"""

_FIXED = """\
import threading, jax

class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def swap(self, params):
        placed = jax.device_put(params)
        with self._lock:
            self._params = placed
"""


def test_baseline_roundtrip_add_suppress_stale(tmp_path):
    target = tmp_path / "engine_twin.py"
    target.write_text(_VIOLATION)

    # 1. The finding appears (no baseline).
    result = run_analysis([str(target)], baseline=None)
    assert len(result.findings) == 1
    finding = result.findings[0]
    assert finding.checker == "lock-discipline"

    # 2. Baseline it (triaged-accepted, justified): run goes green and
    #    the suppression is attributed to the entry.
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps([{
        "checker": finding.checker,
        "path": finding.path,
        "contains": "device_put",
        "justification": "twin fixture: accepted for the round-trip test",
    }]))
    result = run_analysis([str(target)], baseline=str(baseline))
    assert result.ok
    assert result.findings == []
    assert len(result.suppressed) == 1
    assert result.suppressed[0][1]["contains"] == "device_put"

    # 3. Fix the code: the entry is now stale and FAILS the run — the
    #    baseline can only shrink, never rot.
    target.write_text(_FIXED)
    result = run_analysis([str(target)], baseline=str(baseline))
    assert not result.ok
    assert result.findings == []
    assert len(result.stale_baseline) == 1

    # 4. Delete the entry: green again.
    baseline.write_text("[]")
    result = run_analysis([str(target)], baseline=str(baseline))
    assert result.ok


def test_subset_run_does_not_condemn_out_of_set_entries(tmp_path):
    """Linting a path subset must not report entries for files the run
    never analyzed as stale — ``tools/analyzer some/file.py`` is an
    advertised usage and must stay green on a clean file."""
    violating = tmp_path / "engine_twin.py"
    violating.write_text(_VIOLATION)
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")

    result = run_analysis([str(violating)], baseline=None)
    (finding,) = result.findings
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps([{
        "checker": finding.checker,
        "path": finding.path,
        "contains": "device_put",
        "justification": "twin fixture: accepted for the subset test",
    }]))

    # Subset that excludes the baselined file: entry is NOT judged.
    result = run_analysis([str(clean)], baseline=str(baseline))
    assert result.ok and result.stale_baseline == []

    # Full set including the (still-violating) file: entry is used.
    result = run_analysis([str(clean), str(violating)],
                          baseline=str(baseline))
    assert result.ok and len(result.suppressed) == 1

    # Fix the file and analyze it: NOW the unused entry is stale.
    violating.write_text(_FIXED)
    result = run_analysis([str(violating)], baseline=str(baseline))
    assert not result.ok and len(result.stale_baseline) == 1


def test_baseline_entry_without_justification_is_a_problem(tmp_path):
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps([{
        "checker": "lock-discipline", "path": "x.py",
        "contains": "anything", "justification": "  ",
    }]))
    entries, problems = load_baseline(str(baseline))
    assert entries == []
    assert len(problems) == 1 and "justification" in problems[0]

    target = tmp_path / "clean.py"
    target.write_text("x = 1\n")
    result = run_analysis([str(target)], baseline=str(baseline))
    assert not result.ok  # a malformed baseline fails the gate loudly


def test_missing_explicit_baseline_is_a_problem(tmp_path):
    target = tmp_path / "clean.py"
    target.write_text("x = 1\n")
    result = run_analysis([str(target)],
                          baseline=str(tmp_path / "absent.json"))
    assert not result.ok
    assert result.baseline_problems


def test_parse_error_findings_cannot_be_baselined(tmp_path):
    target = tmp_path / "broken.py"
    target.write_text("def f(:\n")
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps([{
        "checker": "parse-error", "path": "broken.py",
        "contains": "could not parse",
        "justification": "trying to hide a syntax error",
    }]))
    result = run_analysis([str(target)], baseline=str(baseline))
    assert not result.ok
    assert result.baseline_problems  # the entry itself is rejected
    assert any(f.checker == "parse-error" for f in result.findings)


# -- JSON schema stability ---------------------------------------------------

_TOP_KEYS = {"schema_version", "paths", "checkers", "findings",
             "suppressed", "stale_baseline", "baseline_problems",
             "reports", "cache", "summary"}
_FINDING_KEYS = {"checker", "path", "line", "col", "message", "hint",
                 "symbol"}
_SUMMARY_KEYS = {"files", "findings", "suppressed", "stale_baseline", "ok"}


def test_json_output_schema_is_stable(tmp_path):
    target = tmp_path / "engine_twin.py"
    target.write_text(_VIOLATION)
    payload = run_analysis([str(target)], baseline=None).to_dict()
    assert payload["schema_version"] == SCHEMA_VERSION == 1
    assert set(payload) == _TOP_KEYS
    assert set(payload["summary"]) == _SUMMARY_KEYS
    assert payload["findings"], "fixture should produce one finding"
    for f in payload["findings"]:
        assert set(f) == _FINDING_KEYS
        assert isinstance(f["line"], int) and f["line"] > 0
    # suppressed rows are findings + the justification that excused them
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps([{
        "checker": "lock-discipline", "path": payload["findings"][0]["path"],
        "contains": "device_put", "justification": "schema fixture",
    }]))
    payload = run_analysis([str(target)],
                           baseline=str(baseline)).to_dict()
    for row in payload["suppressed"]:
        assert set(row) == _FINDING_KEYS | {"justification"}
    # the lock graph report keeps its shape
    graph = payload["reports"]["lock-discipline"]["lock_graph"]
    (mod_report,) = graph.values()
    assert set(mod_report) == {"locks", "order_edges"}


def test_json_output_is_deterministic(tmp_path):
    target = tmp_path / "engine_twin.py"
    target.write_text(_VIOLATION)
    a = run_analysis([str(target)], baseline=None).to_dict()
    b = run_analysis([str(target)], baseline=None).to_dict()
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_collected_skip_dirs_are_rooted_not_bare_names(tmp_path):
    """A source directory merely NAMED 'captured' must still be analyzed;
    only the repo-rooted tools/captured artifact dir is skipped (bare-name
    skipping would let the gate silently drop a real package dir)."""
    from tools.analyzer.core import collect_files

    (tmp_path / "pyproject.toml").write_text("[tool.x]\n")
    pkg = tmp_path / "pkg" / "captured"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text("x = 1\n")
    artifacts = tmp_path / "tools" / "captured"
    artifacts.mkdir(parents=True)
    (artifacts / "stray.py").write_text("x = 1\n")
    cache = tmp_path / "pkg" / "__pycache__"
    cache.mkdir()
    (cache / "junk.py").write_text("x = 1\n")

    files, problems = collect_files([str(tmp_path)])
    rel = {str(f).replace(str(tmp_path), "").replace("\\", "/").lstrip("/")
           for f in files}
    assert problems == []
    assert "pkg/captured/mod.py" in rel
    assert "tools/captured/stray.py" not in rel
    assert not any("__pycache__" in f for f in rel)
