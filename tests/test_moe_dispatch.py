"""Capacity-factor MoE dispatch (parallel/moe_dispatch.py): the all_to_all
token-routing path vs the dense-dispatch oracle, drop semantics, the
distributed == local equivalence, and the load-balancing auxiliary loss.

VERDICT round 1 called dense-only dispatch 'half-built'; the contract
pinned here is the one the module docstring promises: capacity dispatch
matches dense dispatch exactly when no token drops.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.models.moe import SwitchMoE
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.parallel.moe_dispatch import (
    build_dispatch,
    load_balance_loss,
    moe_capacity_forward,
)

E = 8


def _moe(dispatch, mesh=None, cf=float(E)):
    # capacity_factor=E -> capacity == local batch -> nothing can drop.
    return SwitchMoE(num_experts=E, hidden=32, dispatch=dispatch,
                     capacity_factor=cf, mesh=mesh)


def _data(b=64, c=16, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(k1, (b, c), jnp.float32)
    return x, k2


def test_build_dispatch_positions_and_drops():
    # 4 tokens all routed to expert 0, capacity 2: first two keep slots
    # 0/1, the rest drop.
    probs = jnp.tile(jnp.array([[0.9] + [0.1 / (E - 1)] * (E - 1)]), (4, 1))
    dispatch, combine = build_dispatch(probs, capacity=2)
    assert dispatch.shape == (4, E, 2)
    np.testing.assert_array_equal(
        np.asarray(dispatch[:, 0].sum(-1)), [1, 1, 0, 0]
    )
    # combine carries the routed prob for kept tokens only
    np.testing.assert_allclose(np.asarray(combine[:2, 0].sum(-1)), 0.9,
                               rtol=1e-6)
    assert float(combine[2:].sum()) == 0.0


def test_capacity_matches_dense_when_no_drops():
    x, key = _data()
    dense = _moe("dense")
    params = dense.init(key, x)
    ref = dense.apply(params, x)
    out = _moe("capacity").apply(params, x)  # same params: same router
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_capacity_distributed_matches_local(mesh8):
    """shard_map all_to_all path == the no-mesh local program."""
    mesh = make_mesh(("data", "expert"), shape=(2, 4))
    x, key = _data()
    local = _moe("capacity")
    params = local.init(key, x)
    ref = local.apply(params, x)
    out = _moe("capacity", mesh=mesh).apply(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_capacity_grads_match_dense(mesh8):
    mesh = make_mesh(("data", "expert"), shape=(2, 4))
    x, key = _data()
    dense = _moe("dense")
    params = dense.init(key, x)

    def loss(apply_params, module):
        return jnp.sum(jnp.sin(module.apply(apply_params, x)))

    g_ref = jax.grad(loss)(params, dense)
    g_cap = jax.grad(loss)(params, _moe("capacity", mesh=mesh))
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
        jax.tree_util.tree_flatten_with_path(g_cap)[0],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=jax.tree_util.keystr(pa))


def test_oversubscribed_tokens_drop_to_zero():
    x, key = _data(b=32)
    moe = _moe("capacity", cf=0.25)  # capacity = 1 slot per expert
    params = moe.init(key, x)
    out = moe.apply(params, x)
    # at most E tokens can be served; the rest must be exactly zero rows
    served = np.count_nonzero(np.abs(np.asarray(out)).sum(-1) > 1e-9)
    assert served <= E


def test_aux_loss_uniform_is_one_and_collapse_grows():
    uniform = jnp.full((128, E), 1.0 / E)
    assert float(load_balance_loss(uniform)) == pytest.approx(1.0, rel=1e-6)
    collapsed = jax.nn.one_hot(jnp.zeros(128, jnp.int32), E)
    assert float(load_balance_loss(collapsed)) == pytest.approx(E, rel=1e-6)


def test_aux_loss_sown_by_module():
    x, key = _data()
    moe = _moe("dense")
    params = moe.init(key, x)
    _, inter = moe.apply(params, x, mutable=["intermediates"])
    (aux,) = inter["intermediates"]["aux_loss"]
    assert np.isfinite(float(aux)) and float(aux) >= 1.0 - 1e-6


def test_moe_classifier_capacity_trains(mesh8, tiny_data):
    """Full train step: moe_mlp with capacity dispatch on a DP x EP mesh."""
    from pytorch_distributed_mnist_tpu.parallel.expert import moe_ep_rules
    from pytorch_distributed_mnist_tpu.parallel.tensor import (
        make_tp_train_step,
        shard_state,
    )
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.data.loader import make_global_batch

    mesh = make_mesh(("data", "expert"), shape=(2, 4))
    model = get_model("moe_mlp", dispatch="capacity", mesh=mesh,
                      capacity_factor=2.0)
    # Params are dispatch-independent; init with the dense twin (the batch-1
    # init trace can't satisfy the token sharding), then swap in the
    # capacity apply_fn — the same pattern the ring-attention ViT uses.
    state = create_train_state(get_model("moe_mlp"), jax.random.key(0))
    state = state.replace(apply_fn=model.apply)
    rules = moe_ep_rules("expert")
    state, sharding = shard_state(state, mesh, rules)
    step = make_tp_train_step(mesh, sharding)
    images, labels = tiny_data
    batch = make_global_batch(
        {"image": np.asarray(images[:32]), "label": np.asarray(labels[:32])},
        mesh,
    )
    state, m = step(state, batch)
    assert np.isfinite(float(m.loss_sum))
    assert int(m.count) == 32
