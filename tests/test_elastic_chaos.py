"""Elastic-world chaos twins: real multi-process worlds losing real
hosts to SIGKILL, supervised by ``runtime/elastic.py`` — proving the
shrink-don't-exit contract (and its GROW mirror) end to end:

- THE acceptance twin (tier-1): a 2-process world loses host 1 to
  SIGKILL *mid-epoch* (between per-batch step programs); the survivor
  agrees the shrunk world, is re-execed as a 1-host world, resumes from
  the last *published* checkpoint (cross-world reshard of the sharded
  zero1 layout), and trains to completion with NO operator action — and
  its post-shrink epoch metrics EQUAL a run started directly at the
  smaller world from the same checkpoint;
- a 3-process world shrinking to a 2-process world (multi-survivor
  membership agreement + a real 2-host rebuilt world);
- a SECOND failure *during* the shrink: a survivor killed (or stalled)
  in its survivor-record window just shrinks the next world further —
  never a hang (the supervisor's settle deadline bounds every rebuild);
- the ``--min-world`` floor: shrinking below it exits with the
  distinct floor code instead of training on a world the operator
  ruled out;
- the GROW acceptance twin (tier-1): the 2 -> 1 -> 2 round trip — host
  1 SIGKILLed mid-epoch, the world shrinks to 1, host 1's join record
  lands (the ``rejoin`` hook), the next epoch-boundary grow rendezvous
  admits it, and the job finishes back at world size 2 with post-grow
  epoch metrics BYTE-EQUAL to a direct 2-host run resumed from the
  same published checkpoint.

All twins drive ``elastic.supervise`` in-process (the supervisor makes
no jax calls; the workers are real subprocesses).
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from pytorch_distributed_mnist_tpu.parallel.launcher import (
    _child_env,
    spawn_local,
)
from pytorch_distributed_mnist_tpu.runtime.elastic import (
    EXIT_FLOOR,
    supervise,
)

pytestmark = [pytest.mark.chaos, pytest.mark.elastic]

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEADLINE = "8"

_BASE = ["--dataset", "synthetic", "--model", "linear",
         "--synthetic-train-size", "256", "--synthetic-test-size", "128",
         "--trainer-mode", "stepwise", "--seed", "0", "--resume", "auto"]


def _flags(ckpt, metrics, epochs=3, batch=64, extra=()):
    return _BASE + ["--epochs", str(epochs), "--batch-size", str(batch),
                    "--checkpoint-dir", str(ckpt),
                    "--metrics-file", str(metrics)] + list(extra)


def _rows(metrics_path):
    with open(metrics_path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _events(rows, kind):
    return [r for r in rows if r.get("kind") == kind]


def _epoch_rows_after_shrink(rows):
    """Epoch metric rows written by the rebuilt world (after the
    world_shrunk event line in the shared JSONL)."""
    idx = next(i for i, r in enumerate(rows)
               if r.get("kind") == "world_shrunk")
    return [r for r in rows[idx + 1:] if "train_loss" in r]


def _epoch_rows_after_grow(rows):
    """Epoch metric rows written by the GROWN world (after the
    world_grown event line in the shared JSONL)."""
    idx = next(i for i, r in enumerate(rows)
               if r.get("kind") == "world_grown")
    return [r for r in rows[idx + 1:] if "train_loss" in r]


def _strip_timing(row):
    return {k: v for k, v in row.items() if k not in ("images_per_sec",)}


def test_elastic_survives_midepoch_kill_and_matches_direct_small_world(
        tmp_path, monkeypatch):
    """THE acceptance twin. Host 1 is SIGKILLed between two of epoch 1's
    step programs (the ``train_step`` fault point). The elastic
    supervisor must: see host 0 unwind with the failure attributed,
    collect its survivor record, rebuild a 1-host world, and resume
    from epoch 0's published checkpoint (saved SHARDED by the 2-host
    zero1 world — a real cross-world reshard) to completion, rc 0, no
    operator action. Then the proof of equivalence: a fresh run started
    DIRECTLY at world size 1 from a copy of the same published
    checkpoint produces byte-equal epoch metrics."""
    ckpt, metrics = tmp_path / "ckpts", tmp_path / "metrics.jsonl"
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", _DEADLINE)
    # Skip 5 hits: epoch 0's four steps run whole (its checkpoint
    # publishes), the kill lands inside epoch 1's step loop.
    monkeypatch.setenv("TPUMNIST_FAULT", "train_step:1:kill:5")
    t0 = time.monotonic()
    rc = supervise(2, _flags(ckpt, metrics,
                             extra=["--optimizer-sharding", "zero1"]),
                   settle_timeout=60, generation_timeout=240)
    elapsed = time.monotonic() - t0
    assert rc == 0, f"elastic run failed (rc={rc})"
    assert elapsed < 200, f"shrink+resume took {elapsed:.0f}s"

    rows = _rows(metrics)
    shrunk = _events(rows, "world_shrunk")
    assert len(shrunk) == 1
    assert shrunk[0]["old_members"] == [0, 1]
    assert shrunk[0]["new_members"] == [0]
    # The resume inspected the checkpoint's world stamp: a 2-process
    # save resharded onto the 1-process world, recorded, not inferred
    # from a failed load.
    reshard = _events(rows, "checkpoint_reshard")
    assert reshard and reshard[0]["saved"]["processes"] == 2
    assert reshard[0]["current"]["processes"] == 1
    resumed = _epoch_rows_after_shrink(rows)
    assert [r["epoch"] for r in resumed] == [1, 2]
    # The rebuilt 1-host world published its epochs (npz at world 1).
    names = set(os.listdir(ckpt))
    assert {"checkpoint_1.npz", "checkpoint_2.npz"} <= names

    # Equivalence: world-1 run started directly from the published
    # checkpoint the shrink resumed from (epoch 0's — the only one
    # published before the kill).
    direct_ckpt = tmp_path / "direct_ckpts"
    direct_ckpt.mkdir()
    shutil.copytree(ckpt / "checkpoint_0.ckpt",
                    direct_ckpt / "checkpoint_0.ckpt")
    direct_metrics = tmp_path / "direct_metrics.jsonl"
    env = _child_env()
    env["TPUMNIST_AGREEMENT_TIMEOUT"] = _DEADLINE
    env.pop("TPUMNIST_FAULT", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytorch_distributed_mnist_tpu"]
        + _flags(direct_ckpt, direct_metrics,
                 extra=["--optimizer-sharding", "zero1"]),
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    direct = [r for r in _rows(direct_metrics) if "train_loss" in r]
    assert [r["epoch"] for r in direct] == [1, 2]
    for elastic_row, direct_row in zip(resumed, direct):
        assert _strip_timing(elastic_row) == _strip_timing(direct_row)


def test_slice_loss_shrinks_to_surviving_slice_flat_world(
        tmp_path, monkeypatch):
    """The slice-loss twin (PR 13 satellite): the 2-host world runs on
    the emulated hierarchical mesh (TPUMNIST_DCN_SLICES=2 — one host
    per slice, exactly the chaos ``--kill-slice`` composition), and
    EVERY host of slice 1 (= host 1) is SIGKILLed mid-epoch. The
    existing elastic machinery must handle it unchanged: the survivor
    votes, the supervisor rebuilds a 1-host world — which the
    configured slice count no longer divides, so cli.py's elastic
    fallback lands it on the surviving slice's FLAT mesh (recorded as
    ``dcn_flat_fallback``) — and the hier-written zero1 checkpoint
    reshards through the ordinary (W, W') matrix to completion, rc 0,
    no new elastic machinery."""
    ckpt, metrics = tmp_path / "ckpts", tmp_path / "metrics.jsonl"
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", _DEADLINE)
    monkeypatch.setenv("TPUMNIST_DCN_SLICES", "2")
    # Slice 1 = host 1 (one host per emulated slice); epoch 0's four
    # steps run whole so its checkpoint publishes, the kill lands
    # inside epoch 1's step loop — the --kill-slice spec shape.
    monkeypatch.setenv("TPUMNIST_FAULT", "train_step:1:kill:5")
    rc = supervise(2, _flags(ckpt, metrics,
                             extra=["--optimizer-sharding", "zero1"]),
                   settle_timeout=60, generation_timeout=240)
    assert rc == 0, f"slice-loss elastic run failed (rc={rc})"

    rows = _rows(metrics)
    shrunk = _events(rows, "world_shrunk")
    assert len(shrunk) == 1
    assert shrunk[0]["old_members"] == [0, 1]
    assert shrunk[0]["new_members"] == [0]
    # The rebuilt world could not host 2 DCN slices and said so — the
    # designed degradation, not a silent relayout.
    fallback = _events(rows, "dcn_flat_fallback")
    assert fallback and "flat" in fallback[0]["detail"]
    # The hier-written checkpoint resharded onto the flat small world
    # through the ordinary path, and the job trained to completion.
    reshard = _events(rows, "checkpoint_reshard")
    assert reshard and reshard[0]["saved"]["processes"] == 2
    resumed = _epoch_rows_after_shrink(rows)
    assert [r["epoch"] for r in resumed] == [1, 2]


def test_shrink_then_grow_matches_direct_large_world(
        tmp_path, monkeypatch):
    """THE grow acceptance twin (tier-1): the 2 -> 1 -> 2 round trip.

    Host 1 is SIGKILLed inside epoch 1's step loop; the world shrinks
    to host 0 alone (generation 1), which trains epoch 1 and publishes
    its checkpoint. Meanwhile host 1 'returns': its join record lands
    while generation 1 runs (the supervise ``rejoin`` hook — exactly
    ``announce_join``). Generation 1's next epoch-boundary grow
    rendezvous admits it: every rank yields EXIT_GROW, and generation 2
    re-execs as a REAL 2-host world resumed from the 1-host world's
    checkpoint — a genuine W' > W cross-world reshard. The run
    completes rc 0 with both directions recorded and labeled.

    Then the proof of equivalence the ISSUE names: a fresh run started
    DIRECTLY at world size 2 from a copy of the same published
    checkpoint produces byte-equal post-grow epoch metrics."""
    ckpt, metrics = tmp_path / "ckpts", tmp_path / "metrics.jsonl"
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", _DEADLINE)
    # Skip 5 hits: epoch 0's four steps run whole (its checkpoint
    # publishes), the kill lands inside epoch 1's step loop.
    monkeypatch.setenv("TPUMNIST_FAULT", "train_step:1:kill:5")
    rc = supervise(2, _flags(ckpt, metrics,
                             extra=["--optimizer-sharding", "zero1"]),
                   grow=True, rejoin=[(1, 1)],
                   settle_timeout=60, generation_timeout=240)
    assert rc == 0, f"elastic grow run failed (rc={rc})"

    rows = _rows(metrics)
    shrunk = _events(rows, "world_shrunk")
    assert len(shrunk) == 1
    assert shrunk[0]["old_members"] == [0, 1]
    assert shrunk[0]["new_members"] == [0]
    grown = _events(rows, "world_grown")
    assert len(grown) == 1
    assert grown[0]["old_members"] == [0]
    assert grown[0]["new_members"] == [0, 1]
    # Both reshard events carry their direction label (the satellite):
    # the shrink resumed a 2-process save on 1 process, the grow a
    # 1-process save on 2.
    reshards = _events(rows, "checkpoint_reshard")
    assert [r["direction"] for r in reshards] == ["shrink", "grow"]
    assert reshards[1]["saved"]["processes"] == 1
    assert reshards[1]["current"]["processes"] == 2
    # The shrunk world trained epoch 1; the grown world epoch 2.
    assert [r["epoch"] for r in _epoch_rows_after_shrink(rows)] == [1, 2]
    resumed = _epoch_rows_after_grow(rows)
    assert [r["epoch"] for r in resumed] == [2]

    # Equivalence: a 2-host world started directly from the checkpoint
    # the grow resumed from (epoch 1's — published by the 1-HOST world,
    # so the direct twin reshards 1 -> 2 exactly as generation 2 did).
    direct_ckpt = tmp_path / "direct_ckpts"
    direct_ckpt.mkdir()
    shutil.copy(ckpt / "checkpoint_1.npz",
                direct_ckpt / "checkpoint_1.npz")
    direct_metrics = tmp_path / "direct_metrics.jsonl"
    monkeypatch.delenv("TPUMNIST_FAULT", raising=False)
    rc = spawn_local(2, _flags(direct_ckpt, direct_metrics,
                               extra=["--optimizer-sharding", "zero1"]),
                     timeout=240)
    assert rc == 0
    direct = [r for r in _rows(direct_metrics) if "train_loss" in r]
    assert [r["epoch"] for r in direct] == [2]
    for grown_row, direct_row in zip(resumed, direct):
        assert _strip_timing(grown_row) == _strip_timing(direct_row)


@pytest.mark.slow
def test_three_host_world_shrinks_to_two(tmp_path, monkeypatch):
    """Multi-survivor membership: a 3-host world loses host 2 at a
    host-side supervised phase (resume resolution — at 3+ ranks a kill
    must surface on the HOST side, because survivors of a mid-device-
    program death park in a timeout-less gloo collective: the
    residual-hazard row in DESIGN.md; the supervisor's settle deadline
    bounds that case but there is nothing to shrink around). Hosts 0
    and 1 both vote, agree the shrunk membership, and are rebuilt as a
    REAL 2-host world (rank renumbering, fresh coordinator) that
    trains to completion. Batch 48 divides 3, 2, and 1 — worlds chosen
    with divisible fallbacks, as the elastic docs prescribe."""
    ckpt, metrics = tmp_path / "ckpts", tmp_path / "metrics.jsonl"
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", _DEADLINE)
    monkeypatch.setenv("TPUMNIST_FAULT", "resume:2:kill")
    rc = supervise(3, _flags(ckpt, metrics, batch=48),
                   settle_timeout=60, generation_timeout=300)
    assert rc == 0
    rows = _rows(metrics)
    shrunk = _events(rows, "world_shrunk")
    assert len(shrunk) == 1
    assert shrunk[0]["old_members"] == [0, 1, 2]
    assert shrunk[0]["new_members"] == [0, 1]
    # The rebuilt 2-host world ran the whole job (the loss struck
    # before any epoch, so the shrunk world trains 0..2).
    assert [r["epoch"] for r in _epoch_rows_after_shrink(rows)] == [0, 1, 2]


@pytest.mark.slow
def test_second_kill_during_rebuild_shrinks_further(tmp_path, monkeypatch):
    """A second failure DURING the shrink: host 2 dies, then host 0 is
    killed inside its survivor-record window (``elastic_rebuild``
    fault). Host 0's vote never lands, so the supervisor counts it
    dead too and rebuilds with host 1 alone — a further shrink, a
    clean completion, never a hang."""
    ckpt, metrics = tmp_path / "ckpts", tmp_path / "metrics.jsonl"
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", _DEADLINE)
    monkeypatch.setenv("TPUMNIST_FAULT",
                       "resume:2:kill,elastic_rebuild:0:kill")
    rc = supervise(3, _flags(ckpt, metrics, batch=48),
                   settle_timeout=60, generation_timeout=300)
    assert rc == 0
    shrunk = _events(_rows(metrics), "world_shrunk")
    assert len(shrunk) == 1
    assert shrunk[0]["old_members"] == [0, 1, 2]
    assert shrunk[0]["new_members"] == [1]


@pytest.mark.slow
def test_stall_during_rebuild_killed_at_settle_deadline(
        tmp_path, monkeypatch):
    """The silent mid-rebuild failure: host 1 STALLS inside its
    survivor-record window. The supervisor's settle deadline kills the
    straggler (recordless -> dead) and rebuilds with host 0 alone;
    the whole scenario is bounded, not a hang."""
    ckpt, metrics = tmp_path / "ckpts", tmp_path / "metrics.jsonl"
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", _DEADLINE)
    monkeypatch.setenv("TPUMNIST_FAULT",
                       "resume:2:kill,elastic_rebuild:1:stall:600")
    t0 = time.monotonic()
    rc = supervise(3, _flags(ckpt, metrics, batch=48),
                   settle_timeout=25, generation_timeout=300)
    assert rc == 0
    assert time.monotonic() - t0 < 280
    shrunk = _events(_rows(metrics), "world_shrunk")
    assert len(shrunk) == 1
    assert shrunk[0]["new_members"] == [0]


@pytest.mark.slow
def test_replacement_join_keeps_world_at_min_world_floor(
        tmp_path, monkeypatch):
    """The --min-world x join interaction: a 2-host world with
    --min-world 2 loses host 1 — alone that is a floor exit (the twin
    below) — but host 7's join record is already pending when the
    rebuild plans, and admission runs BEFORE the floor check, so the
    supervisor rebuilds at [0, 7]: same size, different members, a
    world_grown event with the loss visible in the member lists.

    The kill targets rank 1 with skip 9, landing it in epoch 2's step
    loop (epochs 0-1 published): fault specs target RANKS, and the
    rebuilt same-size world HAS a rank 1 (host 7) — a smaller skip
    would re-kill the replacement when its own hit count caught up
    (the rank-renumbering caveat in the chaos docs). With skip 9 the
    rebuilt generation runs only epoch 2's four steps and the fault
    can never re-fire."""
    ckpt, metrics = tmp_path / "ckpts", tmp_path / "metrics.jsonl"
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", _DEADLINE)
    monkeypatch.setenv("TPUMNIST_FAULT", "train_step:1:kill:9")
    rc = supervise(2, _flags(ckpt, metrics,
                             extra=["--optimizer-sharding", "zero1"]),
                   min_world=2, rejoin=[(7, 0)],
                   settle_timeout=60, generation_timeout=240)
    assert rc == 0
    rows = _rows(metrics)
    grown = _events(rows, "world_grown")
    assert len(grown) == 1
    assert grown[0]["old_members"] == [0, 1]
    assert grown[0]["new_members"] == [0, 7]
    assert _events(rows, "world_shrunk") == []
    assert [r["epoch"] for r in _epoch_rows_after_grow(rows)] == [2]


@pytest.mark.slow
def test_min_world_floor_stops_shrinking(tmp_path, monkeypatch):
    """--min-world 2 on a 2-host world losing a host: the survivor is
    below the floor, so the supervisor exits with the distinct floor
    code instead of rebuilding a world the operator ruled out."""
    ckpt, metrics = tmp_path / "ckpts", tmp_path / "metrics.jsonl"
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", _DEADLINE)
    monkeypatch.setenv("TPUMNIST_FAULT", "train_epoch:1:kill:1")
    rc = supervise(2, _flags(ckpt, metrics), min_world=2,
                   settle_timeout=60, generation_timeout=240)
    assert rc == EXIT_FLOOR
    # No rebuilt generation ever ran: no world_shrunk event, and the
    # epoch-1 training never happened anywhere.
    assert _events(_rows(metrics), "world_shrunk") == []
