"""Fixture suite: the handler-discipline checker + the real handlers.

Pins the PR 10 ``/resize`` incident: a handler branch that returns
without writing a status line is a dropped connection to the client;
two replies on one path corrupt keep-alive framing.
"""

import os
import pathlib

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src, filename="snippet.py"):
    return analyze_snippet(src, checkers=["handler-discipline"],
                           filename=filename)


# -- firing ------------------------------------------------------------------


def test_fires_on_branch_that_never_replies():
    """The PR 10 /resize shape: an early return with no status line."""
    src = """
class Handler:
    def do_POST(self):
        if self.path == "/resize":
            if self.busy:
                return
            self.send_response(200)
            return
        self.send_error(404)
"""
    findings = _findings(src)
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == "Handler.do_POST"
    assert "dropped" in f.message and "PR 10" in f.message


def test_fires_on_double_reply_path():
    src = """
class Handler:
    def do_GET(self):
        self.send_response(200)
        if self.path == "/stats":
            self.send_response(200)
        self.wfile.write(b"{}")
"""
    findings = _findings(src)
    assert len(findings) == 1
    assert "more than one response" in findings[0].message


def test_fires_on_unbounded_body_read():
    src = """
class Handler:
    def do_POST(self):
        body = self.rfile.read()
        self.send_response(200)
"""
    findings = _findings(src)
    assert len(findings) == 1
    assert "blocks forever" in findings[0].message


def test_fires_when_one_except_arm_swallows_without_reply():
    """An exception handler that logs and falls off the end drops the
    connection exactly like an early return."""
    src = """
class Handler:
    def do_GET(self):
        try:
            payload = self.compute()
        except ValueError:
            return
        self.send_response(200)
"""
    findings = _findings(src)
    assert len(findings) == 1
    assert "dropped" in findings[0].message


# -- non-firing --------------------------------------------------------------


def test_clean_when_every_branch_replies_once():
    src = """
class Handler:
    def do_GET(self):
        if self.path == "/healthz":
            self.send_response(200)
            return
        self.send_error(404)
"""
    assert _findings(src) == []


def test_clean_when_reply_goes_through_a_resolvable_helper():
    """The index follows self._reply -> send_response, so helper-based
    handlers need no special-casing."""
    src = """
class Handler:
    def _reply(self, code, body):
        self.send_response(code)
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            self._reply(200, b"{}")
            return
        self._reply(404, b"")
"""
    assert _findings(src) == []


def test_clean_when_a_branch_raises():
    """A raise terminal is the server loop's problem, not a drop."""
    src = """
class Handler:
    def do_POST(self):
        if self.path not in self.routes:
            raise KeyError(self.path)
        self.send_response(200)
"""
    assert _findings(src) == []


def test_clean_on_length_bounded_body_read():
    src = """
class Handler:
    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        self.send_response(200)
"""
    assert _findings(src) == []


# -- the real handlers stay clean --------------------------------------------


_SERVER = pathlib.Path(_REPO) / "pytorch_distributed_mnist_tpu" / \
    "serve" / "server.py"
_ROUTER = pathlib.Path(_REPO) / "pytorch_distributed_mnist_tpu" / \
    "serve" / "router.py"


def test_real_server_handlers_are_clean():
    assert _findings(_SERVER.read_text(), filename="server.py") == []


def test_real_router_handlers_are_clean():
    assert _findings(_ROUTER.read_text(), filename="router.py") == []
