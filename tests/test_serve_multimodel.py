"""Multi-model serving (`--model-set`, ISSUE 15): N models from one
process over one chip budget — routing on the request's `model` field,
per-plane isolation (one model's hot reload touches nothing of the
other's), per-plane /stats blocks, and the loadgen `--expect-models`
smoke over real loopback HTTP."""

import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.server import build_parser, create_server
from pytorch_distributed_mnist_tpu.train.checkpoint import save_checkpoint
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.utils.profiling import compile_log

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _publish(ckpt_dir, model_name, epoch, seed):
    model = get_model(model_name, compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0)
    return state


def _args(model_set, **overrides):
    argv = [
        "--model-set", model_set, "--dtype", "f32",
        "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,8",
        "--max-wait-ms", "2", "--max-queue", "64",
        "--poll-interval", "0.1",
        # Split-plane boots: this suite pins no fused behavior, and the
        # fused AOT warm would re-pay its compile wall per boot (x replicas)
        # across the whole file -- tier-1 compile budget. The fused default
        # is pinned in test_serve_server.py / test_serve_fused.py.
        "--no-fuse",
    ]
    for k, v in overrides.items():
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        else:
            argv += [flag, str(v)]
    return build_parser().parse_args(argv)


class _Server:
    def __init__(self, args):
        self.httpd = create_server(args)
        host, port = self.httpd.server_address[:2]
        self.url = f"http://{host}:{port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.close()
        self.httpd.server_close()
        self.thread.join(10.0)

    def get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=30) as r:
            return json.loads(r.read())

    def post(self, path, payload):
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read())


@pytest.fixture()
def two_model_server(tmp_path):
    d1, d2 = tmp_path / "linear", tmp_path / "cnn"
    state_lin = _publish(d1, "linear", epoch=3, seed=1)
    state_cnn = _publish(d2, "cnn", epoch=7, seed=2)
    srv = _Server(_args(f"linear={d1},cnn={d2}"))
    try:
        yield srv, state_lin, state_cnn, d1, d2
    finally:
        srv.close()


def test_routes_on_model_field_with_correct_predictions(
        two_model_server):
    srv, state_lin, state_cnn, _, _ = two_model_server
    images, _ = synthetic_dataset(4, seed=3)
    payload = {"images": images.tolist()}
    norm = jnp.asarray(normalize_images(images))

    code, reply = srv.post("/predict", {**payload, "model": "linear"})
    assert code == 200 and reply["model"] == "linear"
    assert reply["model_epoch"] == 3
    model = get_model("linear", compute_dtype=jnp.float32)
    want = np.argmax(np.asarray(model.apply(
        state_lin.params, norm, train=False)), axis=-1)
    assert reply["predictions"] == [int(v) for v in want]

    code, reply = srv.post("/predict", {**payload, "model": "cnn"})
    assert code == 200 and reply["model"] == "cnn"
    assert reply["model_epoch"] == 7
    model = get_model("cnn", compute_dtype=jnp.float32)
    want = np.argmax(np.asarray(model.apply(
        state_cnn.params, norm, train=False)), axis=-1)
    assert reply["predictions"] == [int(v) for v in want]


def test_missing_and_unknown_model_are_400s(two_model_server):
    srv = two_model_server[0]
    images, _ = synthetic_dataset(1, seed=0)
    payload = {"images": images.tolist()}
    code, reply = srv.post("/predict", payload)
    assert code == 400
    assert "must name 'model'" in reply["error"]
    assert "linear" in reply["error"] and "cnn" in reply["error"]
    code, reply = srv.post("/predict", {**payload, "model": "vit"})
    assert code == 400 and "unknown model" in reply["error"]


def test_stats_carries_per_model_blocks_and_healthz_models(
        two_model_server):
    srv = two_model_server[0]
    images, _ = synthetic_dataset(1, seed=0)
    srv.post("/predict", {"images": images.tolist(), "model": "cnn"})
    stats = srv.get("/stats")
    assert stats["model_set"] == ["cnn", "linear"]
    models = stats["models"]
    assert sorted(models) == ["cnn", "linear"]
    for name, block in models.items():
        assert "latency_ms" in block and "window" in block
        assert block["buckets"] == [1, 8]
        # The per-plane compile block shows only that plane's programs
        # (names carry the model as the first segment after '@').
        for prog in block["compile"]["programs"]:
            assert prog.partition("@")[2].split(".")[0] == name
    assert models["cnn"]["requests"] == 1
    assert models["linear"]["requests"] == 0
    assert models["cnn"]["model_epoch"] == 7
    assert models["linear"]["model_epoch"] == 3
    health = srv.get("/healthz")
    assert health["models"] == {"cnn": 7, "linear": 3}
    # The weighted-fair gate is live (default weights 1.0 each).
    assert stats["fair_dispatch"]["weights"] == {
        "cnn": 1.0, "linear": 1.0}
    assert stats["fair_dispatch"]["grants"]["cnn"] >= 1


def test_one_models_reload_is_invisible_to_the_other(
        two_model_server):
    """Isolation: publishing a new checkpoint for linear swaps ONLY the
    linear plane — cnn keeps its epoch and, critically, no serve
    program anywhere recompiles (a reload is an atomic param swap on
    every plane it touches, and it touches one)."""
    srv, _, _, d1, _ = two_model_server
    images, _ = synthetic_dataset(2, seed=4)
    payload = {"images": images.tolist()}
    compiles_before = {
        name: rec["backend_compiles"]
        for name, rec in compile_log.stats()["programs"].items()
        if name.startswith("serve_forward_")}

    state_new = _publish(d1, "linear", epoch=9, seed=9)
    lin_plane = srv.httpd.ctx.planes["linear"]
    cnn_plane = srv.httpd.ctx.planes["cnn"]
    # The background poll thread (0.1s interval) may legitimately win
    # the race to this publish; poll_once is lock-serialized against it,
    # so EITHER poll installs — exactly once (the reloads==1 pin below).
    installed = lin_plane.watcher.poll_once()
    assert installed or lin_plane.engine.params_epoch == 9
    assert lin_plane.engine.params_epoch == 9
    assert cnn_plane.engine.params_epoch == 7
    # cnn's own watcher sees nothing new.
    assert cnn_plane.watcher.poll_once() is False

    code, reply = srv.post("/predict", {**payload, "model": "linear"})
    assert code == 200 and reply["model_epoch"] == 9
    model = get_model("linear", compute_dtype=jnp.float32)
    want = np.argmax(np.asarray(model.apply(
        state_new.params, jnp.asarray(normalize_images(images)),
        train=False)), axis=-1)
    assert reply["predictions"] == [int(v) for v in want]
    code, reply = srv.post("/predict", {**payload, "model": "cnn"})
    assert code == 200 and reply["model_epoch"] == 7

    compiles_after = {
        name: rec["backend_compiles"]
        for name, rec in compile_log.stats()["programs"].items()
        if name.startswith("serve_forward_")}
    assert compiles_after == compiles_before
    stats = srv.get("/stats")
    assert stats["models"]["linear"]["reloads"] == 1
    assert stats["models"]["cnn"]["reloads"] == 0


def test_loadgen_expect_models_smoke_over_loopback(two_model_server):
    srv = two_model_server[0]
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--smoke", "--url", srv.url, "--requests", "40",
         "--concurrency", "4", "--model", "cnn",
         "--expect-models", "2"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["smoke_ok"] is True
    assert report["models_served"] == ["cnn", "linear"]
    assert report["model_set"] == ["cnn", "linear"]
    # --expect-models has teeth: the wrong count fails the smoke.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--smoke", "--url", srv.url, "--requests", "10",
         "--concurrency", "2", "--model", "cnn",
         "--expect-models", "3"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1


def test_model_weights_flag_validation(tmp_path):
    d1 = tmp_path / "a"
    d1.mkdir()
    with pytest.raises(SystemExit, match="requires --model-set"):
        create_server(build_parser().parse_args(
            ["--checkpoint-dir", str(d1), "--model", "linear",
             "--model-weights", "linear=2"]))
    with pytest.raises(SystemExit, match="twice"):
        create_server(build_parser().parse_args(
            ["--model-set", f"linear={d1},linear={d1}"]))
    with pytest.raises(SystemExit, match="unknown model"):
        create_server(build_parser().parse_args(
            ["--model-set", f"zzz={d1}"]))
    with pytest.raises(SystemExit, match="MODEL=CHECKPOINT_DIR"):
        create_server(build_parser().parse_args(
            ["--model-set", "linear"]))


def test_weighted_fair_dispatch_under_dual_backlog(tmp_path):
    """Both models hammered concurrently with 3:1 weights: the gate's
    granted-rows split lands near the weights (tolerant: fairness binds
    only while both planes genuinely contend)."""
    d1, d2 = tmp_path / "lin", tmp_path / "cnn"
    _publish(d1, "linear", epoch=0, seed=1)
    _publish(d2, "cnn", epoch=0, seed=2)
    srv = _Server(_args(f"linear={d1},cnn={d2}",
                        model_weights="linear=3,cnn=1"))
    try:
        images, _ = synthetic_dataset(1, seed=0)
        payload = {"images": images.tolist()}
        errors = []

        def hammer(model, n):
            for _ in range(n):
                code, _ = srv.post("/predict",
                                   {**payload, "model": model})
                if code != 200:
                    errors.append((model, code))

        threads = [threading.Thread(target=hammer, args=(m, 60),
                                    daemon=True)
                   for m in ("linear", "cnn") for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        assert not errors, errors[:5]
        stats = srv.get("/stats")
        fair = stats["fair_dispatch"]
        assert fair["weights"] == {"linear": 3.0, "cnn": 1.0}
        assert fair["granted_rows"]["linear"] > 0
        assert fair["granted_rows"]["cnn"] > 0
        assert stats["models"]["linear"]["requests"] == 120
        assert stats["models"]["cnn"]["requests"] == 120
    finally:
        srv.close()
