"""The shadow-traffic accuracy canary (ISSUE 14): unit state machine on
stub planes, and the loopback-server acceptance runs — a quantized
publish PROMOTES after clean shadow traffic, and an injected-
disagreement publish AUTO-ROLLS-BACK, both under live loadgen with zero
dropped requests."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.canary import (
    CANARY_FAULT_ENV,
    PRIMARY,
    ROLLED_BACK,
    SHADOW,
    ShadowCanary,
)
from pytorch_distributed_mnist_tpu.serve.server import (
    build_parser,
    create_server,
)
from pytorch_distributed_mnist_tpu.train.checkpoint import save_checkpoint
from pytorch_distributed_mnist_tpu.train.state import create_train_state

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- stub planes -------------------------------------------------------------


class _Plane:
    """An engine/pool stand-in: deterministic logits from a row
    transform, the full canary-facing surface, no jax."""

    def __init__(self, logits_fn, epoch=0, fail_dispatch=False,
                 fail_complete=False):
        self.logits_fn = logits_fn
        self.epoch = epoch
        self.fail_dispatch = fail_dispatch
        self.fail_complete = fail_complete
        self.buckets = (1, 8)
        self.max_batch = 8
        self.dispatches = 0
        self.swaps = []
        self.warmed = False

    @property
    def params_epoch(self):
        return self.epoch

    def preprocess(self, images):
        return np.asarray(images, np.float32)

    def warmup(self):
        self.warmed = True

    def dispatch(self, images):
        if self.fail_dispatch:
            raise RuntimeError("injected dispatch death")
        self.dispatches += 1
        return np.asarray(images, np.float32)

    def complete(self, handle):
        if self.fail_complete:
            raise RuntimeError("injected completion death")
        return self.logits_fn(handle), self.epoch

    def swap_params(self, params, epoch=None, path=None):
        self.swaps.append(epoch)
        self.epoch = epoch
        return 1


def _base_logits(x):
    n = x.shape[0]
    out = np.zeros((n, 10), np.float32)
    out[np.arange(n), np.arange(n) % 10] = 5.0
    return out


def _agreeing(x):
    return _base_logits(x) + 0.01  # same argmax, tiny logit delta


def _disagreeing(x):
    out = _base_logits(x)
    return -out  # argmax moves off the spiked class for every row


def _batch(n=4):
    return np.zeros((n, 4), np.float32)


# -- unit: sampling + state machine ------------------------------------------


def test_fraction_sampler_is_exact():
    canary = ShadowCanary(_Plane(_base_logits), _Plane(_agreeing), "bf16",
                          fraction=0.25, promote_after=10_000)
    for handle in (canary.dispatch(_batch()) for _ in range(16)):
        canary.complete(handle)
    snap = canary.snapshot()
    assert snap["shadow_batches"] == 4  # exactly a quarter
    assert canary.candidate.dispatches == 4
    assert snap["state"] == SHADOW


def test_promotes_after_clean_rows_and_routes_to_candidate():
    base, cand = _Plane(_base_logits), _Plane(_agreeing)
    canary = ShadowCanary(base, cand, "bf16", fraction=1.0,
                          promote_after=12, budget=0.1)
    while canary.state == SHADOW:
        canary.complete(canary.dispatch(_batch(4)))
    snap = canary.snapshot()
    assert snap["state"] == PRIMARY and snap["promotions"] == 1
    assert snap["compared_rows"] >= 12 and snap["disagreed_rows"] == 0
    assert snap["logit_delta"]["max"] == pytest.approx(0.01, abs=1e-4)
    # Promoted: replies now COME FROM the candidate (its logits differ
    # by the 0.01 offset), and no further shadow dispatches happen.
    base_dispatches = base.dispatches
    logits, _ = canary.complete(canary.dispatch(_batch(2)))
    assert logits[0, 0] == pytest.approx(5.01)
    assert base.dispatches == base_dispatches


def test_rolls_back_when_disagreement_blows_the_budget():
    canary = ShadowCanary(_Plane(_base_logits), _Plane(_disagreeing),
                          "int8", fraction=1.0, promote_after=100,
                          budget=0.05)  # allowance: 5 rows
    for _ in range(3):  # 12 rows, all disagreeing
        canary.complete(canary.dispatch(_batch(4)))
        if canary.state == ROLLED_BACK:
            break
    snap = canary.snapshot()
    assert snap["state"] == ROLLED_BACK and snap["rollbacks"] == 1
    assert snap["disagreed_rows"] > 5
    # Permanent for this publish: no further shadowing, baseline answers.
    cand_dispatches = canary.candidate.dispatches
    logits, _ = canary.complete(canary.dispatch(_batch(2)))
    assert logits[0, 0] == pytest.approx(5.0)  # baseline's
    assert canary.candidate.dispatches == cand_dispatches
    assert canary.snapshot()["shadow_batches"] == snap["shadow_batches"]


def test_shadow_dispatch_errors_count_and_never_fail_the_reply():
    base = _Plane(_base_logits)
    cand = _Plane(_agreeing, fail_dispatch=True)
    canary = ShadowCanary(base, cand, "int8w", fraction=1.0,
                          promote_after=100, budget=0.0)
    logits, epoch = canary.complete(canary.dispatch(_batch(4)))
    assert logits.shape == (4, 10)  # the reply arrived regardless
    snap = canary.snapshot()
    assert snap["shadow_errors"] == 1
    assert snap["state"] == ROLLED_BACK  # zero budget: first error rolls


def test_shadow_completion_errors_count_toward_budget():
    cand = _Plane(_agreeing, fail_complete=True)
    canary = ShadowCanary(_Plane(_base_logits), cand, "int8w",
                          fraction=1.0, promote_after=100, budget=0.0)
    logits, _ = canary.complete(canary.dispatch(_batch(4)))
    assert logits.shape == (4, 10)
    assert canary.snapshot()["state"] == ROLLED_BACK


def test_epoch_skew_skips_the_comparison():
    cand = _Plane(_agreeing, epoch=1)  # baseline serves epoch 0
    canary = ShadowCanary(_Plane(_base_logits, epoch=0), cand, "bf16",
                          fraction=1.0, promote_after=4, budget=0.0)
    canary.complete(canary.dispatch(_batch(4)))
    snap = canary.snapshot()
    assert snap["skewed_comparisons"] == 1
    assert snap["compared_rows"] == 0  # judged nothing
    assert snap["state"] == SHADOW


def test_swap_params_resets_the_cycle_per_publish():
    base, cand = _Plane(_base_logits), _Plane(_disagreeing)
    canary = ShadowCanary(base, cand, "int8", fraction=1.0,
                          promote_after=100, budget=0.0)
    canary.complete(canary.dispatch(_batch(4)))
    assert canary.state == ROLLED_BACK
    installed = canary.swap_params({"w": 1}, epoch=7, path="ckpt_7")
    assert installed == 1
    assert base.swaps == [7] and cand.swaps == [7]  # fanned to BOTH
    snap = canary.snapshot()
    assert snap["state"] == SHADOW  # the new publish re-earns promotion
    assert snap["publishes"] == 1 and snap["rollbacks"] == 1
    assert snap["compared_rows"] == 0 and snap["disagreed_rows"] == 0


def test_stale_publish_does_not_reset_a_promoted_canary():
    """A checkpoint both planes refuse as STALE (the engines'
    swap-ordering rule — e.g. an old file copied back, or a stale NFS
    readdir view) must not demote a promoted candidate or count as a
    publish: nothing installed, so nothing re-earns."""

    class _StalePlane(_Plane):
        def swap_params(self, params, epoch=None, path=None):
            self.swaps.append(epoch)
            return 0  # refused as stale

    base, cand = _StalePlane(_base_logits), _StalePlane(_agreeing)
    canary = ShadowCanary(base, cand, "bf16", fraction=1.0,
                          promote_after=4, budget=0.1)
    canary.complete(canary.dispatch(_batch(4)))  # promotes
    assert canary.state == PRIMARY
    assert canary.swap_params({"w": 1}, epoch=0) == 0
    snap = canary.snapshot()
    assert snap["state"] == PRIMARY  # still serving the quantized plane
    assert snap["publishes"] == 0  # the stale file never served
    assert base.swaps == [0] and cand.swaps == [0]  # it WAS offered


def test_injected_fault_env_forces_disagreement(monkeypatch):
    monkeypatch.setenv(CANARY_FAULT_ENV, "disagree")
    canary = ShadowCanary(_Plane(_base_logits), _Plane(_agreeing), "bf16",
                          fraction=1.0, promote_after=100, budget=0.0)
    canary.complete(canary.dispatch(_batch(4)))
    assert canary.state == ROLLED_BACK  # despite identical argmax


def test_constructor_rejections():
    planes = (_Plane(_base_logits), _Plane(_agreeing))
    with pytest.raises(ValueError, match="fraction"):
        ShadowCanary(*planes, "bf16", fraction=0.0)
    with pytest.raises(ValueError, match="fraction"):
        ShadowCanary(*planes, "bf16", fraction=1.5)
    with pytest.raises(ValueError, match="promote_after"):
        ShadowCanary(*planes, "bf16", promote_after=0)
    with pytest.raises(ValueError, match="budget"):
        ShadowCanary(*planes, "bf16", budget=-0.1)


def test_fault_env_name_matches_chaos_cli():
    """tools/chaos.py spells the env var out to stay jax-import-free;
    the literals must never drift."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos", os.path.join(REPO, "tools", "chaos.py"))
    chaos = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos)
    assert chaos.CANARY_FAULT_ENV == CANARY_FAULT_ENV


def test_serve_canary_events_ride_the_sink(tmp_path):
    """Promote/rollback/reset land as serve_canary JSONL lines in the
    shared metrics stream (the PR 3 sink)."""
    from pytorch_distributed_mnist_tpu.utils.profiling import (
        JsonlSink,
        ServeLog,
    )

    path = tmp_path / "metrics.jsonl"
    serve_log = ServeLog()
    serve_log.set_sink(JsonlSink(str(path)), source="serve")
    canary = ShadowCanary(_Plane(_base_logits), _Plane(_agreeing), "bf16",
                          fraction=1.0, promote_after=4, budget=0.1,
                          serve_log=serve_log)
    canary.complete(canary.dispatch(_batch(4)))  # promotes
    canary.swap_params({"w": 1}, epoch=1)  # resets
    lines = [json.loads(line) for line in
             path.read_text().strip().splitlines()]
    kinds = [(r["kind"], r["event"]) for r in lines]
    assert ("serve_canary", "promoted") in kinds
    assert ("serve_canary", "reset") in kinds
    assert all(r["precision"] == "bf16" for r in lines)


# -- loopback server acceptance ----------------------------------------------


def _publish(ckpt_dir, epoch, seed):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0)
    return state


def _serve_args(ckpt_dir, **overrides):
    argv = [
        "--checkpoint-dir", str(ckpt_dir),
        "--model", "linear", "--dtype", "f32",
        "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,8,32",
        "--max-wait-ms", "2", "--max-queue", "128",
        "--poll-interval", "0.1",
        # Split-plane boots: this suite pins no fused behavior, and the
        # fused AOT warm would re-pay its compile wall per boot (x replicas)
        # across the whole file -- tier-1 compile budget. The fused default
        # is pinned in test_serve_server.py / test_serve_fused.py.
        "--no-fuse",
    ]
    for k, v in overrides.items():
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        else:
            argv += [flag, str(v)]
    return build_parser().parse_args(argv)


class _Server:
    def __init__(self, args):
        self.httpd = create_server(args)
        host, port = self.httpd.server_address[:2]
        self.url = f"http://{host}:{port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.close()
        self.httpd.server_close()
        self.thread.join(10.0)

    def get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=30) as r:
            return json.loads(r.read())

    def post(self, path, payload):
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())


def _loadgen_smoke(url, requests, extra=()):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--smoke", "--url", url, "--requests", str(requests),
         "--concurrency", "8", *extra],
        capture_output=True, text=True, timeout=300)
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    return proc.returncode, report


def test_canary_promotes_under_live_loadgen(tmp_path):
    """Acceptance (promote leg): a bf16 publish shadows clean traffic,
    promotes to primary, and loadgen answers 200 for EVERY request
    throughout — with /stats carrying serve_precision and the canary
    block, and the loadgen report carrying both."""
    ckpt = tmp_path / "ckpt"
    state = _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_precision="bf16",
                              canary_fraction=1.0,
                              canary_promote_after=40,
                              canary_budget=0.1))
    try:
        rc, report = _loadgen_smoke(
            srv.url, 120, extra=("--expect-precision", "bf16"))
        assert rc == 0, report
        assert report["ok"] == 120 and report["transport_errors"] == 0
        assert report["serve_precision"] == "bf16"
        assert report["canary"]["precision"] == "bf16"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            canary = srv.get("/stats")["canary"]
            if canary["state"] == PRIMARY:
                break
            srv.post("/predict",
                     {"images": synthetic_dataset(1, seed=0)[0].tolist()})
        assert canary["state"] == PRIMARY
        assert canary["promotions"] == 1 and canary["rollbacks"] == 0
        assert canary["compared_rows"] >= 40
        # Promoted replies still match the direct forward pass (bf16
        # weight rounding on this linear model stays argmax-stable).
        images, _ = synthetic_dataset(4, seed=1)
        reply = srv.post("/predict", {"images": images.tolist()})
        model = get_model("linear", compute_dtype=jnp.float32)
        want = np.argmax(np.asarray(model.apply(
            state.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)
        assert reply["predictions"] == [int(v) for v in want]
        assert reply["model_epoch"] == 0
    finally:
        srv.close()


def test_canary_rolls_back_under_live_loadgen(tmp_path, monkeypatch):
    """Acceptance (rollback leg): an injected-disagreement publish rolls
    back under live loadgen with ZERO dropped requests — the baseline
    answers everything — and a NEW publish resets the cycle to shadow."""
    monkeypatch.setenv(CANARY_FAULT_ENV, "disagree")
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_precision="int8w",
                              canary_fraction=1.0,
                              canary_promote_after=100000,
                              canary_budget=0.0))
    try:
        rc, report = _loadgen_smoke(
            srv.url, 120, extra=("--expect-precision", "int8w"))
        assert rc == 0, report  # every request answered 200, zero drops
        assert report["ok"] == 120 and report["transport_errors"] == 0
        canary = srv.get("/stats")["canary"]
        assert canary["state"] == ROLLED_BACK
        assert canary["rollbacks"] == 1 and canary["promotions"] == 0
        assert canary["disagreed_rows"] > 0
        stats = srv.get("/stats")
        assert stats["serve_precision"] == "int8w"
        # Rollback is permanent for THIS publish; the next one re-enters
        # shadow through the watcher's one reload path.
        shadow_before = canary["shadow_batches"]
        rc, _ = _loadgen_smoke(srv.url, 40)
        assert rc == 0
        assert srv.get("/stats")["canary"]["shadow_batches"] \
            == shadow_before
        _publish(ckpt, epoch=1, seed=11)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            canary = srv.get("/stats")["canary"]
            if canary["publishes"] == 1:
                break
            time.sleep(0.2)
        assert canary["publishes"] == 1
        assert canary["state"] in (SHADOW, ROLLED_BACK)  # fault still on
        assert srv.get("/healthz")["model_epoch"] == 1
    finally:
        srv.close()


def test_canary_flag_rejections_and_resize_refusal(tmp_path):
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    with pytest.raises(SystemExit, match="quantized --serve-precision"):
        create_server(_serve_args(ckpt, canary_fraction=0.5))
    with pytest.raises(SystemExit, match="0, 1"):
        create_server(_serve_args(ckpt, serve_precision="bf16",
                                  canary_fraction=1.5))
    srv = _Server(_serve_args(ckpt, serve_precision="bf16",
                              canary_fraction=0.5, serve_devices=2))
    try:
        # /resize is refused while a canary is active: the two planes'
        # topology must not diverge under the comparison.
        req = urllib.request.Request(
            srv.url + "/resize",
            data=json.dumps({"serve_devices": 1}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=30)
            raised = None
        except urllib.error.HTTPError as exc:
            raised = exc.code
            body = json.loads(exc.read())
        assert raised == 400 and "canary" in body["error"]
    finally:
        srv.close()


def test_direct_quantized_serving_without_canary(tmp_path):
    """--serve-precision without --canary-fraction serves the quantized
    plane directly (the trusted path the bench sweeps), with
    serve_precision in /stats and NO canary block."""
    ckpt = tmp_path / "ckpt"
    state = _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_precision="bf16"))
    try:
        stats = srv.get("/stats")
        assert stats["serve_precision"] == "bf16"
        assert "canary" not in stats
        images, _ = synthetic_dataset(3, seed=2)
        reply = srv.post("/predict", {"images": images.tolist()})
        model = get_model("linear", compute_dtype=jnp.float32)
        want = np.argmax(np.asarray(model.apply(
            state.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)
        assert reply["predictions"] == [int(v) for v in want]
    finally:
        srv.close()
