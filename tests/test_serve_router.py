"""Unit suite for the router's pure parts (serve/router.py): no
sockets, no threads started — the state machines, the hash ring, the
retry-safety classifier, the rolling-reload sequencer, the fleet canary
verdicts, and the merged-quantile math, each pinned deterministically.
The live fleet behavior (real backends, real SIGKILL) lives in
tests/test_serve_router_fleet.py and tools/chaos.py --fleet."""

import http.client
import random
import urllib.error

import pytest

from pytorch_distributed_mnist_tpu.serve.router import (
    HEALTHY,
    PRIMARY,
    PROBATION,
    QUARANTINED,
    ROLLED_BACK,
    SHADOW,
    Backend,
    BackendHealth,
    Fleet,
    FleetAutoscaler,
    FleetCanary,
    HashRing,
    RollingReload,
    TransportError,
    classify_failure,
    epoch_of_checkpoint,
    merge_windows,
    pick_backend,
    republish_with_epoch,
    retry_safe,
)

pytestmark = pytest.mark.fleet


# ---------------------------------------------------------------------------
# Consistent-hash ring
# ---------------------------------------------------------------------------


def test_hash_ring_affinity_stable_under_node_removal():
    """Removing one of N nodes re-homes only ~1/N of the keys, and every
    key whose owner SURVIVED keeps it — the property that makes a
    backend death invisible to the other backends' warm clients."""
    nodes = ["10.0.0.1:8000", "10.0.0.2:8000", "10.0.0.3:8000"]
    ring = HashRing(nodes)
    keys = [f"client-{i}" for i in range(3000)]
    before = {k: ring.node_for(k) for k in keys}
    ring.remove(nodes[1])
    after = {k: ring.node_for(k) for k in keys}

    moved = sum(1 for k in keys if before[k] != after[k])
    # Every moved key belonged to the removed node; survivors' keys
    # never move (the defining consistent-hashing property).
    for k in keys:
        if before[k] != nodes[1]:
            assert after[k] == before[k], k
        else:
            assert after[k] != nodes[1]
    assert moved == sum(1 for k in keys if before[k] == nodes[1])
    # ~1/3 of keys moved (64 virtual points keep the spread tight).
    assert 0.15 < moved / len(keys) < 0.55

    # Re-adding restores the original assignment exactly (hashing is
    # deterministic, not history-dependent).
    ring.add(nodes[1])
    assert {k: ring.node_for(k) for k in keys} == before


def test_hash_ring_basics():
    ring = HashRing(replicas=8)
    assert ring.node_for("anyone") is None
    ring.add("a:1")
    assert len(ring) == 1 and "a:1" in ring
    assert ring.node_for("x") == "a:1"
    ring.add("a:1")  # idempotent
    assert len(ring) == 1
    ring.remove("a:1")
    assert len(ring) == 0 and ring.node_for("x") is None
    with pytest.raises(ValueError, match="replicas"):
        HashRing(replicas=0)


# ---------------------------------------------------------------------------
# Dispatch decision
# ---------------------------------------------------------------------------


def _backend(name, inflight=None, total=0):
    b = Backend(name)
    b.inflight = dict(inflight or {})
    b.total_inflight = total
    return b


def test_pick_backend_least_loaded_tie_breaks():
    """Order of the keys: per-class in-flight, then total in-flight,
    then lexicographic name — fully deterministic."""
    a = _backend("h:1", {"interactive": 2}, total=2)
    b = _backend("h:2", {"interactive": 1}, total=5)
    c = _backend("h:3", {"interactive": 1}, total=3)
    # Fewest per-class wins even with more total elsewhere.
    assert pick_backend([a, b, c], klass="interactive") is c
    # Tie on per-class -> fewest total.
    c.total_inflight = 5
    assert pick_backend([a, b, c], klass="interactive") is b
    # Full tie -> lexicographic name.
    b.inflight = {"interactive": 2}
    c.inflight = {"interactive": 2}
    b.total_inflight = c.total_inflight = 2
    assert pick_backend([a, b, c], klass="interactive") is a
    # No candidates -> None (the caller's fleet 503).
    assert pick_backend([], klass="interactive") is None


def test_pick_backend_rotates_when_idle():
    """In-flight all zero (fast backends, open-loop arrivals): the
    requests-served key rotates dispatch instead of pinning the whole
    stream to one lexicographic winner."""
    fleet = Fleet()
    for n in ("127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"):
        fleet.add(n)
    served = []
    for _ in range(9):
        b = fleet.acquire()
        served.append(b.name)
        fleet.release(b)  # completes before the next arrival
    assert sorted(served.count(n) for n in set(served)) == [3, 3, 3]


def test_pick_backend_affinity_beats_load():
    """A client's ring choice wins while that backend is a candidate;
    when it is not (quarantined/excluded), least-loaded takes over."""
    names = ["h:1", "h:2", "h:3"]
    backends = {n: _backend(n) for n in names}
    ring = HashRing(names)
    client = "sticky-client"
    home = ring.node_for(client)
    backends[home].total_inflight = 99  # affinity is not load-based
    backends[home].inflight = {"interactive": 99}
    chosen = pick_backend(list(backends.values()), klass="interactive",
                          client_id=client, ring=ring)
    assert chosen.name == home
    # Home gone from the candidates -> least-loaded among the rest.
    rest = [b for n, b in backends.items() if n != home]
    fallback = pick_backend(rest, klass="interactive",
                            client_id=client, ring=ring)
    assert fallback is min(rest, key=lambda b: b.name)


# ---------------------------------------------------------------------------
# Retry-safety classifier
# ---------------------------------------------------------------------------


def test_classify_failure_buckets():
    assert classify_failure(ConnectionRefusedError()) == "refused"
    assert classify_failure(ConnectionResetError()) == "reset"
    assert classify_failure(BrokenPipeError()) == "reset"
    assert classify_failure(http.client.RemoteDisconnected("")) == "reset"
    assert classify_failure(TimeoutError()) == "timeout"
    assert classify_failure(OSError("no route")) == "transport"
    assert classify_failure(ValueError("junk")) == "other"
    # URLError unwraps to its reason.
    assert classify_failure(
        urllib.error.URLError(ConnectionRefusedError())) == "refused"
    # TransportError unwraps to the underlying exception.
    assert classify_failure(
        TransportError(ConnectionResetError(), False)) == "reset"


def test_retry_safe_only_proves_non_execution():
    """Refused/reset-before-body retry (the backend provably never ran
    the request); timeout, mid-body reset, and HTTP replies do NOT —
    re-dispatching those could double-run a mutation-free but
    accounting-visible request."""
    assert retry_safe(ConnectionRefusedError())
    assert retry_safe(ConnectionResetError())
    assert retry_safe(http.client.RemoteDisconnected(""))
    assert retry_safe(TransportError(ConnectionRefusedError(), False))
    # The same reset AFTER response bytes arrived: the backend answered.
    assert not retry_safe(ConnectionResetError(), body_started=True)
    assert not retry_safe(TransportError(ConnectionResetError(), True))
    # Ambiguous or post-execution failures never retry.
    assert not retry_safe(TimeoutError())
    assert not retry_safe(OSError("no route"))
    assert not retry_safe(
        urllib.error.HTTPError("u", 500, "boom", {}, None))


# ---------------------------------------------------------------------------
# Quarantine / probation state machine
# ---------------------------------------------------------------------------


def test_health_quarantine_and_probation_readmission():
    h = BackendHealth(quarantine_after=3, probation_successes=2)
    assert h.state == HEALTHY and h.routable
    assert h.note_failure() is None
    assert h.note_failure() is None
    assert h.note_failure() == QUARANTINED
    assert h.state == QUARANTINED and not h.routable
    assert h.quarantines == 1
    # Further failures while quarantined are a no-op (no double count).
    assert h.note_failure() is None and h.quarantines == 1
    # First success -> probation (routable again, but on a short leash).
    assert h.note_success() == PROBATION
    assert h.routable
    # The readmission streak.
    assert h.note_success() is None  # streak 1 of 2
    assert h.note_success() == HEALTHY
    assert h.readmissions == 1


def test_health_probation_one_strike():
    h = BackendHealth(quarantine_after=3, probation_successes=3)
    for _ in range(3):
        h.note_failure()
    h.note_success()
    assert h.state == PROBATION
    # One failure on probation re-quarantines immediately — no grace of
    # quarantine_after for a backend that just proved flaky.
    assert h.note_failure() == QUARANTINED
    assert h.quarantines == 2


def test_health_success_resets_failure_count():
    h = BackendHealth(quarantine_after=3)
    h.note_failure()
    h.note_failure()
    h.note_success()  # blip over
    h.note_failure()
    h.note_failure()
    assert h.state == HEALTHY  # 2 consecutive, threshold is 3
    assert h.note_failure() == QUARANTINED
    with pytest.raises(ValueError, match="quarantine_after"):
        BackendHealth(quarantine_after=0)


def test_fleet_quarantine_removes_from_ring_and_acquire():
    fleet = Fleet(quarantine_after=2)
    for n in ("127.0.0.1:1", "127.0.0.1:2"):
        fleet.add(n)
    fleet.note_failure("127.0.0.1:1", "refused")
    fleet.note_failure("127.0.0.1:1", "refused")
    assert fleet.get("127.0.0.1:1").health.state == QUARANTINED
    assert fleet.n_routable() == 1
    # Acquire never lands on a quarantined backend — even for a client
    # whose ring point used to live there.
    for i in range(50):
        b = fleet.acquire(client_id=f"c{i}")
        assert b.name == "127.0.0.1:2"
        fleet.release(b)
    # Heal: success -> probation -> routable again.
    fleet.note_success("127.0.0.1:1", {"model_epoch": 3})
    assert fleet.get("127.0.0.1:1").health.state == PROBATION
    assert fleet.n_routable() == 2
    assert fleet.get("127.0.0.1:1").epoch == 3


def test_fleet_acquire_reserves_inflight_and_excludes():
    fleet = Fleet()
    fleet.add("127.0.0.1:1")
    fleet.add("127.0.0.1:2")
    a = fleet.acquire(klass="interactive")
    assert a.total_inflight == 1
    # The reservation is visible to the next acquire: it picks the
    # other backend (least-loaded saw the in-flight slot).
    b = fleet.acquire(klass="interactive")
    assert b.name != a.name
    # A retry excludes the failed backend even when it is least-loaded.
    fleet.release(a, "interactive")
    c = fleet.acquire(klass="interactive", exclude=(b.name,))
    assert c.name == a.name
    # Draining removes from rotation without touching health.
    fleet.release(b, "interactive")
    fleet.release(c, "interactive")
    fleet.set_draining(a.name, True)
    assert fleet.acquire().name == b.name
    assert fleet.get(a.name).health.state == HEALTHY
    fleet.set_draining(a.name, False)
    assert fleet.n_routable() == 2


# ---------------------------------------------------------------------------
# Rolling-reload sequencer
# ---------------------------------------------------------------------------


class _ScriptedOps:
    """Fake rollout ops recording the exact call sequence."""

    def __init__(self, target_epoch, fail_publish_on=None,
                 active_counts=None):
        self.calls = []
        self.target = target_epoch
        self.fail_publish_on = fail_publish_on
        self.epochs = {}
        self.active_counts = dict(active_counts or {})

    def drain(self, name):
        self.calls.append(("drain", name))

    def active_requests(self, name):
        self.calls.append(("active", name))
        n = self.active_counts.get(name, 0)
        if n > 0:
            self.active_counts[name] = n - 1
        return n

    def publish(self, name):
        self.calls.append(("publish", name))
        if name == self.fail_publish_on:
            raise OSError(f"disk full on {name}")
        self.epochs[name] = self.target

    def epoch(self, name):
        self.calls.append(("epoch", name))
        return self.epochs.get(name)

    def undrain(self, name):
        self.calls.append(("undrain", name))


def test_rolling_reload_strict_ordering():
    """One backend at a time, each fully through
    drain -> wait-zero -> publish -> verify -> undrain before the next
    is touched; in-flight requests are actually waited out."""
    ops = _ScriptedOps(target_epoch=7, active_counts={"b2": 2})
    rr = RollingReload(ops, sleep=lambda s: None,
                       clock=_FakeClock().tick)
    out = rr.run(["b1", "b2", "b3"], target_epoch=7)
    assert out == {"ok": True, "updated": ["b1", "b2", "b3"],
                   "target_epoch": 7}
    # Collapse the active-poll repeats; the shape must be the strict
    # per-backend sequence with zero interleaving.
    shape = [c for i, c in enumerate(ops.calls)
             if not (c[0] == "active" and i and ops.calls[i - 1] == c)]
    assert shape == [
        ("drain", "b1"), ("active", "b1"), ("publish", "b1"),
        ("epoch", "b1"), ("undrain", "b1"),
        ("drain", "b2"), ("active", "b2"), ("publish", "b2"),
        ("epoch", "b2"), ("undrain", "b2"),
        ("drain", "b3"), ("active", "b3"), ("publish", "b3"),
        ("epoch", "b3"), ("undrain", "b3"),
    ]
    # b2's two in-flight requests forced extra active polls.
    assert sum(1 for c in ops.calls if c == ("active", "b2")) == 3


def test_rolling_reload_failure_stops_and_undrains_victim():
    """A publish failure undrains the victim and STOPS: backends not
    yet touched keep serving the old epoch (the point of rolling)."""
    ops = _ScriptedOps(target_epoch=7, fail_publish_on="b2")
    rr = RollingReload(ops, sleep=lambda s: None,
                       clock=_FakeClock().tick)
    out = rr.run(["b1", "b2", "b3"], target_epoch=7)
    assert out["ok"] is False and out["failed"] == "b2"
    assert out["updated"] == ["b1"]
    assert "disk full" in out["error"]
    assert ("undrain", "b2") in ops.calls  # victim rejoined
    assert not any(name == "b3" for _, name in ops.calls)  # untouched


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def tick(self):
        self.t += 0.01
        return self.t


def test_rolling_reload_drain_timeout():
    class _Stuck(_ScriptedOps):
        def active_requests(self, name):
            return 1  # never drains

    ops = _Stuck(target_epoch=1)
    rr = RollingReload(ops, drain_timeout_s=0.5, sleep=lambda s: None,
                       clock=_FakeClock().tick)
    out = rr.run(["b1"], target_epoch=1)
    assert out["ok"] is False and out["failed"] == "b1"
    assert "in-flight" in out["error"]
    assert ("undrain", "b1") in ops.calls


# ---------------------------------------------------------------------------
# Fleet canary verdicts
# ---------------------------------------------------------------------------


def test_fleet_canary_promote():
    canary = FleetCanary(0.25, ["b1"], target_epoch=5, baseline_epoch=4,
                         promote_after=10, budget=0.2)
    assert canary.state == SHADOW
    verdicts = [canary.note_result(True) for _ in range(10)]
    assert verdicts[:-1] == [None] * 9 and verdicts[-1] == "promote"
    assert canary.state == PRIMARY
    # Post-verdict rows are ignored (the verdict fires exactly once).
    assert canary.note_result(False) is None
    snap = canary.snapshot()
    assert snap["compared_rows"] == 10 and snap["promotions"] == 1


def test_fleet_canary_rollback_outranks_promotion():
    # budget 0.2 * promote_after 10 = 2 disagreements tolerated; the
    # third rolls back even with plenty of agreeing rows banked.
    canary = FleetCanary(0.25, ["b1"], target_epoch=5, baseline_epoch=4,
                         promote_after=10, budget=0.2)
    for _ in range(7):
        assert canary.note_result(True) is None
    assert canary.note_result(False) is None
    assert canary.note_result(False) is None
    assert canary.note_result(False) == "rollback"
    assert canary.state == ROLLED_BACK
    snap = canary.snapshot()
    assert snap["rollbacks"] == 1 and snap["disagreed_rows"] == 3
    assert snap["disagree_rate"] == 0.3


def test_fleet_canary_install_failure_short_circuits():
    canary = FleetCanary(0.25, ["b1"], target_epoch=5, baseline_epoch=4)
    assert canary.fail() == "rollback"
    assert canary.state == ROLLED_BACK
    assert canary.fail() is None  # idempotent
    assert canary.note_result(True) is None  # measurement closed


def test_fleet_canary_cohort_deterministic():
    canary = FleetCanary(0.3, ["b1"], target_epoch=5, baseline_epoch=4)
    clients = [f"client-{i}" for i in range(2000)]
    cohort = [c for c in clients if canary.wants(c)]
    # Same client, same side, every time; anonymous stays baseline.
    assert cohort == [c for c in clients if canary.wants(c)]
    assert not canary.wants(None) and not canary.wants("")
    assert 0.2 < len(cohort) / len(clients) < 0.4
    with pytest.raises(ValueError, match="fraction"):
        FleetCanary(0.0, ["b1"], 5, 4)


def test_fleet_canary_fault_injection_forces_disagreement(monkeypatch):
    """TPUMNIST_FLEET_FAULT=canary_disagree turns every cohort reply
    into a disagreement — the chaos twin's deterministic bad publish."""
    monkeypatch.setenv("TPUMNIST_FLEET_FAULT", "canary_disagree")
    canary = FleetCanary(0.5, ["b1"], target_epoch=5, baseline_epoch=4,
                         promote_after=100, budget=0.02)
    verdict = None
    for _ in range(10):
        verdict = verdict or canary.note_result(True)  # ok, but faulted
    assert verdict == "rollback" and canary.state == ROLLED_BACK


# ---------------------------------------------------------------------------
# Merged fleet quantiles
# ---------------------------------------------------------------------------


def _flat_percentile(vals, q):
    vals = sorted(vals)
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return vals[idx]


def _window_block(samples, seconds=10.0, queue_depth=0):
    return {
        "seconds": seconds,
        "rps": round(len(samples) / seconds, 3),
        "queue_depth": queue_depth,
        "count": len(samples),
        "p50_ms": _flat_percentile(samples, 0.50),
        "p95_ms": _flat_percentile(samples, 0.95),
        "p99_ms": _flat_percentile(samples, 0.99),
    }


def test_merge_windows_identical_backends_exact():
    """Backends sharing a distribution merge to that distribution —
    the CDF model is exact in the homogeneous case."""
    rng = random.Random(7)
    samples = [rng.uniform(1.0, 100.0) for _ in range(4000)]
    block = _window_block(samples)
    merged = merge_windows([block, dict(block), dict(block)])
    assert merged["backends"] == 3
    assert merged["count"] == 3 * len(samples)
    assert merged["rps"] == pytest.approx(3 * block["rps"], rel=1e-6)
    for key in ("p50_ms", "p95_ms", "p99_ms"):
        assert merged[key] == pytest.approx(block[key], rel=0.02), key


def test_merge_windows_vs_flat_recompute():
    """Heterogeneous backends (one fast, one slow, one mid, skewed
    counts): the merged quantiles track a flat recompute over the
    pooled samples within the documented tolerance, and the merged p50
    lands between the per-backend extremes."""
    rng = random.Random(11)
    pools = [
        [rng.uniform(1.0, 10.0) for _ in range(3000)],     # fast, busy
        [rng.uniform(20.0, 60.0) for _ in range(1000)],    # mid
        [rng.uniform(80.0, 200.0) for _ in range(200)],    # slow, idle
    ]
    blocks = [_window_block(p) for p in pools]
    merged = merge_windows(blocks)
    flat = [s for p in pools for s in p]
    assert merged["count"] == len(flat)
    for q, key in ((0.50, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
        want = _flat_percentile(flat, q)
        assert merged[key] == pytest.approx(want, rel=0.15), (key, want)
    assert min(b["p50_ms"] for b in blocks) <= merged["p50_ms"] \
        <= max(b["p50_ms"] for b in blocks)


def test_merge_windows_skips_empty_and_none():
    merged = merge_windows([None, {"count": 0}, None])
    assert merged["backends"] == 0 and merged["count"] == 0
    assert merged["p99_ms"] == 0.0
    one = _window_block([5.0, 6.0, 7.0, 8.0])
    merged = merge_windows([None, one, {"count": 0}])
    assert merged["backends"] == 1 and merged["count"] == 4
    assert merged["p50_ms"] == pytest.approx(one["p50_ms"], rel=0.05)


# ---------------------------------------------------------------------------
# Fleet autoscaler decide()
# ---------------------------------------------------------------------------


def test_fleet_autoscaler_decisions():
    sc = FleetAutoscaler(2, 4, slo_p95_ms=100.0, cooldown_s=10.0,
                         down_after=2)
    calm = {"p95_ms": 10.0, "count": 100}
    busy = {"p95_ms": 250.0, "count": 100}
    # Below the floor: up immediately, cooldown or not.
    assert sc.decide(1, calm, now=0.0) == "up"
    assert sc.decide(1, calm, now=0.1) == "up"
    # At the floor, busy, but inside cooldown -> hold.
    assert sc.decide(2, busy, now=1.0) is None
    # Cooldown expired -> up on SLO breach.
    assert sc.decide(2, busy, now=20.0) == "up"
    # At the ceiling, still busy -> no further up.
    assert sc.decide(4, busy, now=40.0) is None
    # Scale down only after down_after consecutive calm ticks.
    assert sc.decide(4, calm, now=60.0) is None   # calm streak 1
    assert sc.decide(4, busy, now=61.0) is None   # streak broken
    assert sc.decide(4, calm, now=62.0) is None   # streak 1 again
    assert sc.decide(4, calm, now=63.0) == "down"
    # Never below the floor.
    assert sc.decide(2, calm, now=80.0) is None
    assert sc.decide(2, calm, now=81.0) is None
    snap = sc.snapshot()
    assert snap["scale_ups"] == 3 and snap["scale_downs"] == 1
    assert snap["decisions"][-1]["action"] == "down"
    with pytest.raises(ValueError, match="fleet-max"):
        FleetAutoscaler(3, 2)


# ---------------------------------------------------------------------------
# Odds and ends
# ---------------------------------------------------------------------------


def test_epoch_of_checkpoint():
    assert epoch_of_checkpoint("/tmp/x/checkpoint_12.npz") == 12
    assert epoch_of_checkpoint("checkpoint_0.ckpt") == 0
    with pytest.raises(ValueError):
        epoch_of_checkpoint("/tmp/weights.npz")


def test_chaos_fault_env_pinned():
    """tools/chaos.py spells the fault env var out (to stay jax-free);
    this pin keeps the two spellings equal."""
    import tools.chaos as chaos
    from pytorch_distributed_mnist_tpu.serve import router

    assert chaos.FLEET_FAULT_ENV == router.FLEET_FAULT_ENV


def test_backend_name_normalization():
    assert Backend("127.0.0.1:8000").name == "127.0.0.1:8000"
    assert Backend("http://127.0.0.1:8000").name == "127.0.0.1:8000"
    assert Backend("http://127.0.0.1:8000/").url \
        == "http://127.0.0.1:8000"
    with pytest.raises(ValueError, match="host:port"):
        Backend("no-port")


def test_republish_with_epoch_rebases_embedded_epoch(tmp_path):
    """The rollback's roll-forward republish must rewrite the epoch the
    checkpoint CARRIES, not just its filename — load_checkpoint trusts
    ``__meta__``'s epoch and the engines refuse older params, so a plain
    copy of baseline weights under a newer name would be rejected and
    the bad epoch would keep serving. Arrays must survive byte-for-byte."""
    np = pytest.importorskip("numpy")
    import io
    import json as json_mod

    meta = {"epoch": 2, "best_acc": 0.5, "leaf_names": ["w", "b"],
            "format_version": 1}
    weights = np.arange(12, dtype=np.float32).reshape(3, 4)
    bias = np.ones(4, dtype=np.float32)
    buf = io.BytesIO()
    np.savez(buf, __meta__=np.frombuffer(
        json_mod.dumps(meta).encode(), np.uint8),
        leaf_0=weights, leaf_1=bias)
    source = tmp_path / "checkpoint_1.npz"
    source.write_bytes(buf.getvalue())

    dest = tmp_path / "checkpoint_3.npz"
    republish_with_epoch(str(source), str(dest), 3)

    with np.load(str(dest)) as z:
        out_meta = json_mod.loads(bytes(z["__meta__"]).decode())
        assert out_meta["epoch"] == 4  # stored epoch+1 convention
        assert out_meta["best_acc"] == 0.5
        assert out_meta["leaf_names"] == ["w", "b"]
        np.testing.assert_array_equal(z["leaf_0"], weights)
        np.testing.assert_array_equal(z["leaf_1"], bias)
    # The source is untouched (the baseline stays what it was).
    with np.load(str(source)) as z:
        assert json_mod.loads(bytes(z["__meta__"]).decode())["epoch"] == 2
