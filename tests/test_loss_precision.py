"""Regression: loss must be computed in f32 even when fused with a bf16 model.

On TPU, XLA's convert-folding demotes `astype(f32)` + exp/log chains back to
bf16 when fused into the model's epilogue, inflating converged eval loss
>10x (observed 0.0105 vs true 0.0004). ops.loss pins the f32 boundary with
an optimization_barrier; this test asserts the fused-vs-unfused agreement
contract that the bug violated.
"""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy, cross_entropy_per_example


def test_fused_bf16_model_loss_matches_unfused():
    model = get_model("cnn")  # bf16 compute
    x = jnp.asarray(np.random.default_rng(0).normal(size=(16, 28, 28, 1)), jnp.float32)
    y = jnp.asarray(np.arange(16) % 10, jnp.int32)
    params = model.init(jax.random.key(0), x)

    logits = jax.jit(model.apply)(params, x)  # materialized f32 logits
    unfused = float(cross_entropy(logits, y))

    @jax.jit
    def fused(params, x, y):
        return cross_entropy(model.apply(params, x), y)

    np.testing.assert_allclose(float(fused(params, x, y)), unfused, rtol=1e-4)


def test_per_example_ce_nonnegative_on_saturated_logits():
    # CE = -log p >= 0 analytically; must hold under any backend rounding.
    logits = jnp.asarray(
        np.random.default_rng(1).normal(scale=40, size=(64, 10)), jnp.float32
    )
    labels = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    per = jax.jit(cross_entropy_per_example)(logits, labels)
    assert float(per.min()) >= 0.0
