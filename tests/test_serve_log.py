"""The shared JSONL metrics sink: train epoch rows, supervision events,
and serving stats land in ONE file in one line-per-record format
(utils/profiling.py JsonlSink + EventLog/ServeLog wiring)."""

import json

import pytest

from pytorch_distributed_mnist_tpu.utils.profiling import (
    EventLog,
    JsonlSink,
    ServeLog,
)

pytestmark = pytest.mark.serve


def _lines(path):
    return [json.loads(l) for l in path.read_text().splitlines()]


def test_event_log_mirrors_to_sink(tmp_path):
    mf = tmp_path / "metrics.jsonl"
    log = EventLog()
    log.record("before_sink", "not mirrored")
    log.set_sink(JsonlSink(str(mf)), source="train")
    log.record("publish_retry", "attempt 1", extra=3)
    log.record("checkpoint_quarantined", "bad file")
    rows = _lines(mf)
    assert [r["kind"] for r in rows] == ["publish_retry",
                                        "checkpoint_quarantined"]
    assert rows[0]["source"] == "train" and rows[0]["extra"] == 3
    assert all("t" in r and "detail" in r for r in rows)
    # the in-memory snapshot keeps everything, sink or not
    assert len(log.snapshot()) == 3
    # reset detaches: a re-entrant run must not append to the old file
    log.reset()
    log.record("after_reset", "dropped from sink")
    assert len(_lines(mf)) == 2


def test_serve_log_stats_lines_share_the_format(tmp_path):
    mf = tmp_path / "metrics.jsonl"
    sink = JsonlSink(str(mf))
    slog = ServeLog()
    slog.set_sink(sink, source="serve")
    slog.record_request(0.010, queue_wait_s=0.002, images=4)
    slog.record_batch(rows=4, bucket=8)
    slog.record_rejection()
    slog.record_reload("/ckpt/checkpoint_3.npz", epoch=3)
    slog.record_reload_failure("/ckpt/checkpoint_4.npz", "corrupt")
    snap = slog.write_stats(final=True)
    rows = _lines(mf)
    kinds = [r["kind"] for r in rows]
    assert kinds == ["serve_reload", "serve_reload_failed", "serve_stats"]
    assert all(r["source"] == "serve" for r in rows)
    stats_row = rows[-1]
    assert stats_row["final"] is True
    assert stats_row["requests"] == snap["requests"] == 1
    assert stats_row["rejected"] == 1 and stats_row["reloads"] == 1
    assert stats_row["batch_histogram"] == {"8": 1}
    assert stats_row["latency_ms"]["p50"] == pytest.approx(10.0, abs=0.1)


def test_train_and_serve_can_share_one_file(tmp_path):
    """Both sides appending to the same path interleave cleanly (one
    line per record, each self-describing via kind/source or the epoch
    schema)."""
    mf = tmp_path / "metrics.jsonl"
    sink = JsonlSink(str(mf))
    elog, slog = EventLog(), ServeLog()
    elog.set_sink(sink, source="train")
    slog.set_sink(sink, source="serve")
    sink.write({"epoch": 0, "train_loss": 1.0})  # cli.run's epoch row
    elog.record("publish_retry", "x")
    slog.record_reload("/ckpt/checkpoint_0.npz", epoch=0)
    rows = _lines(mf)
    assert len(rows) == 3
    assert rows[0]["epoch"] == 0
    assert {rows[1]["source"], rows[2]["source"]} == {"train", "serve"}


def test_serve_log_percentiles_ordering():
    slog = ServeLog()
    for i in range(100):
        slog.record_request((i + 1) / 1000.0)
    lat = slog.snapshot()["latency_ms"]
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    assert lat["count"] == 100
    assert lat["p50"] == pytest.approx(51.0, abs=2.0)
