"""``bench.py --mode zero`` on the CPU backend: schema smoke for the
overlapped-ZeRO BENCH block — per-step comm/compute decomposition, the
ABBA-paired overlapped-vs-propagation speedup, overlap fraction, train
MFU, the CPU fallback honestly labelled, and the fails-loudly contract
when steady-state recompiles are nonzero — so the zero-mode BENCH schema
can't silently rot while CI only exercises the in-process pieces."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_comm_overlap_fraction_math():
    """The decomposition helper (utils/profiling.py): fully hidden,
    fully exposed, clamped edges, and the no-comm None."""
    from pytorch_distributed_mnist_tpu.utils.profiling import (
        comm_overlap_fraction,
    )

    # step == compute: every comm ms was hidden.
    assert comm_overlap_fraction(100.0, 100.0, 40.0) == 1.0
    # step == compute + comm: fully serialized.
    assert comm_overlap_fraction(140.0, 100.0, 40.0) == 0.0
    # half the comm extended the step.
    assert comm_overlap_fraction(120.0, 100.0, 40.0) == 0.5
    # noise pushing past the edges clamps instead of lying.
    assert comm_overlap_fraction(90.0, 100.0, 40.0) == 1.0
    assert comm_overlap_fraction(500.0, 100.0, 40.0) == 0.0
    # no measurable communication: nothing to overlap, never 0/0.
    assert comm_overlap_fraction(100.0, 100.0, 0.0) is None
    assert comm_overlap_fraction(None, 100.0, 40.0) is None


def test_per_tier_overlap_fractions_math():
    """The two-tier decomposition helper: each tier's entry is the
    guaranteed-hidden LOWER bound (the whole exposure charged against
    that tier alone), None propagating per tier."""
    from pytorch_distributed_mnist_tpu.utils.profiling import (
        per_tier_overlap_fractions,
    )

    # 30 ms exposed: at least 10 of ici's 40 must have been hidden
    # (0.25) no matter the attribution; dcn's 30 could all be exposed.
    fr = per_tier_overlap_fractions(130.0, 100.0, {"ici": 40.0, "dcn": 30.0})
    assert fr["ici"] == 0.25
    assert fr["dcn"] == 0.0
    # step == compute: every tier fully hidden.
    fr = per_tier_overlap_fractions(100.0, 100.0, {"ici": 40.0, "dcn": 30.0})
    assert fr == {"ici": 1.0, "dcn": 1.0}
    # a zero-comm tier has nothing to overlap; the other still scores.
    fr = per_tier_overlap_fractions(100.0, 100.0, {"ici": 40.0, "dcn": 0.0})
    assert fr["ici"] == 1.0 and fr["dcn"] is None
    # unknown compute: nothing can be attributed.
    fr = per_tier_overlap_fractions(100.0, None, {"ici": 40.0})
    assert fr["ici"] is None


def _run_zero_bench(env_extra, timeout=540):
    env = os.environ.copy()
    env.update({
        "JAX_PLATFORMS": "cpu",
        # Small drives: this asserts SCHEMA, not throughput. The compile
        # cache stays off — the bench both writes and re-reads entries
        # in one process, the exact pattern DESIGN.md 6c bans.
        "BENCH_ZERO_STEPS": "3",
        "BENCH_ZERO_BATCH": "128",
        "BENCH_ZERO_REPS": "3",
        "BENCH_COMPILE_CACHE": "",
        "TPUMNIST_COMPILE_CACHE": "",
        # Exercises the MFU math on CPU (the _peak_flops test hook the
        # training bench uses); stamped into the line as fake_bounds.
        "BENCH_FAKE_PEAK_FLOPS": "1e12",
    })
    env.update(env_extra)
    env.pop("XLA_FLAGS", None)  # let the bench force its own CPU world
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mode", "zero"],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    return proc, report


@pytest.mark.slow
def test_bench_zero_reports_overlap_block():
    proc, report = _run_zero_bench({})
    assert proc.returncode == 0, proc.stdout + proc.stderr

    assert report["metric"] == "mnist_zero_overlap_train_images_per_sec_per_chip"
    assert report.get("error") is None
    assert report["value"] > 0
    # CPU-fallback labeling, the --mode serve/input convention.
    assert report["backend"] == "cpu"
    assert report["n_chips"] >= 2  # the bench forced a multi-device world

    z = report["zero_overlap"]
    assert z["level"] == 3 and z["bucket_mb"] == 4.0
    assert z["steps"] == 3 and z["global_batch"] == 128
    # The measured decomposition: positive walls for the step and both
    # twins, a paired speedup with one ratio per rep, and an overlap
    # fraction inside [0, 1].
    assert z["step_ms_overlap"] > 0 and z["step_ms_propagation"] > 0
    assert z["comm_ms_per_step"] > 0 and z["compute_ms_per_step"] > 0
    assert len(z["pairs"]) == 3
    assert z["overlap_vs_propagation_speedup"] > 0
    assert report["vs_baseline"] == z["overlap_vs_propagation_speedup"]
    assert z["overlap_fraction"] is None or 0.0 <= z["overlap_fraction"] <= 1.0
    assert isinstance(z["overlap_beats_propagation"], bool)

    # Train MFU through _peak_flops (fake peak -> real number on CPU).
    assert z["mfu"] is not None and z["mfu"] >= 0
    assert z["flops_per_step"] > 0
    assert report["fake_bounds"] == {"BENCH_FAKE_PEAK_FLOPS": "1e12"}

    # The acceptance invariant: zero steady-state recompiles, BOTH paths.
    assert z["zero_steady_state_recompiles_overlap"] is True
    assert z["zero_steady_state_recompiles_propagation"] is True

    # CPU fallback honestly labelled (the BENCH_r05 precedent): the
    # caveat says overlap cannot manifest here, so the sign of the
    # speedup is not accelerator evidence.
    assert z["cpu_fallback"] is True
    assert "not" in z["caveat"] and "accelerator" in z["caveat"]

    # The two-tier (DCN x ICI) block: the forced 4-chip CPU world
    # emulates 2 slices by default, honestly labelled, with a per-tier
    # comm breakdown and per-drive recompile verdicts.
    tt = z["two_tier"]
    assert tt["dcn_slices"] == 2 and tt["chips_per_slice"] == 2
    assert tt["dcn_emulated"] is True
    assert "DCN" in tt["caveat"]
    assert tt["bucket_mb_dcn"] == tt["bucket_mb"] == 4.0
    assert tt["step_ms_two_tier"] > 0
    assert len(tt["pairs"]) == 3 and tt["vs_flat_overlap_speedup"] > 0
    assert set(tt["tiers"]) == {"ici", "dcn"}
    for tier in ("ici", "dcn"):
        row = tt["tiers"][tier]
        assert row["comm_ms_per_step"] > 0
        assert row["overlap_fraction"] is None \
            or 0.0 <= row["overlap_fraction"] <= 1.0
        assert row["zero_steady_state_recompiles"] is True
    assert tt["zero_steady_state_recompiles_two_tier"] is True


@pytest.mark.slow
def test_bench_zero_fails_loudly_on_steady_state_recompiles():
    """A backend compile inside the measured drive window (injected via
    the test-only hook) must flip the verdict, put the recompile in the
    error, and exit nonzero — the bench can never greenwash a
    shape-unstable steady state."""
    proc, report = _run_zero_bench({"BENCH_ZERO_INJECT_RECOMPILE": "1"})
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "recompile" in report["error"]
    assert report["zero_overlap"]["zero_steady_state_recompiles_overlap"] \
        is False
    # The uninjected path's verdict stays clean: attribution is per path.
    assert report["zero_overlap"][
        "zero_steady_state_recompiles_propagation"] is True


@pytest.mark.slow
def test_bench_zero_fails_loudly_on_hier_mesh_recompiles():
    """The fails-loudly contract re-pinned on the HIERARCHICAL mesh: a
    compile injected into the two-tier drive flips that verdict and
    exits 1 while the flat paths — and the per-tier comm twins — stay
    clean, so attribution survives the hierarchy."""
    proc, report = _run_zero_bench(
        {"BENCH_ZERO_INJECT_RECOMPILE": "two_tier"})
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "recompile" in report["error"] and "two_tier" in report["error"]
    z = report["zero_overlap"]
    assert z["two_tier"]["zero_steady_state_recompiles_two_tier"] is False
    for tier in ("ici", "dcn"):
        assert z["two_tier"]["tiers"][tier][
            "zero_steady_state_recompiles"] is True
    assert z["zero_steady_state_recompiles_overlap"] is True
    assert z["zero_steady_state_recompiles_propagation"] is True
