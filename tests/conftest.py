"""Test environment: 8 virtual CPU devices, hermetic and TPU-free.

Must run before jax initializes its backend, hence env vars at module import
(pytest imports conftest before test modules). This is the simulated-mesh
strategy from SURVEY.md section 4: ``xla_force_host_platform_device_count=8``
lets every mesh/psum/sharded-loader property run on CPU without a pod.
"""

import os

# Force CPU even when the environment points JAX at a real accelerator
# (e.g. JAX_PLATFORMS=axon): the test suite must be hermetic and see exactly
# 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    xla_flags = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
if "xla_cpu_collective_call_terminate_timeout_seconds" not in xla_flags:
    # 8 virtual devices timeshare this host's SINGLE core: XLA:CPU's
    # default 40s in-process collective rendezvous termination can fire
    # from pure scheduling starvation (observed: collective-permute
    # rendezvous abort, 5 of 8 threads arrived, same program passes when
    # the core is idle). Starvation is not deadlock — give it time.
    xla_flags += (" --xla_cpu_collective_call_terminate_timeout_seconds=600"
                  " --xla_cpu_collective_timeout_seconds=600")
os.environ["XLA_FLAGS"] = xla_flags

import jax

# Some environments ship a jax plugin that force-writes jax_platforms on
# import (overriding JAX_PLATFORMS); write it back before any backend
# initializes so the suite really runs on the 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: most of the suite's wall-clock is XLA
# recompilation of near-identical programs across test processes (round-2
# VERDICT measured 1127s for 255 tests, ~19 min of mostly compiles). The
# cache dir is shared with bench.py/tools (same .xla_cache, gitignored);
# entries are keyed by platform so CPU test entries never collide with
# TPU bench entries.
_cache_dir = os.environ.get(
    "TPU_MNIST_TEST_CACHE",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".xla_cache"))
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import numpy as np
import pytest


def pytest_collection_modifyitems(config, items):
    """Default fast profile: deselect ``@pytest.mark.slow`` unless the
    caller passed ``-m`` (their expression wins) or named a test
    explicitly by node id (``pytest tests/x.py::test_y`` must run it, not
    report '1 deselected' and exit green having run nothing — the failure
    mode an ``addopts = -m 'not slow'`` filter has)."""
    if config.option.markexpr:
        return
    named = []
    for arg in config.invocation_params.args:
        if "::" in str(arg):
            a = str(arg)
            # Normalize to the rootdir-relative node id form.
            tail = a[a.index("tests/"):] if "tests/" in a else a
            named.append(tail)
    kept, dropped = [], []
    for item in items:
        if "slow" in item.keywords and not any(
                item.nodeid == n or item.nodeid.startswith(n + "::")
                or item.nodeid.startswith(n + "[")  # param id omitted
                for n in named):
            dropped.append(item)
        else:
            kept.append(item)
    if dropped:
        config.hook.pytest_deselected(items=dropped)
        items[:] = kept


@pytest.fixture(scope="session")
def mesh8():
    import jax

    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh

    assert jax.device_count() == 8, "virtual 8-device CPU mesh not active"
    return make_mesh(("data",))


@pytest.fixture(scope="session")
def tiny_data():
    """Small deterministic synthetic dataset, normalized, shared across tests."""
    from pytorch_distributed_mnist_tpu.data.mnist import normalize_images, synthetic_dataset

    images, labels = synthetic_dataset(512, seed=42)
    return normalize_images(images), labels.astype(np.int32)


@pytest.fixture(autouse=True)
def _reset_loss_impl():
    """The loss impl is a process-global trace-time switch (ops/loss.py);
    a test that sets 'fused' must not leak it into later-collected tests
    (which would silently stop exercising the XLA path — including the
    bf16 optimization-barrier regression coverage)."""
    yield
    from pytorch_distributed_mnist_tpu.ops.loss import set_loss_impl

    set_loss_impl("xla")
