"""Test environment: 8 virtual CPU devices, hermetic and TPU-free.

Must run before jax initializes its backend, hence env vars at module import
(pytest imports conftest before test modules). This is the simulated-mesh
strategy from SURVEY.md section 4: ``xla_force_host_platform_device_count=8``
lets every mesh/psum/sharded-loader property run on CPU without a pod.
"""

import os
import subprocess
import sys

# Force CPU even when the environment points JAX at a real accelerator
# (e.g. JAX_PLATFORMS=axon): the test suite must be hermetic and see exactly
# 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"

# Repo root on sys.path: the analyzer suites import the uninstalled
# ``tools`` package (conftest imports before every test module, so no
# per-file bootstrap is needed).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _xla_flags_supported(flags: str) -> bool:
    """Whether this jaxlib's XLA knows ``flags``. XLA *aborts the process*
    (parse_flags_from_env fatal) on an unknown flag at backend init — an
    older jaxlib would take the whole suite down with it, 0 tests run —
    so probe in a throwaway child first (~1s, once per pytest session)."""
    probe = ("import os; os.environ['XLA_FLAGS'] = %r; "
             "from jaxlib import xla_client; xla_client.make_cpu_client()"
             % flags)
    try:
        return subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, timeout=120
        ).returncode == 0
    except (OSError, subprocess.SubprocessError):
        return False


xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    xla_flags = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
_collective_flags = (" --xla_cpu_collective_call_terminate_timeout_seconds=600"
                     " --xla_cpu_collective_timeout_seconds=600")
if "xla_cpu_collective_call_terminate_timeout_seconds" not in xla_flags \
        and _xla_flags_supported(_collective_flags.strip()):
    # 8 virtual devices timeshare this host's SINGLE core: XLA:CPU's
    # default 40s in-process collective rendezvous termination can fire
    # from pure scheduling starvation (observed: collective-permute
    # rendezvous abort, 5 of 8 threads arrived, same program passes when
    # the core is idle). Starvation is not deadlock — give it time.
    # (Older jaxlibs predate these flags; there the default timeout is
    # all we get, which only risks flakes on a loaded core, not aborts.)
    xla_flags += _collective_flags
os.environ["XLA_FLAGS"] = xla_flags

import jax

# Some environments ship a jax plugin that force-writes jax_platforms on
# import (overriding JAX_PLATFORMS); write it back before any backend
# initializes so the suite really runs on the 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: DISABLED by default inside the pytest process.
# On this jaxlib (0.4.x CPU), a process that both WRITES cache entries and
# then re-instantiates a jit of the same program (every test file does)
# executes a freshly-DESERIALIZED executable next to the one it compiled —
# and that read-after-write pattern flakily corrupts the glibc heap
# ("double free or corruption" mid-suite, ~50% reproducible; see
# docs/DESIGN.md "compile-latency subsystem" for the full analysis). The
# safe patterns — cold run writes only, warm fresh process reads only —
# are exactly what production and the subprocess-based warm-start tests
# use, so the cache stays on for spawned children via the shared wiring
# (utils/compile_cache.py); opt back in here with TPU_MNIST_TEST_CACHE on
# a jaxlib where in-process reuse is sound.
from pytorch_distributed_mnist_tpu.utils.compile_cache import (  # noqa: E402
    configure_ambient,
)

# The env var outranks the pinned ambient in resolve_cache_dir, so a
# developer's exported TPUMNIST_COMPILE_CACHE (the documented production
# warm-up knob) would silently re-enable the in-process cache behind the
# pin — drop it from THIS process. Subprocess children spawned by tests
# build their own env and stay on the (safe, fresh-process) default.
os.environ.pop("TPUMNIST_COMPILE_CACHE", None)
configure_ambient(os.environ.get("TPU_MNIST_TEST_CACHE", ""))

# Agreement watchdogs default ON in tests (off in production): any
# multi-process child a test spawns inherits this via _child_env, so a
# protocol regression that re-introduces a strand fails as a loud
# PeerFailure near this deadline instead of idling until the test's
# communicate() timeout. 300s is far above any legitimate skew between
# healthy ranks (whole 2-rank runs finish in well under that); chaos
# twins override with a tight per-test value.
os.environ.setdefault("TPUMNIST_AGREEMENT_TIMEOUT", "300")

import numpy as np
import pytest


def pytest_collection_modifyitems(config, items):
    """Default fast profile: deselect ``@pytest.mark.slow`` unless the
    caller passed ``-m`` (their expression wins) or named a test
    explicitly by node id (``pytest tests/x.py::test_y`` must run it, not
    report '1 deselected' and exit green having run nothing — the failure
    mode an ``addopts = -m 'not slow'`` filter has)."""
    if config.option.markexpr:
        return
    named = []
    for arg in config.invocation_params.args:
        if "::" in str(arg):
            a = str(arg)
            # Normalize to the rootdir-relative node id form.
            tail = a[a.index("tests/"):] if "tests/" in a else a
            named.append(tail)
    kept, dropped = [], []
    for item in items:
        if "slow" in item.keywords and not any(
                item.nodeid == n or item.nodeid.startswith(n + "::")
                or item.nodeid.startswith(n + "[")  # param id omitted
                for n in named):
            dropped.append(item)
        else:
            kept.append(item)
    if dropped:
        config.hook.pytest_deselected(items=dropped)
        items[:] = kept


@pytest.fixture(scope="session")
def mesh8():
    import jax

    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh

    assert jax.device_count() == 8, "virtual 8-device CPU mesh not active"
    return make_mesh(("data",))


@pytest.fixture(scope="session")
def tiny_data():
    """Small deterministic synthetic dataset, normalized, shared across tests."""
    from pytorch_distributed_mnist_tpu.data.mnist import normalize_images, synthetic_dataset

    images, labels = synthetic_dataset(512, seed=42)
    return normalize_images(images), labels.astype(np.int32)


@pytest.fixture(autouse=True)
def _reset_loss_impl():
    """The loss impl is a process-global trace-time switch (ops/loss.py);
    a test that sets 'fused' must not leak it into later-collected tests
    (which would silently stop exercising the XLA path — including the
    bf16 optimization-barrier regression coverage)."""
    yield
    from pytorch_distributed_mnist_tpu.ops.loss import set_loss_impl

    set_loss_impl("xla")
