"""Train/eval step engine: loss decreases, DP equivalence (the DDP test),
scan epoch == stepwise epoch, explicit shard_map == GSPMD auto."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.collectives import make_explicit_dp_train_step
from pytorch_distributed_mnist_tpu.parallel.mesh import data_sharding, make_mesh
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import (
    make_eval_step,
    make_train_epoch,
    make_train_step,
)


def fresh_state(model_name="linear", lr=1e-3):
    model = get_model(model_name, compute_dtype=jnp.float32)  # f32 for exact tests
    return create_train_state(model, jax.random.key(0), lr=lr)


def batch_of(tiny_data, start, n):
    images, labels = tiny_data
    return {"image": jnp.asarray(images[start : start + n]),
            "label": jnp.asarray(labels[start : start + n])}


def test_loss_decreases_single_device(tiny_data):
    state = fresh_state()
    step = make_train_step()
    batch = batch_of(tiny_data, 0, 64)
    losses = []
    for _ in range(20):
        state, m = step(state, batch)
        losses.append(float(m.loss_sum) / float(m.count))
    assert losses[-1] < losses[0] * 0.7


def test_step_counter_increments(tiny_data):
    state = fresh_state()
    step = make_train_step()
    state, _ = step(state, batch_of(tiny_data, 0, 32))
    state, _ = step(state, batch_of(tiny_data, 32, 32))
    assert int(state.step) == 2


def test_dp_equivalence_8dev_vs_1dev(tiny_data, mesh8):
    """N-device DP step == single-device step on the same global batch.

    This is the DDP-equivalence property from SURVEY.md section 7 item 3: the
    reference gets it from DDP allreduce; here sharding propagation must
    produce the identical update.
    """
    batch = batch_of(tiny_data, 0, 128)

    s1 = fresh_state()
    step1 = make_train_step()
    s1, m1 = step1(s1, batch)

    s8 = fresh_state()
    step8 = make_train_step(mesh8)
    gbatch = {k: jax.device_put(v, data_sharding(mesh8)) for k, v in batch.items()}
    s8, m8 = step8(s8, gbatch)

    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(float(m1.loss_sum), float(m8.loss_sum), rtol=1e-5)
    assert float(m1.count) == float(m8.count) == 128


def test_explicit_shard_map_matches_auto(tiny_data, mesh8):
    """shard_map+psum step produces the same update as the GSPMD auto step."""
    batch = batch_of(tiny_data, 0, 128)
    gbatch = {k: jax.device_put(v, data_sharding(mesh8)) for k, v in batch.items()}

    sa = fresh_state()
    auto = make_train_step(mesh8)
    sa, ma = auto(sa, gbatch)

    se = fresh_state()
    explicit = make_explicit_dp_train_step(mesh8)
    gbatch2 = {k: jax.device_put(v, data_sharding(mesh8)) for k, v in batch.items()}
    se, me = explicit(se, gbatch2)

    for a, b in zip(jax.tree.leaves(sa.params), jax.tree.leaves(se.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    np.testing.assert_allclose(float(ma.correct), float(me.correct))
    np.testing.assert_allclose(
        float(ma.loss_sum) / float(ma.count), float(me.loss_sum) / float(me.count), rtol=1e-5
    )


def test_scan_epoch_matches_stepwise(tiny_data):
    images, labels = tiny_data
    nsteps, bs = 4, 32
    batches = {
        "image": jnp.asarray(images[: nsteps * bs]).reshape(nsteps, bs, 28, 28, 1),
        "label": jnp.asarray(labels[: nsteps * bs]).reshape(nsteps, bs),
    }
    s_scan = fresh_state()
    epoch = make_train_epoch()
    s_scan, m_scan = epoch(s_scan, batches)

    s_step = fresh_state()
    step = make_train_step()
    total = None
    for i in range(nsteps):
        b = {"image": batches["image"][i], "label": batches["label"][i]}
        s_step, m = step(s_step, b)
        total = m if total is None else type(m)(
            total.loss_sum + m.loss_sum, total.correct + m.correct, total.count + m.count
        )
    for a, b in zip(jax.tree.leaves(s_scan.params), jax.tree.leaves(s_step.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(float(m_scan.loss_sum), float(total.loss_sum), rtol=1e-5)


def test_eval_step_does_not_train(tiny_data):
    state = fresh_state()
    ev = make_eval_step()
    batch = batch_of(tiny_data, 0, 64)
    before = jax.tree.map(np.asarray, state.params)
    m = ev(state, batch)
    after = jax.tree.map(np.asarray, state.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(a, b)
    assert float(m.count) == 64


def test_lr_injection_changes_update_magnitude(tiny_data):
    batch = batch_of(tiny_data, 0, 64)
    step = make_train_step()

    def update_norm(lr):
        s = fresh_state(lr=1e-3).with_learning_rate(lr)
        p0 = jax.tree.map(np.asarray, s.params)
        s, _ = step(s, batch)
        deltas = jax.tree.map(lambda a, b: np.abs(np.asarray(a) - b).sum(), s.params, p0)
        return sum(jax.tree.leaves(deltas))

    assert update_norm(1e-2) > update_norm(1e-4) * 5
