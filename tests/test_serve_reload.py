"""CheckpointWatcher: publish -> poll -> atomic swap; corrupt/vanished
checkpoints never take the server down; the GC window keeps the
watcher's load target alive."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import synthetic_dataset
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
from pytorch_distributed_mnist_tpu.serve.reload import CheckpointWatcher
from pytorch_distributed_mnist_tpu.train.checkpoint import (
    prune_checkpoints,
    save_checkpoint,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.utils.profiling import ServeLog

pytestmark = pytest.mark.serve


@pytest.fixture()
def setup(tmp_path):
    model = get_model("linear", compute_dtype=jnp.float32)
    template = create_train_state(model, jax.random.key(0))
    images, _ = synthetic_dataset(8, seed=1)
    return model, template, images, str(tmp_path)


def _publish(template, epoch, seed, directory, keep_last=0):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=directory, process_index=0,
                    keep_last=keep_last)
    return state


def test_poll_installs_newly_published(setup):
    model, template, images, ckpt_dir = setup
    engine = InferenceEngine(model.apply, template.params, buckets=(8,))
    engine.warmup()
    log = ServeLog()
    watcher = CheckpointWatcher(ckpt_dir, template, engine.swap_params,
                                serve_log=log)
    assert not watcher.poll_once()  # empty dir: nothing to do

    state_a = _publish(template, epoch=0, seed=10, directory=ckpt_dir)
    assert watcher.poll_once()
    assert engine.params_epoch == 0
    got = engine.logits(images)
    want = np.asarray(model.apply(state_a.params, jnp.asarray(
        engine.preprocess(images)), train=False))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert not watcher.poll_once()  # same path: no redundant reload

    state_b = _publish(template, epoch=1, seed=20, directory=ckpt_dir)
    assert watcher.poll_once()
    assert engine.params_epoch == 1
    want_b = np.asarray(model.apply(state_b.params, jnp.asarray(
        engine.preprocess(images)), train=False))
    np.testing.assert_allclose(engine.logits(images), want_b,
                               rtol=1e-6, atol=1e-6)
    assert log.snapshot()["reloads"] == 2


def test_corrupt_checkpoint_keeps_serving(setup):
    model, template, images, ckpt_dir = setup
    engine = InferenceEngine(model.apply, template.params, buckets=(8,))
    engine.warmup()
    log = ServeLog()
    watcher = CheckpointWatcher(ckpt_dir, template, engine.swap_params,
                                serve_log=log)
    _publish(template, epoch=0, seed=10, directory=ckpt_dir)
    assert watcher.poll_once()
    before = engine.logits(images)

    # A torn write that somehow escaped the atomic-publish discipline
    # (or plain disk corruption of the newest file).
    with open(os.path.join(ckpt_dir, "checkpoint_3.npz"), "wb") as f:
        f.write(b"this is not an npz file")
    assert not watcher.poll_once()
    np.testing.assert_array_equal(engine.logits(images), before)
    assert engine.params_epoch == 0
    snap = log.snapshot()
    assert snap["reload_failures"] == 1 and snap["reloads"] == 1
    # The bad path is remembered: no retry hot-loop...
    assert not watcher.poll_once()
    assert log.snapshot()["reload_failures"] == 1
    # ...but a NEWER publish is picked up immediately.
    _publish(template, epoch=5, seed=30, directory=ckpt_dir)
    assert watcher.poll_once()
    assert engine.params_epoch == 5


def test_model_mismatch_rejected_not_served(setup):
    """A checkpoint from a different architecture fails template
    validation and is refused; the server keeps its params."""
    model, template, images, ckpt_dir = setup
    engine = InferenceEngine(model.apply, template.params, buckets=(8,))
    engine.warmup()
    before = engine.logits(images)
    cnn = get_model("cnn")
    cnn_state = create_train_state(cnn, jax.random.key(0))
    save_checkpoint(cnn_state, epoch=2, best_acc=0.9, is_best=False,
                    directory=ckpt_dir, process_index=0)
    log = ServeLog()
    watcher = CheckpointWatcher(ckpt_dir, template, engine.swap_params,
                                serve_log=log)
    assert not watcher.poll_once()
    np.testing.assert_array_equal(engine.logits(images), before)
    assert log.snapshot()["reload_failures"] == 1


def test_transient_failure_retries_same_path(setup, monkeypatch):
    """A transient load error (EIO, momentary OOM) must NOT blacklist the
    path: after training's final publish no newer checkpoint will ever
    appear to clear it, so the next poll retries and succeeds."""
    model, template, images, ckpt_dir = setup
    engine = InferenceEngine(model.apply, template.params, buckets=(8,))
    engine.warmup()
    log = ServeLog()
    watcher = CheckpointWatcher(ckpt_dir, template, engine.swap_params,
                                serve_log=log)
    _publish(template, epoch=0, seed=10, directory=ckpt_dir)

    import pytorch_distributed_mnist_tpu.serve.reload as reload_mod

    calls = {"n": 0}
    from pytorch_distributed_mnist_tpu.serve.engine import (
        load_params_for_serving as real_load,
    )

    def flaky(path, tmpl):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(5, "Input/output error")  # flaky NFS read
        return real_load(path, tmpl)

    import pytorch_distributed_mnist_tpu.serve.engine as engine_mod

    monkeypatch.setattr(engine_mod, "load_params_for_serving", flaky)
    assert not watcher.poll_once()  # transient failure recorded...
    assert log.snapshot()["reload_failures"] == 1
    assert watcher.poll_once()  # ...and the SAME path succeeds next poll
    assert engine.params_epoch == 0
    assert log.snapshot()["reloads"] == 1


def test_stale_nfs_missing_shards_retries(setup, monkeypatch):
    """_load_sharded's missing-shards ValueError is absence-level (stale
    NFS readdir of an atomically-published dir), NOT corruption — the
    same taxonomy is_corrupt_checkpoint_error documents — so the watcher
    must retry the same path, not blacklist it."""
    model, template, images, ckpt_dir = setup
    engine = InferenceEngine(model.apply, template.params, buckets=(8,))
    engine.warmup()
    log = ServeLog()
    watcher = CheckpointWatcher(ckpt_dir, template, engine.swap_params,
                                serve_log=log)
    _publish(template, epoch=0, seed=10, directory=ckpt_dir)

    calls = {"n": 0}
    from pytorch_distributed_mnist_tpu.serve.engine import (
        load_params_for_serving as real_load,
    )

    def stale_then_ok(path, tmpl):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError(
                f"{path}: leaf params is missing shards (0/10 elements "
                f"present) — incomplete save?")
        return real_load(path, tmpl)

    import pytorch_distributed_mnist_tpu.serve.engine as engine_mod

    monkeypatch.setattr(engine_mod, "load_params_for_serving",
                        stale_then_ok)
    assert not watcher.poll_once()
    assert watcher.poll_once()  # same path, next poll: view settled
    assert engine.params_epoch == 0


def test_watcher_thread_polls(setup):
    """The daemon thread variant actually installs a publish."""
    import time

    model, template, images, ckpt_dir = setup
    engine = InferenceEngine(model.apply, template.params, buckets=(8,))
    engine.warmup()
    watcher = CheckpointWatcher(ckpt_dir, template, engine.swap_params,
                                poll_interval_s=0.05).start()
    try:
        _publish(template, epoch=4, seed=40, directory=ckpt_dir)
        deadline = time.time() + 10.0
        while engine.params_epoch != 4 and time.time() < deadline:
            time.sleep(0.02)
        assert engine.params_epoch == 4
    finally:
        watcher.stop()


def test_gc_window_never_deletes_watcher_target(setup):
    """The prune/reload ordering guarantee: publishing epoch E with
    --keep-last N leaves every epoch in [E-N, E] on disk — in particular
    the PREVIOUS latest, which is the file a watcher may be mid-load on
    when the publish happens."""
    model, template, images, ckpt_dir = setup
    for e in range(6):
        _publish(template, epoch=e, seed=e, directory=ckpt_dir,
                 keep_last=2)
        names = sorted(n for n in os.listdir(ckpt_dir)
                       if n.startswith("checkpoint_"))
        window = [f"checkpoint_{k}.npz" for k in range(max(0, e - 2), e + 1)]
        assert names == window
        # the previous latest — the watcher's possible in-flight load —
        # is always inside the window
        if e:
            assert f"checkpoint_{e - 1}.npz" in names


def test_prune_window_with_gaps(tmp_path):
    """Window semantics are epoch-distance, not file-count: epochs 1/5/9
    with keep_last=2 prunes everything older than 9-2=7."""
    model = get_model("linear", compute_dtype=jnp.float32)
    for e in (1, 5, 9):
        state = create_train_state(model, jax.random.key(e))
        save_checkpoint(state, epoch=e, best_acc=0.1, is_best=False,
                        directory=str(tmp_path), process_index=0)
    prune_checkpoints(str(tmp_path), keep_last=2)
    names = sorted(n for n in os.listdir(tmp_path)
                   if n.startswith("checkpoint_"))
    assert names == ["checkpoint_9.npz"]
