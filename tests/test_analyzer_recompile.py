"""Fixture suite: the recompile-hazard checker."""


import pytest


from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src):
    return analyze_snippet(src, checkers=["recompile-hazard"])


# -- firing ------------------------------------------------------------------


def test_fires_on_scalar_into_precompile_product():
    src = """
def serve(fn, params_spec, image_spec, x):
    exe = precompile(fn, params_spec, image_spec, program="fwd")
    return exe(x, 0.5)
"""
    (f,) = _findings(src)
    assert "argument 1" in f.message and "AOT-compiled" in f.message


def test_fires_on_scalar_into_lower_compile_product():
    src = """
def bench(step, state_spec, batch_spec):
    compiled = step.lower(state_spec, batch_spec).compile()
    return compiled(-1, batch_spec)
"""
    (f,) = _findings(src)
    assert "argument 0" in f.message


def test_fires_on_scalar_into_self_attribute_executable():
    src = """
class Engine:
    def warm(self, fn, spec):
        self._fwd = precompile(fn, spec)

    def infer(self, params):
        return self._fwd(params, 3)
"""
    (f,) = _findings(src)
    assert f.symbol.endswith("infer")


def test_fires_on_jit_without_static_declaration():
    src = """
import jax

def forward(params, x, train=False, impl="xla"):
    return x

prog = jax.jit(forward)
"""
    (f,) = _findings(src)
    assert "train" in f.message and "impl" in f.message
    assert "static_argnums" in f.message


def test_fires_on_bare_jit_decorator_with_config_default():
    src = """
import jax

@jax.jit
def kernel(x, interpret=False):
    return x
"""
    (f,) = _findings(src)
    assert "interpret" in f.message


# -- non-firing --------------------------------------------------------------


def test_silent_when_statics_are_declared():
    src = """
import functools, jax

@functools.partial(jax.jit, static_argnames=("interpret",))
def kernel(x, interpret=False):
    return x

def forward(params, x, train=False):
    return x

prog = jax.jit(forward, static_argnames=("train",))
"""
    assert _findings(src) == []


def test_silent_on_array_variables_into_executables():
    """The trainer/engine idiom: staged arrays and specs, never bare
    literals."""
    src = """
def serve(fn, params_spec, image_spec, params, staged):
    exe = precompile(fn, params_spec, image_spec)
    return exe(params, staged)
"""
    assert _findings(src) == []


def test_silent_on_partial_bound_config():
    """functools.partial binding before jit is the steps.py idiom — the
    bound value is baked in at trace time, nothing to declare."""
    src = """
import functools, jax

def step(state, batch, aux_weight=0.0):
    return state

step_fn = functools.partial(step, aux_weight=0.5)
prog = jax.jit(step_fn, donate_argnums=(0,))
"""
    assert _findings(src) == []


def test_silent_on_float_default_without_static():
    """Float defaults are weight-like (aux_weight), not config flags —
    jit traces them fine; only hashable bool/str config is flagged."""
    src = """
import jax

def step(state, batch, aux_weight=0.0):
    return state

prog = jax.jit(step)
"""
    assert _findings(src) == []
