"""Fixture suite: the recompile-hazard checker."""


import pytest


from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src):
    return analyze_snippet(src, checkers=["recompile-hazard"])


# -- firing ------------------------------------------------------------------


def test_fires_on_scalar_into_precompile_product():
    src = """
def serve(fn, params_spec, image_spec, x):
    exe = precompile(fn, params_spec, image_spec, program="fwd")
    return exe(x, 0.5)
"""
    (f,) = _findings(src)
    assert "argument 1" in f.message and "AOT-compiled" in f.message


def test_fires_on_scalar_into_lower_compile_product():
    src = """
def bench(step, state_spec, batch_spec):
    compiled = step.lower(state_spec, batch_spec).compile()
    return compiled(-1, batch_spec)
"""
    (f,) = _findings(src)
    assert "argument 0" in f.message


def test_fires_on_scalar_into_self_attribute_executable():
    src = """
class Engine:
    def warm(self, fn, spec):
        self._fwd = precompile(fn, spec)

    def infer(self, params):
        return self._fwd(params, 3)
"""
    (f,) = _findings(src)
    assert f.symbol.endswith("infer")


def test_fires_on_jit_without_static_declaration():
    src = """
import jax

def forward(params, x, train=False, impl="xla"):
    return x

prog = jax.jit(forward)
"""
    (f,) = _findings(src)
    assert "train" in f.message and "impl" in f.message
    assert "static_argnums" in f.message


def test_fires_on_bare_jit_decorator_with_config_default():
    src = """
import jax

@jax.jit
def kernel(x, interpret=False):
    return x
"""
    (f,) = _findings(src)
    assert "interpret" in f.message


# -- non-firing --------------------------------------------------------------


def test_silent_when_statics_are_declared():
    src = """
import functools, jax

@functools.partial(jax.jit, static_argnames=("interpret",))
def kernel(x, interpret=False):
    return x

def forward(params, x, train=False):
    return x

prog = jax.jit(forward, static_argnames=("train",))
"""
    assert _findings(src) == []


def test_silent_on_array_variables_into_executables():
    """The trainer/engine idiom: staged arrays and specs, never bare
    literals."""
    src = """
def serve(fn, params_spec, image_spec, params, staged):
    exe = precompile(fn, params_spec, image_spec)
    return exe(params, staged)
"""
    assert _findings(src) == []


def test_silent_on_partial_bound_config():
    """functools.partial binding before jit is the steps.py idiom — the
    bound value is baked in at trace time, nothing to declare."""
    src = """
import functools, jax

def step(state, batch, aux_weight=0.0):
    return state

step_fn = functools.partial(step, aux_weight=0.5)
prog = jax.jit(step_fn, donate_argnums=(0,))
"""
    assert _findings(src) == []


def test_silent_on_float_default_without_static():
    """Float defaults are weight-like (aux_weight), not config flags —
    jit traces them fine; only hashable bool/str config is flagged."""
    src = """
import jax

def step(state, batch, aux_weight=0.0):
    return state

prog = jax.jit(step)
"""
    assert _findings(src) == []


# -- the shard_map-reduce-scatter shape (ISSUE 7, parallel/zero_overlap.py) --


def test_fires_on_jit_of_rs_step_with_config_default():
    """An overlapped-ZeRO step whose body takes a bool config flag
    (interpret/debug toggles) jitted without statics: each distinct
    value re-traces the whole bucket chain — the recompile class the
    zero bench's steady-state verdict exists to catch."""
    src = """
import jax
from jax import lax

def zero_step(state, batch, debug_buckets=False):
    g = compute_grads(state, batch)
    return lax.psum_scatter(g, "data", scatter_dimension=0, tiled=True)

prog = jax.jit(zero_step, donate_argnums=(0,))
"""
    (f,) = _findings(src)
    assert "debug_buckets" in f.message


def test_fires_on_scalar_into_compiled_zero_step():
    """The AOT-compiled overlapped step's spec holds committed arrays;
    a raw literal where the batch belongs either fails the argument
    check or silently re-keys a compile through a fallback wrapper."""
    src = """
import jax

def bench(step_jit, state, batch):
    compiled = step_jit.lower(state, batch).compile()
    return compiled(state, 128)
"""
    (f,) = _findings(src)
    assert "scalar" in f.message


def test_silent_on_clean_zero_step_factory():
    """The sanctioned zero_overlap factory: plan/level/bucket budget are
    closure-bound at build time (no config params on the traced body),
    and the compiled executable is called with arrays only."""
    src = """
import jax
from jax import lax

def make_zero_step(mesh, plan):
    def body(state, batch):
        g = compute_grads(state, batch)
        return lax.psum_scatter(g, "data", scatter_dimension=0, tiled=True)

    return jax.jit(jax.shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None), donate_argnums=(0,))

def drive(step_jit, state, batch):
    compiled = step_jit.lower(state, batch).compile()
    return compiled(state, batch)
"""
    assert _findings(src) == []


def test_silent_on_static_declared_rs_config():
    src = """
import jax
from jax import lax

def zero_step(state, batch, debug_buckets=False):
    g = compute_grads(state, batch)
    return lax.psum_scatter(g, "data", scatter_dimension=0, tiled=True)

prog = jax.jit(zero_step, static_argnames=("debug_buckets",))
"""
    assert _findings(src) == []


# -- the serving-mesh lowering shape (ISSUE 8, serve/programs.py) ------------


def test_fires_on_literal_into_compiled_mesh_bucket():
    """The sharded engine's bucket executables take (params, staged
    batch); a raw literal where the batch belongs re-keys a compile
    through the jit fallback — the steady-state violation the per
    bucket x mode bench verdict fails loudly on."""
    src = """
import jax

def warm_and_drive(pjit_forward, params_spec, image_spec, params):
    compiled = pjit_forward.lower(params_spec, image_spec).compile()
    return compiled(params, 128)
"""
    (f,) = _findings(src)
    assert "scalar" in f.message


def test_fires_on_mode_config_default_on_mesh_forward():
    """A debug/interpret toggle with a default on the pjit-lowered
    serve forward, jitted without statics: each distinct value
    re-traces every bucket program of the mesh group."""
    src = """
import jax

def make_serve_forward(apply_fn):
    def forward(params, images, interpret=False):
        return apply_fn(params, images, train=False)

    return jax.jit(forward, in_shardings=None, out_shardings=None)
"""
    (f,) = _findings(src)
    assert "interpret" in f.message


def test_silent_on_clean_bucket_lowering_loop():
    """The sanctioned programs/engine shape: one lower().compile() per
    bucket against ShapeDtypeStruct specs, the compiled product called
    with arrays only; serve mode and rules are closure-bound at build
    time."""
    src = """
import jax
import numpy as np

def warm_buckets(pjit_forward, params_spec, buckets, input_shape):
    compiled = {}
    for bucket in buckets:
        spec = jax.ShapeDtypeStruct((bucket,) + input_shape, np.float32)
        compiled[bucket] = pjit_forward.lower(params_spec, spec).compile()
    return compiled

def drive(compiled, params, staged):
    return compiled[staged.shape[0]](params, staged)
"""
    assert _findings(src) == []


def test_silent_on_closure_bound_mode_rules():
    """Mode/axis/rule-table configuration bound in the factory closure
    (never a parameter of the traced forward) cannot re-key a compile."""
    src = """
import jax

def make_serve_forward(apply_fn, mode, rules, shardings):
    axis = rules[mode]

    def forward(params, images):
        return apply_fn(params, images, train=False)

    return jax.jit(forward, in_shardings=shardings, out_shardings=None)
"""
    assert _findings(src) == []


# -- the quantize plane (ISSUE 14) -------------------------------------------


def test_fires_on_scale_constant_into_quantized_bucket_program():
    """The precision plane's cardinal hazard: a PER-PUBLISH quantization
    scale baked into a compiled bucket program as a literal — every hot
    reload's new scales would re-key (recompile) every bucket program.
    Scales must ride the quantized tree as ARGUMENTS."""
    src = """
class QuantEngine:
    def warm(self, fn, qparams_spec, image_spec):
        self._fwd = precompile(fn, qparams_spec, image_spec, program="q")

    def infer(self, qvalues, staged):
        return self._fwd(qvalues, staged, 0.0078125)
"""
    (f,) = _findings(src)
    assert f.symbol.endswith("infer") and "argument 2" in f.message


def test_silent_on_scales_as_arguments_of_the_bucket_program():
    """The shipped shape: the quantized tree — int8 values AND their
    f32 scales — is one pytree argument of the compiled program; a new
    publish swaps the argument, never the executable."""
    src = """
def serve(fn, qparams_spec, image_spec, qparams, staged):
    exe = precompile(fn, qparams_spec, image_spec, program="fwd")
    return exe(qparams, staged)
"""
    assert _findings(src) == []


# -- the whole-program plane (ISSUE 16) --------------------------------------


def test_fires_on_bucket_literal_into_fused_executable():
    """The fused plane is ONE AOT program per bucket; threading the
    bucket size through the compiled program as a scalar argument would
    re-key it per request — the exact steady-state recompile the fusion
    exists to delete. Bucket selection belongs OUTSIDE the executable
    (the per-bucket program table)."""
    src = """
class Engine:
    def warm(self, fused, params_spec, raw_spec):
        self._fused_fwd = precompile(fused, params_spec, raw_spec,
                                     program="fwd.fused")

    def dispatch_fused(self, params, staged):
        return self._fused_fwd(params, staged, 8)
"""
    (f,) = _findings(src)
    assert f.symbol.endswith("dispatch_fused") and "argument 2" in f.message


def test_silent_on_donated_fused_dispatch():
    """The shipped shape: the donated fused program takes arrays only —
    params tree and the staged raw batch; donation changes buffer
    ownership, never shapes, so nothing re-keys."""
    src = """
import jax

def wrap_fused_forward(fused):
    return jax.jit(fused, donate_argnums=(1,))

class Engine:
    def warm(self, fused):
        self._fused_fwd = wrap_fused_forward(fused)

    def dispatch_fused(self, params, staged):
        return self._fused_fwd(params, staged)
"""
    assert _findings(src) == []
