"""Unit tests for the small utility surfaces: StepTimer (the throughput
meter behind the BASELINE metric), rank-aware logging, and mesh
construction/validation (the reference's world-size assertion, ``:351``)."""

import time

import jax
import pytest

from pytorch_distributed_mnist_tpu.parallel.mesh import (
    data_sharding,
    make_mesh,
    replicated_sharding,
)
from pytorch_distributed_mnist_tpu.utils.logging import get_logger, log0
from pytorch_distributed_mnist_tpu.utils.profiling import StepTimer, phase


def test_step_timer_counts_only_measured_phases():
    t = StepTimer(num_chips=2)
    with t.measure(1000):
        time.sleep(0.05)
    time.sleep(0.05)  # unmeasured (the eval/checkpoint span)
    with t.measure(1000):
        time.sleep(0.05)
    # Lower bound only: sleep() guarantees a minimum, not a maximum, so an
    # upper bound would flake on a loaded host. The exclusion of the
    # unmeasured span is pinned by the relative-rate test below.
    assert t.elapsed >= 0.1
    assert t.images == 2000 and t.steps == 2
    assert t.images_per_sec == pytest.approx(2000 / t.elapsed)
    assert t.images_per_sec_per_chip == pytest.approx(t.images_per_sec / 2)
    assert t.last_images_per_sec_per_chip == pytest.approx(
        t.last_images_per_sec / 2)


def test_step_timer_last_phase_rate_is_not_cumulative():
    t = StepTimer(num_chips=1)
    with t.measure(100):
        time.sleep(0.2)  # slow "compile" epoch
    with t.measure(100):
        time.sleep(0.02)
    assert t.last_images_per_sec > t.images_per_sec  # epoch 0 excluded


def test_step_timer_records_time_on_exception():
    t = StepTimer(num_chips=1)
    with pytest.raises(RuntimeError):
        with t.measure(10):
            time.sleep(0.01)
            raise RuntimeError("train blew up")
    assert t.elapsed > 0 and t.images == 10


def test_log0_prints_only_on_process_zero(capsys, monkeypatch):
    log0("hello")
    assert "hello" in capsys.readouterr().out
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    log0("silent")
    assert capsys.readouterr().out == ""
    log0("forced", all_ranks=True)
    assert "forced" in capsys.readouterr().out


def test_get_logger_idempotent_handlers():
    a = get_logger("t_once")
    b = get_logger("t_once")
    assert a is b and len(a.handlers) == 1


def test_make_mesh_validates_shape():
    n = jax.device_count()
    with pytest.raises(ValueError, match="!= device count"):
        make_mesh(("data",), shape=(n + 1,))
    with pytest.raises(ValueError, match="shape is required"):
        make_mesh(("data", "model"))
    mesh = make_mesh(("data",))
    assert mesh.devices.size == n


def test_shardings_shapes():
    mesh = make_mesh(("data",))
    assert data_sharding(mesh).spec == jax.sharding.PartitionSpec("data")
    assert replicated_sharding(mesh).spec == jax.sharding.PartitionSpec()


def test_phase_annotation_is_reentrant_nullcost():
    with phase("train", epoch=0):
        with phase("inner"):
            pass
