"""Native C++ data backend: builds from source, then must agree bit-for-bit
with the NumPy fallback path (same contract, different engine)."""

import shutil
import subprocess

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data import native
from pytorch_distributed_mnist_tpu.data.mnist import (
    MNIST_MEAN,
    MNIST_STD,
    synthetic_dataset,
    write_idx,
)


@pytest.fixture(scope="module", autouse=True)
def built_library():
    import os

    # This module TESTS the native engine: the fallback switch must not
    # turn the whole suite into fixture errors (or trigger a pointless
    # rebuild of a .so that exists). Lift it for the module and re-probe.
    switched_off = os.environ.get("TPUMNIST_NATIVE", "") == "0"
    if switched_off:
        del os.environ["TPUMNIST_NATIVE"]
        native._lib = None  # force re-probe without the switch
    try:
        if not native.available():
            if shutil.which("make") is None or shutil.which("g++") is None:
                pytest.skip("no native toolchain")
            import pytorch_distributed_mnist_tpu as pkg

            root = os.path.dirname(
                os.path.dirname(os.path.abspath(pkg.__file__)))
            subprocess.run(["make", "-C", os.path.join(root, "native")],
                           check=True)
            native._lib = None  # force re-probe
        assert native.available()
        yield
    finally:
        if switched_off:
            os.environ["TPUMNIST_NATIVE"] = "0"
            native._lib = None


def test_version():
    # v3 added the serve-dispatch entry points (tm_pad_copy,
    # tm_cast_f32); v4 the int8 serving plane's quant/dequant
    # (tm_quant_i8, tm_dequant_f32).
    assert native._load().tm_version() == 4


@pytest.mark.parametrize("stale_version", [2, 3])
def test_stale_library_rejected_whole(monkeypatch, stale_version):
    """A stale .so (TPU_MNIST_NATIVE_LIB override, or a never-re-made
    build) must be rejected WHOLE: a pre-v3 fused tm_normalize is ~1ulp
    off the bits every equivalence/trajectory pin asserts, and a pre-v4
    library lacks the quant/dequant entry points the int8 serving plane
    stages through — a partial surface would silently mix native and
    fallback per call site. Stale -> fallback, per DESIGN.md 4b."""
    class _Sym:
        def __init__(self, ret=None):
            self._ret = ret

        def __call__(self, *args):
            return self._ret

    class _StubLib:
        def __init__(self):
            for name in ("tm_idx_load", "tm_free", "tm_normalize",
                         "tm_gather", "tm_pad_copy", "tm_cast_f32"):
                setattr(self, name, _Sym())
            self.tm_version = _Sym(stale_version)

    monkeypatch.setattr(native, "_find_library", lambda: "stub.so")
    monkeypatch.setattr(native.ctypes, "CDLL", lambda path: _StubLib())
    native._lib = None
    try:
        assert native._load() is None
        assert not native.available()
    finally:
        native._lib = None  # re-probe the real library for later tests


def test_parse_idx_zero_length_dim(tmp_path):
    # (0, 28, 28): empty file must parse to an empty array, not crash.
    arr = np.zeros((0, 28, 28), np.uint8)
    p = str(tmp_path / "empty-idx3-ubyte")
    write_idx(p, arr)
    got = native.parse_idx(p)
    assert got is not None and got.shape == (0, 28, 28)


def test_parse_idx_truncated_payload(tmp_path):
    # Header promises more bytes than the file holds -> clean None.
    import struct

    p = str(tmp_path / "trunc-idx3-ubyte")
    with open(p, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", 100, 28, 28))
        f.write(b"\x00" * 10)  # far short of 100*28*28
    assert native.parse_idx(p) is None


def test_parse_idx_huge_ndim_byte(tmp_path):
    # data[3]=0xFF on a short file: must return None, not read out of bounds.
    p = str(tmp_path / "badndim")
    with open(p, "wb") as f:
        f.write(b"\x00\x00\x08\xff\x01")
    assert native.parse_idx(p) is None


def test_parse_idx_matches_numpy(tmp_path):
    arr = np.random.default_rng(0).integers(0, 256, (7, 28, 28)).astype(np.uint8)
    p = str(tmp_path / "imgs-idx3-ubyte")
    write_idx(p, arr)
    got = native.parse_idx(p)
    np.testing.assert_array_equal(got, arr)


def test_parse_idx_gzip(tmp_path):
    import gzip

    arr = np.arange(256, dtype=np.uint8)
    raw = str(tmp_path / "x-idx1-ubyte")
    write_idx(raw, arr)
    with open(raw, "rb") as f, gzip.open(raw + ".gz", "wb") as g:
        g.write(f.read())
    np.testing.assert_array_equal(native.parse_idx(raw + ".gz"), arr)


def test_parse_idx_bad_file_returns_none(tmp_path):
    p = str(tmp_path / "bad")
    with open(p, "wb") as f:
        f.write(b"\x01\x02garbage")
    assert native.parse_idx(p) is None


def test_normalize_matches_numpy_bitwise():
    """The C kernel runs the fallback's exact float32 op sequence
    (div/sub/div, not a fused scale*x+offset), so the two engines agree
    to the BIT on every representable input — which engine normalized a
    batch can never show up in a trajectory. Exhaustive over all 256
    uint8 values."""
    images = np.arange(256, dtype=np.uint8).repeat(16).reshape(-1, 16, 4)
    got = native.normalize_images(images, MNIST_MEAN, MNIST_STD, workers=4)
    want = ((images.astype(np.float32) / 255.0 - MNIST_MEAN)
            / MNIST_STD)[..., None]
    np.testing.assert_array_equal(
        got.view(np.uint32), want.view(np.uint32))


def test_gather_matches_numpy_fancy_indexing():
    rng = np.random.default_rng(1)
    images = rng.normal(size=(50, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, 50).astype(np.int32)
    idx = rng.integers(0, 50, (4, 8))
    got_imgs, got_lbls = native.gather_epoch(images, labels, idx, workers=3)
    np.testing.assert_array_equal(got_imgs, images[idx.reshape(-1)].reshape(4, 8, 28, 28, 1))
    np.testing.assert_array_equal(got_lbls, labels[idx.reshape(-1)].reshape(4, 8))


def test_gather_out_of_bounds_returns_none():
    images = np.zeros((5, 2), np.float32)
    labels = np.zeros(5, np.int32)
    idx = np.array([[0, 99]])
    assert native.gather_epoch(images, labels, idx) is None


def test_gather_matches_numpy_bitwise():
    """The epoch gather is a row copy: bitwise by construction, pinned
    so a future 'optimization' can't quietly change that."""
    rng = np.random.default_rng(8)
    images = rng.normal(size=(64, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, 64).astype(np.int32)
    idx = rng.integers(0, 64, (3, 16))
    got_imgs, got_lbls = native.gather_epoch(images, labels, idx, workers=4)
    want = images[idx.reshape(-1)].reshape(3, 16, 28, 28, 1)
    np.testing.assert_array_equal(
        got_imgs.view(np.uint32), want.view(np.uint32))
    np.testing.assert_array_equal(got_lbls, labels[idx.reshape(-1)].reshape(3, 16))


# -- v3 serve-dispatch entry points (ISSUE 6) --------------------------------


def _numpy_pad(dst, src):
    dst[:len(src)] = src
    dst[len(src):] = 0.0


@pytest.mark.parametrize("rows", [0, 1, 100, 128])
def test_pad_into_matches_numpy_bitwise(rows):
    """The staging fill (copy + zero tail) the serve dispatch runs per
    batch: native and the engine's NumPy fallback write identical
    bytes, including the degenerate empty and exact-fit cases."""
    rng = np.random.default_rng(rows)
    src = rng.normal(size=(rows, 28, 28, 1)).astype(np.float32)
    got = np.full((128, 28, 28, 1), np.nan, np.float32)
    want = np.full((128, 28, 28, 1), np.nan, np.float32)
    assert native.pad_into(got, src, workers=4)
    _numpy_pad(want, src)
    np.testing.assert_array_equal(got.view(np.uint32), want.view(np.uint32))


def test_pad_into_rejects_bad_layouts():
    """Anything the C kernel can't handle safely returns False — the
    caller runs the NumPy fallback, never a corrupted copy."""
    dst = np.zeros((8, 4), np.float32)
    assert not native.pad_into(dst, np.zeros((9, 4), np.float32))  # src > dst
    assert not native.pad_into(dst, np.zeros((2, 5), np.float32))  # row shape
    assert not native.pad_into(dst, np.zeros((2, 4), np.float64))  # dtype
    assert not native.pad_into(
        dst, np.zeros((2, 8), np.float32)[:, ::2])  # non-contiguous src
    assert not native.pad_into(np.zeros((8, 4), np.float64),
                               np.zeros((2, 4), np.float32))  # dst dtype
    frozen = np.zeros((8, 4), np.float32)
    frozen.flags.writeable = False
    # A frozen dst must fall back (where NumPy's slice-assign raises),
    # never be scribbled through the raw pointer.
    assert not native.pad_into(frozen, np.zeros((2, 4), np.float32))


def test_cast_f32_matches_numpy_bitwise():
    """float64 -> float32 rounds to nearest even in both engines; the
    serve preprocess path may take either without a bit of drift."""
    rng = np.random.default_rng(11)
    arr = rng.normal(size=(129, 28, 28, 1)) * 1e3
    got = native.cast_f32(arr, workers=4)
    want = arr.astype(np.float32)
    np.testing.assert_array_equal(got.view(np.uint32), want.view(np.uint32))


def test_cast_f32_rejects_other_dtypes():
    assert native.cast_f32(np.zeros((2, 2), np.float32)) is None
    assert native.cast_f32(np.zeros((2, 2), np.int64)) is None
    assert native.cast_f32(
        np.zeros((2, 8), np.float64)[:, ::2]) is None  # non-contiguous


def test_quant_i8_matches_numpy_bitwise():
    """float32 -> int8 symmetric quantization: the native kernel's
    round-to-nearest-even via the precomputed f32 reciprocal is
    BITWISE-identical to the NumPy fallback expression the serving
    plane uses (serve/programs.py) — which engine quantized a batch can
    never show up in the logits."""
    rng = np.random.default_rng(12)
    arr = (rng.normal(size=(65, 28, 28, 1)) * 2.5).astype(np.float32)
    scale = np.float32(np.abs(arr).max() / np.float32(127.0))
    got = native.quant_i8(arr, float(scale), workers=4)
    inv = np.float32(1.0) / scale
    want = np.clip(np.rint(arr * inv), -127, 127).astype(np.int8)
    np.testing.assert_array_equal(got, want)
    # Ties (x/scale exactly .5) round to even in both engines.
    half = (np.arange(-8, 8, dtype=np.float32) + np.float32(0.5))
    got_half = native.quant_i8(half, 1.0, workers=1)
    want_half = np.clip(np.rint(half), -127, 127).astype(np.int8)
    np.testing.assert_array_equal(got_half, want_half)


def test_quant_i8_non_finite_pinned():
    """NaN quantizes to 0 and ±inf clips to ±127 in BOTH engines
    (static_cast of NaN is UB in C; NaN.astype(int8) is platform-
    defined in NumPy — both paths pin the same explicit values, so a
    client-supplied non-finite pixel can't make the engines diverge)."""
    x = np.array([np.nan, np.inf, -np.inf, 1.0], np.float32)
    got = native.quant_i8(x, 1.0, workers=1)
    np.testing.assert_array_equal(got, np.array([0, 127, -127, 1],
                                                np.int8))


def test_quant_i8_rejects_bad_inputs():
    assert native.quant_i8(np.zeros((2, 2), np.float64), 1.0) is None
    assert native.quant_i8(np.zeros((2, 2), np.float32), 0.0) is None
    assert native.quant_i8(np.zeros((2, 2), np.float32), -1.0) is None
    assert native.quant_i8(
        np.zeros((2, 8), np.float32)[:, ::2], 1.0) is None  # non-contiguous


def test_dequant_f32_matches_numpy_bitwise():
    """int8 -> float32 dequantization (q * scale) is one f32 multiply
    per element in both engines — bitwise-identical."""
    q = np.arange(-127, 128, dtype=np.int8).reshape(5, 51)
    scale = np.float32(0.0123)
    got = native.dequant_f32(q, float(scale), workers=2)
    want = q.astype(np.float32) * scale
    np.testing.assert_array_equal(got.view(np.uint32), want.view(np.uint32))


def test_dequant_f32_rejects_other_dtypes():
    assert native.dequant_f32(np.zeros((2, 2), np.uint8), 1.0) is None
    assert native.dequant_f32(np.zeros((2, 2), np.float32), 1.0) is None


def test_tpumnist_native_zero_disables_library(monkeypatch):
    """TPUMNIST_NATIVE=0 is the explicit in-process fallback switch the
    input bench uses to time the NumPy path with the library present."""
    monkeypatch.setenv("TPUMNIST_NATIVE", "0")
    monkeypatch.setattr(native, "_lib", None)
    assert not native.available()
    assert native.cast_f32(np.zeros((2, 2), np.float64)) is None
    assert not native.pad_into(np.zeros((4, 2), np.float32),
                               np.zeros((2, 2), np.float32))
    assert native.quant_i8(np.zeros((2, 2), np.float32), 1.0) is None
    assert native.dequant_f32(np.zeros((2, 2), np.int8), 1.0) is None
    monkeypatch.delenv("TPUMNIST_NATIVE")
    monkeypatch.setattr(native, "_lib", None)
    assert native.available()


def _numpy_mode(monkeypatch):
    monkeypatch.setenv("TPUMNIST_NATIVE", "0")
    monkeypatch.setattr(native, "_lib", None)


def test_engine_preprocess_native_equals_numpy_bitwise(monkeypatch):
    """THE dispatch-path equivalence pin: InferenceEngine.preprocess on
    raw uint8 and on float64 inputs returns bit-identical stacks
    whether the native library or the NumPy fallback runs."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
    from pytorch_distributed_mnist_tpu.train.state import create_train_state

    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    engine = InferenceEngine(model.apply, state.params)
    raw, _ = synthetic_dataset(33, seed=6)
    f64 = np.random.default_rng(6).normal(size=(33, 28, 28, 1))

    nat_raw = engine.preprocess(raw)
    nat_f64 = engine.preprocess(f64)
    _numpy_mode(monkeypatch)
    np_raw = engine.preprocess(raw)
    np_f64 = engine.preprocess(f64)
    np.testing.assert_array_equal(nat_raw.view(np.uint32),
                                  np_raw.view(np.uint32))
    np.testing.assert_array_equal(nat_f64.view(np.uint32),
                                  np_f64.view(np.uint32))


def test_engine_predict_native_equals_numpy_bitwise(monkeypatch):
    """End-to-end dispatch: a padded (non-exact-bucket) predict returns
    bit-identical logits with the native staging fill on or off."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
    from pytorch_distributed_mnist_tpu.train.state import create_train_state

    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    engine = InferenceEngine(model.apply, state.params)
    raw, _ = synthetic_dataset(13, seed=7)  # pads 13 -> bucket 32
    stack = engine.preprocess(raw)
    nat_logits = engine.logits(stack)
    _numpy_mode(monkeypatch)
    np_logits = engine.logits(stack)
    np.testing.assert_array_equal(
        np.asarray(nat_logits).view(np.uint32),
        np.asarray(np_logits).view(np.uint32))


@pytest.mark.slow
def test_library_builds_from_source(tmp_path):
    """The committed source must actually compile (make -C native) and
    export the v3 surface — otherwise the .so in the tree can silently
    rot while every test runs against the stale binary. Builds in a
    copy so the checked-in library is never raced."""
    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("no native toolchain")
    import ctypes
    import os

    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    build = tmp_path / "native"
    shutil.copytree(src, build)
    os.remove(build / "libtpumnist_native.so")
    subprocess.run(["make", "-C", str(build)], check=True,
                   capture_output=True)
    lib = ctypes.CDLL(str(build / "libtpumnist_native.so"))
    lib.tm_version.restype = ctypes.c_int
    assert lib.tm_version() == 4
    for sym in ("tm_pad_copy", "tm_cast_f32", "tm_normalize", "tm_gather",
                "tm_quant_i8", "tm_dequant_f32"):
        assert hasattr(lib, sym)


def test_loader_native_and_numpy_stacked_epoch_agree():
    from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
    from pytorch_distributed_mnist_tpu.data.mnist import normalize_images

    images, labels = synthetic_dataset(120, seed=5)
    x = normalize_images(images)
    loader = MNISTDataLoader(x, labels.astype(np.int32), batch_size=32, train=True, seed=9)
    loader.set_sample_epoch(2)
    ep_native = loader.stacked_epoch()

    lib, native._lib = native._lib, None  # simulate missing library
    try:
        import unittest.mock as mock

        with mock.patch.object(native, "_find_library", return_value=None):
            ep_numpy = loader.stacked_epoch()
    finally:
        native._lib = lib
    for k in ("image", "label", "mask"):
        np.testing.assert_array_equal(ep_native[k], ep_numpy[k])
