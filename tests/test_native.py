"""Native C++ data backend: builds from source, then must agree bit-for-bit
with the NumPy fallback path (same contract, different engine)."""

import shutil
import subprocess

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data import native
from pytorch_distributed_mnist_tpu.data.mnist import (
    MNIST_MEAN,
    MNIST_STD,
    synthetic_dataset,
    write_idx,
)


@pytest.fixture(scope="module", autouse=True)
def built_library():
    if not native.available():
        if shutil.which("make") is None or shutil.which("g++") is None:
            pytest.skip("no native toolchain")
        import pytorch_distributed_mnist_tpu as pkg
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(pkg.__file__)))
        subprocess.run(["make", "-C", os.path.join(root, "native")], check=True)
        native._lib = None  # force re-probe
    assert native.available()


def test_version():
    assert native._load().tm_version() == 2


def test_parse_idx_zero_length_dim(tmp_path):
    # (0, 28, 28): empty file must parse to an empty array, not crash.
    arr = np.zeros((0, 28, 28), np.uint8)
    p = str(tmp_path / "empty-idx3-ubyte")
    write_idx(p, arr)
    got = native.parse_idx(p)
    assert got is not None and got.shape == (0, 28, 28)


def test_parse_idx_truncated_payload(tmp_path):
    # Header promises more bytes than the file holds -> clean None.
    import struct

    p = str(tmp_path / "trunc-idx3-ubyte")
    with open(p, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", 100, 28, 28))
        f.write(b"\x00" * 10)  # far short of 100*28*28
    assert native.parse_idx(p) is None


def test_parse_idx_huge_ndim_byte(tmp_path):
    # data[3]=0xFF on a short file: must return None, not read out of bounds.
    p = str(tmp_path / "badndim")
    with open(p, "wb") as f:
        f.write(b"\x00\x00\x08\xff\x01")
    assert native.parse_idx(p) is None


def test_parse_idx_matches_numpy(tmp_path):
    arr = np.random.default_rng(0).integers(0, 256, (7, 28, 28)).astype(np.uint8)
    p = str(tmp_path / "imgs-idx3-ubyte")
    write_idx(p, arr)
    got = native.parse_idx(p)
    np.testing.assert_array_equal(got, arr)


def test_parse_idx_gzip(tmp_path):
    import gzip

    arr = np.arange(256, dtype=np.uint8)
    raw = str(tmp_path / "x-idx1-ubyte")
    write_idx(raw, arr)
    with open(raw, "rb") as f, gzip.open(raw + ".gz", "wb") as g:
        g.write(f.read())
    np.testing.assert_array_equal(native.parse_idx(raw + ".gz"), arr)


def test_parse_idx_bad_file_returns_none(tmp_path):
    p = str(tmp_path / "bad")
    with open(p, "wb") as f:
        f.write(b"\x01\x02garbage")
    assert native.parse_idx(p) is None


def test_normalize_matches_numpy():
    images, _ = synthetic_dataset(257, seed=3)
    got = native.normalize_images(images, MNIST_MEAN, MNIST_STD, workers=4)
    want = (images.astype(np.float32) / 255.0 - MNIST_MEAN) / MNIST_STD
    np.testing.assert_allclose(got, want[..., None], rtol=1e-6, atol=1e-7)


def test_gather_matches_numpy_fancy_indexing():
    rng = np.random.default_rng(1)
    images = rng.normal(size=(50, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, 50).astype(np.int32)
    idx = rng.integers(0, 50, (4, 8))
    got_imgs, got_lbls = native.gather_epoch(images, labels, idx, workers=3)
    np.testing.assert_array_equal(got_imgs, images[idx.reshape(-1)].reshape(4, 8, 28, 28, 1))
    np.testing.assert_array_equal(got_lbls, labels[idx.reshape(-1)].reshape(4, 8))


def test_gather_out_of_bounds_returns_none():
    images = np.zeros((5, 2), np.float32)
    labels = np.zeros(5, np.int32)
    idx = np.array([[0, 99]])
    assert native.gather_epoch(images, labels, idx) is None


def test_loader_native_and_numpy_stacked_epoch_agree():
    from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
    from pytorch_distributed_mnist_tpu.data.mnist import normalize_images

    images, labels = synthetic_dataset(120, seed=5)
    x = normalize_images(images)
    loader = MNISTDataLoader(x, labels.astype(np.int32), batch_size=32, train=True, seed=9)
    loader.set_sample_epoch(2)
    ep_native = loader.stacked_epoch()

    lib, native._lib = native._lib, None  # simulate missing library
    try:
        import unittest.mock as mock

        with mock.patch.object(native, "_find_library", return_value=None):
            ep_numpy = loader.stacked_epoch()
    finally:
        native._lib = lib
    for k in ("image", "label", "mask"):
        np.testing.assert_array_equal(ep_native[k], ep_numpy[k])
