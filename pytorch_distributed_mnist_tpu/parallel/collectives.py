"""Explicit collectives: the shard_map/psum data-parallel step.

This is the TPU-native analog of what ``DistributedDataParallel`` does under
the hood in the reference (``/root/reference/multi_proc_single_gpu.py:
188-189``): every backward pass fires a gradient AllReduce (NCCL there, XLA
``psum`` over the mesh's ``data`` axis here), then each replica applies the
identical averaged update (``:91-92``).

Two interchangeable implementations of the same semantics live in this
framework:

- the **auto (GSPMD)** path in ``train/steps.py``: write the global-batch
  program, give jit the shardings, and XLA's sharding propagation inserts
  the AllReduce — idiomatic, and what production code should use;
- the **explicit** path here: ``jax.shard_map`` gives each device its local
  shard and the gradient reduction is a visible ``lax.pmean`` — the direct
  DDP translation, kept because it makes the communication auditable and
  the DDP-equivalence property directly testable (SURVEY.md section 4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy
from pytorch_distributed_mnist_tpu.ops.metrics import MetricState


def make_explicit_dp_train_step(mesh: Mesh, axis: str = "data"):
    """Build a donated, jitted DP train step with an explicit psum.

    Returns ``step(state, batch) -> (state, MetricState)`` where ``batch`` is
    a dict of global arrays sharded on ``axis`` along dim 0. Inside the
    per-device body the batch is local; gradients are ``pmean``-ed across the
    axis exactly as DDP averages rank gradients, so the update equals the
    global-batch-mean gradient step (reference loss-mean semantics, ``:88``).
    """

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def sharded_body(state, batch):
        images, labels = batch["image"], batch["label"]
        mask = batch.get("mask")

        def loss_fn(params):
            logits = state.apply_fn(params, images, train=True)
            return cross_entropy(logits, labels, mask), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        # DDP semantics: average gradients of per-replica mean losses.
        grads = lax.pmean(grads, axis)
        new_state = state.apply_gradients(grads)
        hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        if mask is None:
            n = jnp.asarray(labels.shape[0], jnp.float32)
        else:
            n = jnp.sum(mask.astype(jnp.float32))
            hit = hit * mask
        metrics = MetricState(
            loss_sum=lax.psum(loss * n, axis),
            correct=lax.psum(jnp.sum(hit), axis),
            count=lax.psum(n, axis),
        )
        return new_state, metrics

    return jax.jit(sharded_body, donate_argnums=(0,))


def make_explicit_dp_eval_step(mesh: Mesh, axis: str = "data"):
    """Explicit-shard_map eval step, the forward-only sibling of the train
    step above. Explicit mode must be explicit END TO END: a GSPMD eval
    step alongside a shard_map train step would silently re-introduce the
    auto path (and, with ``--loss fused``, gather the batch for a pallas
    call the shard_map body hands local shards instead)."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    def sharded_body(state, batch):
        images, labels = batch["image"], batch["label"]
        mask = batch.get("mask")
        logits = state.apply_fn(state.params, images, train=False)
        loss = cross_entropy(logits, labels, mask)
        hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        if mask is None:
            n = jnp.asarray(labels.shape[0], jnp.float32)
        else:
            n = jnp.sum(mask.astype(jnp.float32))
            hit = hit * mask
        return MetricState(
            loss_sum=lax.psum(loss * n, axis),
            correct=lax.psum(jnp.sum(hit), axis),
            count=lax.psum(n, axis),
        )

    return jax.jit(sharded_body)
