"""Ulysses-style sequence parallelism: all-to-all head resharding.

The second of the two sequence-parallel strategies (the brief's "ring
attention OR all-to-all sequence/context parallelism"); the reference has
neither (no attention at all, ``/root/reference/multi_proc_single_gpu.py:
119-126``, SURVEY.md section 2c).

Scheme: activations arrive sequence-sharded ``(B, T/n, H, D)``. One
``lax.all_to_all`` re-shards heads instead of tokens -> ``(B, T, H/n, D)``;
each device then runs plain dense attention over the FULL sequence for its
own head subset (attention is embarrassingly parallel over heads); a second
all-to-all restores sequence sharding. Two all-to-alls per attention call
ride ICI; compute is untouched dense attention, which XLA already maps
perfectly onto the MXU — the tradeoff vs the ring (``parallel/ring.py``) is
O(T^2) score memory per device but fewer, larger collectives.

Requires ``num_heads % axis_size == 0``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_mnist_tpu.ops.attention import full_attention


def ulysses_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    local_attention: Optional[Callable] = None,
) -> jnp.ndarray:
    """Per-device body; token axis sharded on ``axis_name`` (inside shard_map).

    ``local_attention`` is the per-device kernel over the full sequence /
    local heads (default: dense ``full_attention``). Because Ulysses hands
    each device the WHOLE sequence for its head subset, the Pallas flash
    kernel slots in directly — unlike the ring, whose blockwise online
    softmax supplies its own attention. This is how ``--attention flash``
    composes with ``--sequence-parallel-impl ulysses`` from the CLI.
    """
    n = lax.axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(
            f"num_heads {q.shape[2]} not divisible by axis size {n}"
        )
    attn = local_attention if local_attention is not None else full_attention

    def to_heads(x):  # (B, T/n, H, D) -> (B, T, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_tokens(x):  # (B, T, H/n, D) -> (B, T/n, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    o = attn(to_heads(q), to_heads(k), to_heads(v), causal=causal, scale=scale)
    return to_tokens(o)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "seq",
    batch_axis: Optional[str] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    local_attention: Optional[Callable] = None,
) -> jnp.ndarray:
    """Ulysses attention on GLOBAL ``(B, T, H, D)`` arrays; T sharded on ``axis``.

    ``batch_axis`` composes with data parallelism (B sharded); the head axis
    cannot also be mesh-sharded here — Ulysses itself re-shards heads.
    """
    spec = P(batch_axis, axis, None, None)
    fn = partial(ulysses_attention_local, axis_name=axis, causal=causal,
                 scale=scale, local_attention=local_attention)
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
