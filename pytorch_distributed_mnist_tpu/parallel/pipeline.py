"""Pipeline parallelism: GPipe-style microbatched stages over a mesh axis.

SURVEY.md section 2c marks PP ABSENT in the reference (no stage
partitioning, no microbatching); the N-D mesh design carries it anyway.
TPU-idiomatic formulation: the pipeline is a *collective program*, not a
scheduler — ``shard_map`` gives every device its stage's weights (stacked
stage params sharded on the ``stage`` axis), and one ``lax.scan`` runs
``M + S - 1`` ticks in lockstep SPMD. Each tick every device applies its
stage to the activation it holds and passes the result one hop to the next
stage with ``lax.ppermute`` (a neighbor ICI transfer, exactly like the ring
in ``parallel/ring.py``). The first S-1 ticks are the classic GPipe fill
bubble, the last S-1 the drain bubble: utilization M / (M + S - 1).

Differentiable end to end (``scan`` + ``ppermute`` have transposes), so a
jitted train step backprops through the pipeline with the reverse
communication pattern — no hand-written backward schedule.

Restrictions: every *pipelined* stage has the same pytree structure and
the same activation shape in and out — which fits any repeated-block
architecture (each stage = ``depth // S`` transformer blocks; see
``parallel/pipeline_vit.py`` for the full embed -> blocks -> head model,
where the ragged-shape embed/head run replicated outside the pipe);
number of stages == size of the ``stage`` axis; microbatch count must
divide the (per-dataslice) batch. ``data_axis`` composes DP x PP on one
mesh: the batch stays sharded on ``data`` while stages ride ``stage``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage pytrees into one pytree with leading S dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "stage",
    num_microbatches: Optional[int] = None,
    data_axis: Optional[str] = None,
    param_specs=None,
) -> jnp.ndarray:
    """Run ``x`` through S pipelined stages: ``y = f_S(... f_1(x))``.

    ``stage_fn(params, h) -> h`` with identical in/out shape;
    ``stage_params`` leaves have leading dim S (use ``stack_stage_params``),
    sharded on ``axis``. ``x`` is the (global) batch, microbatched on dim 0.
    With ``data_axis`` the batch dim stays sharded on that mesh axis (each
    data slice runs its own pipeline flow over the same stage weights);
    microbatching then applies to the per-slice batch. Returns the
    full-batch output, replicated over ``axis``.

    ``param_specs`` (a PartitionSpec pytree matching ``stage_params``)
    overrides the default ``P(axis)``-on-dim-0 layout — the PP x TP
    composition (``parallel/pipeline_tp.py``) shards block weights on the
    ``model`` mesh axis *in addition to* the stage dim, and its
    ``stage_fn`` closes the partial sums with psums over that axis; this
    function's scan/ppermute schedule is axis-local and unchanged.
    """
    n_stages = mesh.shape[axis]
    m = num_microbatches or n_stages
    data_size = mesh.shape[data_axis] if data_axis else 1
    if x.shape[0] % data_size:
        raise ValueError(
            f"global batch {x.shape[0]} not divisible by data axis "
            f"{data_axis}={data_size}"
        )
    batch = x.shape[0] // data_size
    if batch % m:
        raise ValueError(
            f"per-dataslice batch {batch} not divisible by microbatches {m}"
        )

    def body(params_local, xg):
        s = lax.axis_index(axis)
        # params_local leaves are (1, ...): this device's stage.
        p = jax.tree_util.tree_map(lambda a: a[0], params_local)
        xm = xg.reshape((m, batch // m) + xg.shape[1:])
        ticks = m + n_stages - 1
        mb_shape = xm.shape[1:]

        def tick(carry, t):
            buf, outs = carry
            # Stage 0 injects microbatch t (clamped; late ticks are bubble).
            inj = lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
            )
            h = jnp.where(s == 0, inj, buf)
            h = stage_fn(p, h)
            # Last stage retires microbatch t - (S - 1).
            widx = t - (n_stages - 1)
            write = (s == n_stages - 1) & (widx >= 0)
            updated = lax.dynamic_update_index_in_dim(
                outs, h.astype(outs.dtype), jnp.clip(widx, 0, m - 1), axis=0
            )
            outs = jnp.where(write, updated, outs)
            # Hand the activation to the next stage (no wraparound: the
            # last stage's output leaves the pipe via ``outs``).
            buf = lax.ppermute(
                h, axis, perm=[(i, i + 1) for i in range(n_stages - 1)]
            )
            return (buf, outs), None

        init = (
            jnp.zeros(mb_shape, xg.dtype),
            jnp.zeros((m,) + mb_shape, xg.dtype),
        )
        (_, outs), _ = lax.scan(tick, init, jnp.arange(ticks))
        # Only the last stage holds real outputs; psum replicates them so
        # the shard_map output can be unsharded on ``axis``.
        outs = lax.psum(jnp.where(s == n_stages - 1, outs, 0.0), axis)
        return outs.reshape((batch,) + xg.shape[1:])

    spec_params = param_specs if param_specs is not None else (
        jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    )
    x_spec = P(data_axis) if data_axis else P()
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec_params, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)


def sequential_apply(stage_fn: Callable, stage_params, x: jnp.ndarray):
    """Reference semantics: the same stages applied one after another."""
    s = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    for i in range(s):
        p = jax.tree_util.tree_map(lambda a: a[i], stage_params)
        x = stage_fn(p, x)
    return x
