"""Pipeline-parallel ViT: embed -> pipelined transformer blocks -> head.

Lifts ``parallel/pipeline.py``'s uniform-stage GPipe machinery onto a real
model from the zoo (VERDICT round 1: the pipeline only ever ran a toy MLP).
The shape-ragged ends — patch embedding ((B, 28, 28, 1) -> (B, T, C)) and
the pooling head ((B, T, C) -> (B, 10)) — run replicated over the ``stage``
axis (they are a fraction of a percent of the FLOPs); the shape-uniform
middle, ``depth`` transformer blocks, is exactly what the GPipe scan
pipelines: stage ``s`` holds blocks ``[s*k, (s+1)*k)`` (``k = depth / S``)
as one stacked pytree sharded on ``stage``, and applies them with a local
``lax.scan``.

The reference has no pipeline parallelism at all (SURVEY.md section 2c:
PP ABSENT, the model is one Linear, ``/root/reference/
multi_proc_single_gpu.py:119-126``); this exists because the N-D mesh
design makes PP a layout + one collective program rather than a scheduler.

Param layout: a *pipelined* train state stores the ViT params re-grouped as

    {"embed": {embed, pos_embed}, "blocks": <one block tree, leaves with
     leading (depth,) dim>, "head": {ln_f, head}}

so the PP sharding rule is a single statement — every ``blocks`` leaf is
``P("stage")`` on dim 0 — and Adam moments inherit it through the pytree
mirror. ``split_vit_params`` / ``merge_vit_params`` convert to/from the
standard flax tree (bitwise: pure stack/unstack), pinned by
tests/test_pipeline_vit.py's forward-equality test.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import flax.linen as nn

from pytorch_distributed_mnist_tpu.models.attention import (
    TransformerBlock,
    VisionTransformer,
    patchify,
)
from pytorch_distributed_mnist_tpu.parallel.pipeline import pipeline_apply

__all__ = [
    "split_vit_params",
    "merge_vit_params",
    "make_pipelined_vit_apply",
    "make_stage_forward_fns",
    "pipeline_stage_rules",
    "pipelined_state_sharding",
    "create_pipelined_vit_state",
    "split_stage_params",
]


def split_vit_params(params):
    """Standard ViT flax tree -> pipelined {embed, blocks, head} layout."""
    p = params["params"]
    depth = sum(1 for k in p if k.startswith("block"))
    if not depth:
        # A blockless tree (wrong model family) would otherwise die in
        # tree_map with an argument-count error; name the real problem.
        raise ValueError(
            f"params have no block* layers to pipeline (keys: "
            f"{sorted(p)})")
    blocks = [p[f"block{i}"] for i in range(depth)]
    stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *blocks)
    return {
        "embed": {"embed": p["embed"], "pos_embed": p["pos_embed"]},
        "blocks": stacked,
        "head": {"ln_f": p["ln_f"], "head": p["head"]},
    }


def merge_vit_params(split):
    """Pipelined layout -> standard flax tree (exact inverse of split)."""
    depth = jax.tree_util.tree_leaves(split["blocks"])[0].shape[0]
    p = {
        "embed": split["embed"]["embed"],
        "pos_embed": split["embed"]["pos_embed"],
        "ln_f": split["head"]["ln_f"],
        "head": split["head"]["head"],
    }
    for i in range(depth):
        p[f"block{i}"] = jax.tree_util.tree_map(
            lambda a, i=i: a[i], split["blocks"]
        )
    return {"params": p}


def make_pipelined_vit_apply(
    model: VisionTransformer,
    mesh: Mesh,
    *,
    axis: str = "stage",
    data_axis: Optional[str] = None,
    num_microbatches: Optional[int] = None,
):
    """Return ``apply_fn(split_params, x, train=False) -> logits``.

    Drop-in for ``model.apply`` in a TrainState (same signature the train
    steps call), but the transformer blocks execute as an S-stage GPipe
    over ``mesh[axis]`` with the batch optionally sharded on ``data_axis``.
    """
    n_stages = mesh.shape[axis]
    if model.depth % n_stages:
        raise ValueError(
            f"vit depth {model.depth} not divisible by {n_stages} pipeline "
            f"stages"
        )
    cd = model.compute_dtype
    embed_mod = nn.Dense(model.embed_dim, dtype=cd)
    block_mod = TransformerBlock(
        model.num_heads, model.mlp_ratio, model.attention_fn, cd
    )
    ln_mod = nn.LayerNorm(dtype=cd)
    head_mod = nn.Dense(model.num_classes, dtype=cd)

    def stage_fn(stage_blocks, h):
        # stage_blocks: this stage's k blocks, leaves (k, ...); apply in
        # order with a scan so the stage body stays a single trace.
        def body(h, bp):
            return block_mod.apply({"params": bp}, h), None

        if model.remat:
            # Same contract as the non-pipelined model's nn.remat blocks:
            # per-block activations recompute in backward, so each stage
            # holds one block's activations instead of k.
            body = jax.checkpoint(body)
        h, _ = lax.scan(body, h, stage_blocks)
        return h

    def apply_fn(split, x, *, train: bool = False):
        del train
        h = patchify(x, model.patch_size, cd)
        h = embed_mod.apply({"params": split["embed"]["embed"]}, h)
        h = h + split["embed"]["pos_embed"].astype(cd)
        # leaves (depth, ...) sharded on dim 0 -> (S, k, ...): a local
        # reshape of the sharded dim (depth % S == 0 checked above).
        staged = jax.tree_util.tree_map(
            lambda a: a.reshape((n_stages, a.shape[0] // n_stages)
                                + a.shape[1:]),
            split["blocks"],
        )
        h = pipeline_apply(
            stage_fn, staged, h, mesh=mesh, axis=axis,
            num_microbatches=num_microbatches, data_axis=data_axis,
        )
        h = ln_mod.apply({"params": split["head"]["ln_f"]}, h)
        h = jnp.mean(h, axis=1)
        h = head_mod.apply({"params": split["head"]["head"]}, h)
        return h.astype(jnp.float32)

    return apply_fn


def pipeline_stage_rules(axis: str = "stage"):
    """Callable rule table for the serve registry (``leaf_spec`` accepts
    callables): every leaf under ``blocks`` is ``P(axis)`` on dim 0 — the
    stacked depth dim, which is the stage seam — everything else
    replicated. The divisibility walk ``serve/programs.py::
    validate_serve_mode`` runs over these reduces to exactly
    "depth % stages == 0", the same constraint
    ``make_pipelined_vit_apply`` enforces for training."""

    def rules(path):
        keys = [str(getattr(k, "key", getattr(k, "name", None)))
                for k in path]
        return P(axis) if "blocks" in keys else P()

    return rules


def split_stage_params(split, n_stages: int):
    """Pipelined ``{embed, blocks, head}`` params -> per-stage trees.

    Stage ``s`` gets blocks ``[s*k, (s+1)*k)`` (``k = depth / S`` — the
    SAME boundaries the training pipeline's stage-axis sharding cuts, so
    a served stage holds exactly what its training twin held); stage 0
    additionally carries ``embed`` and the last stage ``head`` (the
    shape-ragged ends, replicated over ``stage`` in training, belong to
    the end stages when each stage is an independent program). Pure
    dim-0 slicing — works on host numpy and jax arrays alike, no copy
    beyond the slice. The MPMD serve plane (``serve/pipeline.py``)
    splits every checkpoint through here.
    """
    blocks = split["blocks"]
    depth = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    if n_stages < 1 or depth % n_stages:
        raise ValueError(
            f"vit depth {depth} not divisible by {n_stages} pipeline "
            f"stages"
        )
    k = depth // n_stages
    stages = []
    for s in range(n_stages):
        tree = {"blocks": jax.tree_util.tree_map(
            lambda a, s=s: a[s * k:(s + 1) * k], blocks)}
        if s == 0:
            tree["embed"] = split["embed"]
        if s == n_stages - 1:
            tree["head"] = split["head"]
        stages.append(tree)
    return stages


def make_stage_forward_fns(model: VisionTransformer, n_stages: int):
    """Per-stage inference forwards: ``[forward_k(stage_params, x) -> y]``.

    Stage 0 maps images to embedded tokens and applies its blocks;
    middle stages are pure block stacks ((B, T, C) in and out, the
    uniform-activation property the GPipe schedule relies on); the last
    stage closes with LN -> mean-pool -> head -> float32 logits. The
    module set and application order are literally
    ``make_pipelined_vit_apply``'s (same ``embed_mod``/``block_mod``/
    ``ln_mod``/``head_mod`` construction, same ``lax.scan`` over the
    stage's stacked blocks), so chaining the S forwards reproduces the
    trained pipeline's math — each one just compiles as an INDEPENDENT
    program on its own chip (``serve/pipeline.py``), no remat (inference
    keeps no activations).
    """
    if model.depth % n_stages:
        raise ValueError(
            f"vit depth {model.depth} not divisible by {n_stages} "
            f"pipeline stages"
        )
    cd = model.compute_dtype
    embed_mod = nn.Dense(model.embed_dim, dtype=cd)
    block_mod = TransformerBlock(
        model.num_heads, model.mlp_ratio, model.attention_fn, cd
    )
    ln_mod = nn.LayerNorm(dtype=cd)
    head_mod = nn.Dense(model.num_classes, dtype=cd)

    def apply_blocks(stage_blocks, h):
        def body(h, bp):
            return block_mod.apply({"params": bp}, h), None

        h, _ = lax.scan(body, h, stage_blocks)
        return h

    def make_forward(s: int):
        def forward(stage_params, x):
            h = x
            if s == 0:
                h = patchify(h, model.patch_size, cd)
                h = embed_mod.apply(
                    {"params": stage_params["embed"]["embed"]}, h)
                h = h + stage_params["embed"]["pos_embed"].astype(cd)
            h = apply_blocks(stage_params["blocks"], h)
            if s == n_stages - 1:
                h = ln_mod.apply({"params": stage_params["head"]["ln_f"]}, h)
                h = jnp.mean(h, axis=1)
                h = head_mod.apply({"params": stage_params["head"]["head"]},
                                   h)
                h = h.astype(jnp.float32)
            return h

        return forward

    return [make_forward(s) for s in range(n_stages)]


def create_pipelined_vit_state(
    model: VisionTransformer,
    rng: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "stage",
    data_axis: Optional[str] = None,
    num_microbatches: Optional[int] = None,
    lr: float = 1e-3,
    optimizer: str = "adam",
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    place: bool = True,
):
    """Return ``(state, state_sharding)``: a TrainState whose params use
    the pipelined layout and whose ``apply_fn`` runs the GPipe program —
    a drop-in for ``create_train_state`` that the standard train/eval
    steps consume unchanged (same pair convention as
    ``shard_state_zero1``).

    ``place=False`` returns the HOST state unplaced (sharding tree still
    computed): a caller composing a further layout on top (ZeRO moments)
    must place exactly once onto the composed sharding — placing here
    first would commit the arrays and make the multi-host re-placement a
    cross-host reshard (see ``parallel.mesh.place_state``).
    """
    from pytorch_distributed_mnist_tpu.parallel.mesh import place_state
    from pytorch_distributed_mnist_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    params = split_vit_params(
        model.init(rng, jnp.zeros((1, 28, 28, 1), jnp.float32))
    )
    tx = make_optimizer(lr, optimizer, momentum, weight_decay)
    apply_fn = make_pipelined_vit_apply(
        model, mesh, axis=axis, data_axis=data_axis,
        num_microbatches=num_microbatches,
    )
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        apply_fn=apply_fn,
        tx=tx,
    )
    sharding = pipelined_state_sharding(state, mesh, axis)
    if not place:
        return state, sharding
    return place_state(state, sharding), sharding


def pipelined_state_sharding(state, mesh: Mesh, axis: str = "stage"):
    """NamedSharding pytree: ``blocks`` leaves P(axis) on dim 0, rest
    replicated. Adam ``mu``/``nu`` mirror the param tree, so the same
    path test covers them."""

    def spec_for(path, leaf):
        keys = [getattr(k, "key", getattr(k, "name", None)) for k in path]
        if "blocks" in keys and getattr(leaf, "ndim", 0) >= 1:
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, state)
