"""Capacity-factor MoE dispatch: all_to_all token routing (GShard-style).

The dense-dispatch ``SwitchMoE`` (models/moe.py) runs every expert's FLOPs
on every token algebraically and lets EP sharding recover the per-device
FLOPs; that keeps the math layout-independent but moves the full (B, E, H)
activation through HBM. This module is the scale formulation the docstring
there promises: each token is physically dispatched to ONE expert's buffer,
bounded by a capacity factor, and tokens cross the ``expert`` mesh axis as
one ``lax.all_to_all`` each way — the XLA collective that rides ICI, the
TPU analog of the reference stack's NCCL alltoall in DeepSpeed-style MoE
(the reference itself has no experts at all:
``/root/reference/multi_proc_single_gpu.py:119-126``, SURVEY.md section 2c
EP ABSENT).

Shape walk (per device, inside shard_map over the ``expert`` axis):

    x_loc (Bg, M) --dispatch one-hot--> (E, Cap, M)        local einsum
      --all_to_all(expert)-->           (G, E_loc, Cap, M) tokens to owners
      --expert MLP (local weights)-->   (G, E_loc, Cap, M)
      --all_to_all back-->              (E, Cap, M)
      --combine one-hot * gate-->       (Bg, M)

Tokens beyond an expert's capacity ``ceil(Bg * cf / E)`` are dropped (their
combine weight is zero — the residual connection in ``MoEClassifier``
carries them through unchanged), the standard switch-transformer contract.
With no oversubscription the result equals dense dispatch exactly, which
is what tests/test_moe_dispatch.py pins.

Routing/dispatch tensors are built in f32 (top-1 is a discrete decision;
bf16 logit noise would make the routing layout-dependent).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = [
    "top1_mask_gate",
    "build_dispatch",
    "moe_capacity_forward",
    "load_balance_loss",
]


def top1_mask_gate(probs: jnp.ndarray):
    """(B, E) router probs -> (one-hot mask (B, E), routed prob gate (B,)).

    THE routing decision, shared by dense dispatch (models/moe.py),
    capacity dispatch, and the aux loss — one implementation so
    tie-breaking/dtype changes can never make them disagree (the
    dense == capacity equivalence tests assume identical routing).
    """
    e = probs.shape[-1]
    mask = jax.nn.one_hot(jnp.argmax(probs, axis=-1), e, dtype=probs.dtype)
    gate = jnp.sum(probs * mask, axis=-1)
    return mask, gate


def build_dispatch(probs: jnp.ndarray, capacity: int):
    """(B, E) router probs -> one-hot dispatch/combine (B, E, Cap).

    Top-1 routing with in-order capacity assignment: the k-th token routed
    to expert e takes slot k; tokens with k >= capacity are dropped (both
    tensors zero for them).
    """
    mask, gate = top1_mask_gate(probs)
    # 0-indexed arrival position of each token within its expert's queue.
    pos = jnp.cumsum(mask, axis=0) * mask - mask
    keep = mask * (pos < capacity)
    dispatch = keep[..., None] * jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=probs.dtype
    )  # (B, E, Cap)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def load_balance_loss(probs: jnp.ndarray) -> jnp.ndarray:
    """Switch-transformer auxiliary loss: ``E * sum_e f_e * p_e``.

    ``f_e`` = fraction of tokens top-1-routed to expert e, ``p_e`` = mean
    router probability of e. Equals 1.0 under perfectly uniform routing;
    grows as routing collapses onto few experts. Differentiable through
    ``p_e`` (the ``f_e`` factor is piecewise constant), which is exactly
    the gradient the switch paper uses to spread the router.
    """
    e = probs.shape[-1]
    mask, _ = top1_mask_gate(probs)
    f = jnp.mean(mask, axis=0)
    p = jnp.mean(probs, axis=0)
    return e * jnp.sum(f * p)


def _expert_mlp(ei, w1, b1, w2, b2, compute_dtype):
    """(..., E, Cap, M) tokens through per-expert two-layer MLPs."""
    ei = ei.astype(compute_dtype)
    h = jax.nn.relu(
        jnp.einsum("...ecm,emh->...ech", ei, w1.astype(compute_dtype))
        + b1.astype(compute_dtype)[..., :, None, :]
    )
    return (
        jnp.einsum("...ech,ehm->...ecm", h, w2.astype(compute_dtype))
        + b2.astype(compute_dtype)[..., :, None, :]
    )


def moe_capacity_forward(
    x: jnp.ndarray,
    probs: jnp.ndarray,
    w1: jnp.ndarray,  # (E, M, H)
    b1: jnp.ndarray,  # (E, H)
    w2: jnp.ndarray,  # (E, H, M)
    b2: jnp.ndarray,  # (E, M)
    *,
    capacity_factor: float = 1.25,
    compute_dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    expert_axis: str = "expert",
    data_axis: Optional[str] = "data",
) -> jnp.ndarray:
    """Capacity-dispatched switch layer: (B, M) -> (B, M).

    Without a mesh (or with a 1-sized expert axis) this is the pure local
    program — same math, no collectives — used by tests as the oracle for
    the distributed path. With a mesh, tokens are grouped over
    ``(data_axis, expert_axis)`` and experts over ``expert_axis``; the two
    ``all_to_all``s exchange token buffers with expert owners.
    """
    e = w1.shape[0]

    def local_forward(x_loc, probs_loc, w1_l, b1_l, w2_l, b2_l, n_groups):
        bg = x_loc.shape[0]
        capacity = max(1, math.ceil(bg * capacity_factor / e))
        dispatch, combine = build_dispatch(probs_loc.astype(jnp.float32),
                                           capacity)
        ei = jnp.einsum("bec,bm->ecm", dispatch.astype(x_loc.dtype), x_loc)
        if n_groups == 1:
            y = _expert_mlp(ei, w1_l, b1_l, w2_l, b2_l, compute_dtype)
        else:
            e_loc = e // n_groups
            ei = ei.reshape((n_groups, e_loc) + ei.shape[1:])
            # (G, E_loc, Cap, M): dim 0 becomes the sender-group index.
            ei = lax.all_to_all(ei, expert_axis, split_axis=0, concat_axis=0)
            y = _expert_mlp(ei, w1_l, b1_l, w2_l, b2_l, compute_dtype)
            y = lax.all_to_all(y, expert_axis, split_axis=0, concat_axis=0)
            y = y.reshape((e,) + y.shape[2:])
        return jnp.einsum(
            "ecm,bec->bm", y.astype(jnp.float32), combine
        ).astype(x_loc.dtype)

    if mesh is None or mesh.shape.get(expert_axis, 1) == 1:
        return local_forward(x, probs, w1, b1, w2, b2, 1)

    n = mesh.shape[expert_axis]
    if e % n:
        raise ValueError(f"{e} experts not divisible by {expert_axis}={n}")
    token_axes = (
        (data_axis, expert_axis)
        if data_axis and mesh.shape.get(data_axis, 1) > 1
        else (expert_axis,)
    )
    n_groups = 1
    for a in token_axes:
        n_groups *= mesh.shape[a]
    if x.shape[0] % n_groups:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by the {n_groups} token "
            f"groups of mesh axes {token_axes} (capacity dispatch shards "
            f"tokens over them)"
        )
    tok = P(token_axes)
    ex = P(expert_axis)
    return jax.shard_map(
        lambda *a: local_forward(*a, n),
        mesh=mesh,
        in_specs=(tok, tok, ex, ex, ex, ex),
        out_specs=tok,
        check_vma=False,
    )(x, probs, w1, b1, w2, b2)
