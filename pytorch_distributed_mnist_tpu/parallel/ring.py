"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence axis at all (its model is ``Linear(784, 10)``,
``/root/reference/multi_proc_single_gpu.py:119-126``; SURVEY.md section 2c
lists ring attention / SP as ABSENT), but long-context is first-class in
this framework's design, so the machinery exists and is tested on the
virtual 8-device mesh.

Design (blockwise ring, a la Ring Attention / blockwise-parallel
transformers): the token axis T is sharded across the ``seq`` mesh axis —
each device holds ``(B, T/n, H, D)`` of Q, K, V. The ring runs n steps; at
step j every device computes one (local Q block) x (visiting K/V block)
online-softmax update (``ops/attention.py``) while ``lax.ppermute`` rotates
the K/V blocks one hop around the ring. Communication is neighbor-to-
neighbor only, which XLA maps onto ICI links; HBM never materializes a
(T, T) score matrix, so sequence length scales linearly in memory per chip.

Causal masking: after j hops, the device at ring position i holds the K/V
block that started at position ``(i - j) mod n``. Block-level global offsets
reconstruct the exact (Tq, Tk) triangular mask, so causal ring attention is
bit-comparable to dense causal attention.

``ring_attention`` works both ways:
- called on GLOBAL arrays under jit (it wraps itself in ``jax.shard_map``
  over the given mesh), or
- ``ring_attention_local`` called INSIDE an enclosing shard_map whose specs
  already shard the token axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_mnist_tpu.ops.attention import (
    online_softmax_block,
    online_softmax_finish,
    online_softmax_init,
)


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-device body: local Q/K/V blocks ``(B, T_local, H, D)`` -> local O.

    Must run inside ``shard_map`` (or any context where ``axis_name`` is
    bound) with the token axis sharded on ``axis_name``.
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    t_local = q.shape[1]

    def block_mask(kv_owner):
        """(Tq_local, Tk_local) causal mask between my Q block and the block
        that originated on device ``kv_owner``."""
        q_off = me * t_local
        k_off = kv_owner * t_local
        qi = q_off + jnp.arange(t_local)[:, None]
        ki = k_off + jnp.arange(t_local)[None, :]
        return qi >= ki

    def update(state, kv, j):
        k_blk, v_blk = kv
        owner = (me - j) % n
        mask = block_mask(owner) if causal else None
        return online_softmax_block(state, q, k_blk, v_blk, scale=scale, mask=mask)

    def body(carry, j):
        state, kv = carry
        state = update(state, kv, j)
        # Rotate K/V one hop: device i sends to i+1 (mod n), so at the next
        # step we hold the block owned by (me - j - 1) mod n.
        kv = lax.ppermute(
            kv, axis_name, perm=[(i, (i + 1) % n) for i in range(n)]
        )
        return (state, kv), None

    # n-1 rotations, not n: the blocks rotated on a final scan step would be
    # discarded, so the last update runs outside the scan.
    (state, kv), _ = lax.scan(
        body, (online_softmax_init(q), (k, v)), jnp.arange(n - 1)
    )
    state = update(state, kv, n - 1)
    return online_softmax_finish(state, dtype=q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "seq",
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Ring attention on GLOBAL ``(B, T, H, D)`` arrays; T sharded on ``axis``.

    Jit-compatible (shard_map composes under jit). ``batch_axis`` /
    ``head_axis`` extend the in/out specs so the same call composes with
    data parallelism (B sharded) and tensor parallelism (H sharded): the
    ring only ever communicates along ``axis``; the other axes just make
    each device's block smaller.
    """
    spec = P(batch_axis, axis, head_axis, None)
    fn = partial(
        ring_attention_local, axis_name=axis, causal=causal, scale=scale
    )
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
