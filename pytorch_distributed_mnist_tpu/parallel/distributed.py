"""Multi-host bootstrap and process-topology probes.

Replaces the reference's rendezvous stack:

- ``dist.init_process_group(backend, init_method='tcp://...', world_size,
  rank)`` (``/root/reference/multi_proc_single_gpu.py:167-168, 323-331``)
  becomes ``jax.distributed.initialize(coordinator_address, num_processes,
  process_id)`` — one process per *host* (SPMD), not per chip.
- ``distributed_is_initialized()`` (``:21-25``) becomes ``is_distributed()``.
- There is no backend flag: the mesh is the backend configuration; XLA routes
  collectives over ICI within a slice and DCN across slices.

All topology access goes through ``process_index()`` / ``process_count()``
so multi-host shard arithmetic is unit-testable with monkeypatched values
(SURVEY.md section 4, "multi-host logic").
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def _multiprocess_env_detected() -> bool:
    """True when the environment indicates a multi-process launch.

    These are the variables JAX's own cluster detection consumes: an
    explicit coordinator (``JAX_COORDINATOR_ADDRESS``), a multi-worker TPU
    pod (``TPU_WORKER_HOSTNAMES`` listing >1 hosts, or megascale
    coordination), or a Slurm / Open MPI launcher. When any is present,
    ``jax.distributed.initialize()`` is called with NO arguments so JAX's
    autodetection fills in address/size/rank itself — this code never
    second-guesses it (a previous revision gated on a nonstandard
    ``TPU_WORKER_COUNT`` variable, which real pod runtimes do not set).
    """
    env = os.environ
    if env.get("JAX_COORDINATOR_ADDRESS") or env.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    hosts = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h.strip()]
    if len(hosts) > 1:
        return True
    for var in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"):
        try:
            if int(env.get(var, "0")) > 1:
                return True
        except ValueError:
            pass
    return False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the multi-host runtime (idempotent).

    With no arguments, auto-detects from the environment the way TPU pods /
    cluster launchers configure it (the analog of ``torch.distributed.launch``
    injecting ``--local_rank``, reference ``:319-321``). Explicit arguments
    mirror the reference's ``--init-method`` / ``--world-size`` / ``--rank``
    flags. Single-process runs skip initialization entirely, like the
    reference's world-size-1 path still calling ``init_process_group`` —
    except here single-process needs no rendezvous at all.
    """
    global _initialized
    if _initialized:
        return
    explicit = coordinator_address is not None or (num_processes or 0) > 1
    if explicit:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif _multiprocess_env_detected():
        # Let JAX's cluster autodetection (TPU pod metadata, Slurm, OMPI)
        # work out coordinator/size/rank on its own.
        jax.distributed.initialize()
    _initialized = True


def is_distributed() -> bool:
    """True iff more than one host process participates (cf. reference ``:21-25``)."""
    return process_count() > 1


def process_index() -> int:
    """This host's rank among participating processes."""
    return jax.process_index()


def process_count() -> int:
    """Number of participating host processes."""
    return jax.process_count()
