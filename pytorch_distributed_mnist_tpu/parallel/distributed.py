"""Multi-host bootstrap and process-topology probes.

Replaces the reference's rendezvous stack:

- ``dist.init_process_group(backend, init_method='tcp://...', world_size,
  rank)`` (``/root/reference/multi_proc_single_gpu.py:167-168, 323-331``)
  becomes ``jax.distributed.initialize(coordinator_address, num_processes,
  process_id)`` — one process per *host* (SPMD), not per chip.
- ``distributed_is_initialized()`` (``:21-25``) becomes ``is_distributed()``.
- There is no backend flag: the mesh is the backend configuration; XLA routes
  collectives over ICI within a slice and DCN across slices.

All topology access goes through ``process_index()`` / ``process_count()``
so multi-host shard arithmetic is unit-testable with monkeypatched values
(SURVEY.md section 4, "multi-host logic").
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the multi-host runtime (idempotent).

    With no arguments, auto-detects from the environment the way TPU pods
    configure it (the analog of ``torch.distributed.launch`` injecting
    ``--local_rank``, reference ``:319-321``). Explicit arguments mirror the
    reference's ``--init-method`` / ``--world-size`` / ``--rank`` flags.
    Single-process runs skip initialization entirely, like the reference's
    world-size-1 path still calling ``init_process_group`` — except here
    single-process needs no rendezvous at all.
    """
    global _initialized
    if _initialized:
        return
    want_multi = (
        coordinator_address is not None
        or (num_processes or 0) > 1
        or int(os.environ.get("TPU_WORKER_COUNT", "1")) > 1
    )
    if want_multi:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    _initialized = True


def is_distributed() -> bool:
    """True iff more than one host process participates (cf. reference ``:21-25``)."""
    return process_count() > 1


def process_index() -> int:
    """This host's rank among participating processes."""
    return jax.process_index()


def process_count() -> int:
    """Number of participating host processes."""
    return jax.process_count()
