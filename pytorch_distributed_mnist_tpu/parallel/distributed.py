"""Multi-host bootstrap and process-topology probes.

Replaces the reference's rendezvous stack:

- ``dist.init_process_group(backend, init_method='tcp://...', world_size,
  rank)`` (``/root/reference/multi_proc_single_gpu.py:167-168, 323-331``)
  becomes ``jax.distributed.initialize(coordinator_address, num_processes,
  process_id)`` — one process per *host* (SPMD), not per chip.
- ``distributed_is_initialized()`` (``:21-25``) becomes ``is_distributed()``.
- There is no backend flag: the mesh is the backend configuration; XLA routes
  collectives over ICI within a slice and DCN across slices.

All topology access goes through ``process_index()`` / ``process_count()``
so multi-host shard arithmetic is unit-testable with monkeypatched values
(SURVEY.md section 4, "multi-host logic").
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False
_init_info: dict = {}


def _multiprocess_env_detected() -> bool:
    """True when the environment indicates a multi-process launch.

    These are the variables JAX's own cluster detection consumes: an
    explicit coordinator (``JAX_COORDINATOR_ADDRESS``), a multi-worker TPU
    pod (``TPU_WORKER_HOSTNAMES`` listing >1 hosts, or megascale
    coordination), or a Slurm / Open MPI launcher. When any is present,
    ``jax.distributed.initialize()`` is called with NO arguments so JAX's
    autodetection fills in address/size/rank itself — this code never
    second-guesses it (a previous revision gated on a nonstandard
    ``TPU_WORKER_COUNT`` variable, which real pod runtimes do not set).
    """
    env = os.environ
    if env.get("JAX_COORDINATOR_ADDRESS") or env.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    hosts = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h.strip()]
    if len(hosts) > 1:
        return True
    for var in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"):
        try:
            if int(env.get(var, "0")) > 1:
                return True
        except ValueError:
            pass
    return False


def _enable_cpu_collectives() -> None:
    """Give the CPU backend a cross-process collectives implementation.

    A multi-process world on the CPU backend (the local pod simulation
    every ``--spawn``/subprocess-twin test runs, and the chaos harness)
    needs one explicitly on this jaxlib: the default is ``none``, under
    which EVERY global computation — train-step psums and the
    supervision agreement allgathers alike — dies with
    "Multiprocess computations aren't implemented on the CPU backend".
    Gloo (TCP, wired to the jax.distributed client) is jax's own local
    multi-process answer. Enabled when CPU is (or will resolve to) the
    PRIMARY platform: an explicit request whose first entry is cpu, or
    no platform preference at all on a machine with no TPU-pod markers —
    the bare-CPU-cluster case, where jax resolves to CPU by itself.
    Real pods (accelerator-first platform lists, or pod environment
    variables) are untouched. Tolerant of jax versions that renamed or
    removed the knob. Must run before the backend initializes (the same
    ordering contract as ``jax.distributed.initialize`` itself).
    """
    try:
        configured = (jax.config.jax_platforms or "").lower()
    except AttributeError:
        configured = ""
    spec = configured or (os.environ.get("JAX_PLATFORMS") or "").lower()
    if spec:
        if spec.split(",")[0].strip() != "cpu":
            return  # an accelerator owns the collectives
    else:
        env = os.environ
        if env.get("TPU_WORKER_HOSTNAMES") \
                or env.get("MEGASCALE_COORDINATOR_ADDRESS"):
            return  # a real pod with no explicit platform preference
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the multi-host runtime (idempotent).

    With no arguments, auto-detects from the environment the way TPU pods /
    cluster launchers configure it (the analog of ``torch.distributed.launch``
    injecting ``--local_rank``, reference ``:319-321``). Explicit arguments
    mirror the reference's ``--init-method`` / ``--world-size`` / ``--rank``
    flags. Single-process runs skip initialization entirely, like the
    reference's world-size-1 path still calling ``init_process_group`` —
    except here single-process needs no rendezvous at all.
    """
    global _initialized
    if _initialized:
        return
    import time

    explicit = coordinator_address is not None or (num_processes or 0) > 1
    if explicit:
        _enable_cpu_collectives()
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _init_info["mode"] = "explicit"
        _init_info["coordinator"] = coordinator_address
    elif _multiprocess_env_detected():
        # Let JAX's cluster autodetection (TPU pod metadata, Slurm, OMPI)
        # work out coordinator/size/rank on its own.
        _enable_cpu_collectives()
        jax.distributed.initialize()
        _init_info["mode"] = "auto"
    else:
        _init_info["mode"] = "single"
    _init_info["initialized_at"] = time.time()
    _initialized = True


def is_distributed() -> bool:
    """True iff more than one host process participates (cf. reference ``:21-25``)."""
    return process_count() > 1


def process_index() -> int:
    """This host's rank among participating processes."""
    return jax.process_index()


def process_count() -> int:
    """Number of participating host processes."""
    return jax.process_count()


def runtime_info() -> dict:
    """Topology snapshot for supervision diagnostics (watchdog phase
    reports, failure events): how this world was bootstrapped, when, and
    this host's coordinates. Values are plain Python so the dict drops
    straight into a JSON summary."""
    info = dict(_init_info)
    info["process_index"] = process_index()
    info["process_count"] = process_count()
    return info
