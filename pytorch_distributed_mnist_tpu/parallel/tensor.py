"""Tensor parallelism: param-sharding rules over an N-D mesh (GSPMD).

The reference's only strategy is data parallelism — DDP replicates every
weight (``/root/reference/multi_proc_single_gpu.py:188-189``; SURVEY.md
section 2c marks TP ABSENT). This framework keeps the mesh N-dimensional so
TP is a ``PartitionSpec`` change, not new machinery (SURVEY.md section 2c's
closing note): the functions here produce a sharding pytree for the whole
``TrainState`` from a small table of path-suffix rules, and a jitted step
factory whose in/out shardings carry it. XLA's sharding propagation then
inserts the Megatron-pattern collectives (column-parallel matmul ->
row-parallel matmul -> AllReduce of the partial sums) over the ``model``
mesh axis — on TPU these ride ICI next to the data-axis gradient AllReduce.

Rule matching is by the LAST TWO path keys of each leaf (e.g.
``('qkv', 'kernel')``). Optimizer moments (Adam ``mu``/``nu``) are full
param-tree replicas inside ``opt_state``, so their leaf paths end with the
same two keys — one rule table shards params and both moments consistently,
the property that makes this a ZeRO-free but layout-consistent design.

**Collective-matmul overlap** (``--tp-overlap``, off by default): the GSPMD
path above leaves the Megatron collectives' placement to XLA — on the
sequence-parallel layout that means a blocking allgather of the sequence
shard sits in front of every column-parallel matmul. ``allgather_matmul``
writes the overlapped schedule out explicitly (the "collective matmul" of
Wang et al., "Overlap Communication with Dependent Computation via
Decomposition", ASPLOS'23): the gather decomposes into ``tp - 1`` ring
``ppermute`` hops, and the matmul into one per-shard row-block step, so
hop k's transfer rides ICI while step k-1's block is on the MXU. Row
blocks of a matmul are independent, so the decomposition is exact — the
overlapped path is trajectory-equal to the unoverlapped one (pinned by
``tests/test_tp_overlap.py``). The fences are the same
``lax.optimization_barrier`` chain idiom as ``parallel/zero_overlap.py``:
they pin issue order without inventing data dependencies on unrelated
compute. ``make_overlap_tp_vit_apply`` embeds it in a Megatron-SP
(sequence-sharded residual stream) ViT body on the head-major explicit
layout from ``parallel/pipeline_tp.py``.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Megatron-style column->row split for the ViT transformer blocks
# (models/attention.py): qkv/mlp1 shard their OUTPUT feature dim (column
# parallel — activations come out head/feature-sharded), proj/mlp2 shard
# their INPUT dim (row parallel — partial sums AllReduce back to replicated).
def vit_tp_rules(axis: str = "model") -> Dict[Tuple[str, str], P]:
    return {
        ("qkv", "kernel"): P(None, axis),
        ("qkv", "bias"): P(axis),
        ("proj", "kernel"): P(axis, None),
        ("mlp1", "kernel"): P(None, axis),
        ("mlp1", "bias"): P(axis),
        ("mlp2", "kernel"): P(axis, None),
    }


def _path_keys(path) -> Tuple[str, ...]:
    keys = []
    for entry in path:
        key = getattr(entry, "key", getattr(entry, "name", None))
        if key is not None:
            keys.append(str(key))
    return tuple(keys)


def leaf_spec(path, rules) -> P:
    """PartitionSpec for one leaf: match the last two path keys, default P().

    ``rules`` may also be a CALLABLE ``rules(path) -> PartitionSpec`` for
    layouts a two-key suffix table cannot express — the pipeline layout's
    "every leaf under ``blocks``" rule (``parallel/pipeline_vit.py::
    pipeline_stage_rules``) is the motivating case; the serve registry's
    divisibility walk (``serve/programs.py::validate_serve_mode``) feeds
    both forms through here.
    """
    if callable(rules):
        return rules(path)
    keys = _path_keys(path)
    return rules.get(tuple(keys[-2:]), P())


def state_shardings(state, mesh: Mesh, rules: Dict[Tuple[str, str], P]):
    """NamedSharding pytree mirroring ``state`` (params AND optimizer moments).

    Leaves with no matching rule — step counter, hyperparams, Adam ``count``,
    biases of unsharded layers — replicate, which is exactly the DDP layout
    the reference uses for everything (``:188-189``).
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, leaf_spec(path, rules)), state
    )


def shard_state(state, mesh: Mesh, rules: Dict[Tuple[str, str], P]):
    """Place an (unsharded) TrainState onto the mesh per the rule table.

    Returns ``(placed_state, sharding_tree)`` — the same pair contract as
    ``shard_state_zero1`` and ``create_pipelined_vit_state``, so callers
    never recompute the tree. Multi-host safe
    (see ``parallel.mesh.place_state``)."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import place_state

    sharding = state_shardings(state, mesh, rules)
    return place_state(state, sharding), sharding


def make_tp_train_step(mesh: Mesh, state_sharding, data_axis: str = "data"):
    """Jitted DP x TP ``step(state, batch) -> (state, MetricState)``.

    Same program as the pure-DP step — this just forwards the TP layout to
    the shared step factory; XLA propagates the rest (column/row-parallel
    matmul collectives, grad AllReduce over ``data_axis``).
    """
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step

    return make_train_step(mesh, data_axis, state_sharding=state_sharding)


def make_tp_eval_step(mesh: Mesh, state_sharding, data_axis: str = "data"):
    """Jitted DP x TP ``step(state, batch) -> MetricState``."""
    from pytorch_distributed_mnist_tpu.train.steps import make_eval_step

    return make_eval_step(mesh, data_axis, state_sharding=state_sharding)


# ---------------------------------------------------------------------------
# Collective-matmul overlap (--tp-overlap): explicit ring schedule.
# ---------------------------------------------------------------------------


def allgather_matmul(x: jnp.ndarray, w: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Overlapped ``allgather(x) @ w``: per-shard matmul steps riding a ring.

    ``x`` is this rank's sequence shard ``(B, T/tp, C)`` (sharded on dim 1
    over mesh axis ``axis``); ``w`` is a replicated-or-local weight whose
    FIRST dim contracts with ``x``'s last. Returns the full-sequence
    product ``(B, T, *w.shape[1:])`` on every rank — the same value as

        jnp.tensordot(lax.all_gather(x, axis, axis=1, tiled=True), w, 1)

    but decomposed: the gather becomes ``tp - 1`` ring ``ppermute`` hops
    and the matmul one row-block step per shard, so each hop's transfer
    overlaps the previous block's compute instead of serializing in front
    of the whole matmul. Row blocks of a matmul are independent (each
    output row is one dot of an input row with ``w``), so the
    decomposition changes scheduling, not math.

    The ``optimization_barrier`` fence chain (``zero_overlap._fenced`` /
    ``_chain``) pins one ordered compute stream — block k's matmul after
    chunk k's arrival — while leaving every ppermute free to issue as
    soon as its operand exists, which is what the overlap needs.
    """
    # Lazy: parallel.zero imports this module's rule helpers, so a
    # module-level import of zero_overlap (which imports zero) would cycle.
    from pytorch_distributed_mnist_tpu.parallel.zero_overlap import (
        _chain,
        _fenced,
    )

    tp = lax.axis_size(axis)
    if tp == 1:
        return jnp.tensordot(x, w, axes=([x.ndim - 1], [0]))
    idx = lax.axis_index(axis)
    # Each rank sends to its predecessor / receives from its successor:
    # after s hops this rank holds the shard that started on rank
    # (idx + s) % tp, so the step-order pieces are a cyclic rotation of
    # the global order — one jnp.roll restores it.
    perm = [(j, (j - 1) % tp) for j in range(tp)]
    token = jnp.zeros((), jnp.float32)
    chunk = x
    pieces = []
    for step in range(tp):
        nxt = lax.ppermute(chunk, axis, perm) if step + 1 < tp else None
        # Fence this step's operand (and the in-flight transfer) behind
        # the chain token so the per-shard matmuls form one ordered
        # stream; the ppermute itself is NOT behind the matmul — its
        # operand is last step's chunk, so it issues while this block
        # multiplies.
        if nxt is None:
            (chunk,), token = _fenced((chunk,), token)
        else:
            (chunk, nxt), token = _fenced((chunk, nxt), token)
        piece = jnp.tensordot(chunk, w, axes=([chunk.ndim - 1], [0]))
        pieces.append(piece)
        token = _chain(token, jnp.sum(piece).astype(jnp.float32))
        chunk = nxt
    stacked = jnp.stack(pieces, axis=0)        # (tp, B, T/tp, ...) step order
    stacked = jnp.roll(stacked, idx, axis=0)   # source-rank (global) order
    moved = jnp.moveaxis(stacked, 0, 1)        # (B, tp, T/tp, ...)
    return moved.reshape(
        (moved.shape[0], tp * moved.shape[2]) + moved.shape[3:])


def overlap_tp_rules(axis: str = "model") -> Dict[Tuple[str, str], P]:
    """Suffix rules for the head-major DEPTH-STACKED layout
    (``pipeline_tp.split_vit_params_tp``): every blocks leaf carries a
    leading ``(depth,)`` dim, attention is head-major — qkv
    ``(depth, C, 3, H, D)``, proj ``(depth, H, D, C)`` — and ``axis``
    lands on the head dim / MLP hidden dim (the same Megatron column->row
    split as ``vit_tp_rules``, expressed on the explicit layout)."""
    return {
        ("qkv", "kernel"): P(None, None, None, axis, None),
        ("qkv", "bias"): P(None, None, axis, None),
        ("proj", "kernel"): P(None, axis, None, None),
        ("mlp1", "kernel"): P(None, None, axis),
        ("mlp1", "bias"): P(None, axis),
        ("mlp2", "kernel"): P(None, axis, None),
    }


def overlap_block_apply(bp, h, *, tp_axis: str, compute_dtype,
                        attention_fn=None):
    """One transformer block on a SEQUENCE-SHARDED residual stream.

    ``h`` is this rank's ``(B, T/tp, C)`` token shard; ``bp`` this rank's
    head-major weight shard (whole heads for qkv/proj, a slice of the MLP
    hidden dim for mlp1/mlp2). The Megatron-SP shape: LayerNorm runs on
    the token shard, each column-parallel matmul gathers the sequence
    THROUGH ``allgather_matmul`` (the overlapped form), attention runs on
    the full sequence with local heads, and each row-parallel matmul's
    partial sums reduce-scatter straight back to the token shard
    (``psum_scatter`` — the transpose of the gather, so between blocks
    only 1/tp of the activations exist per rank).

    Math parity with ``models/attention.py::TransformerBlock``: identical
    flax LayerNorm/gelu modules and compute-dtype policy; the only
    difference is float reassociation inside the psum_scatter.
    """
    import flax.linen as nn

    from pytorch_distributed_mnist_tpu.ops.attention import full_attention

    cd = compute_dtype
    ln = nn.LayerNorm(dtype=cd)

    x = h
    y = ln.apply({"params": bp["ln1"]}, x)
    a = bp["attn"]
    wqkv = a["qkv"]["kernel"].astype(cd)         # (C, 3, Hl, D)
    bqkv = a["qkv"]["bias"].astype(cd)           # (3, Hl, D)
    qkv = allgather_matmul(y.astype(cd), wqkv, tp_axis) + bqkv
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attend = attention_fn or full_attention
    o = attend(q, k, v)                          # (B, T, Hl, D) local heads
    wproj = a["proj"]["kernel"].astype(cd)       # (Hl, D, C)
    part = jnp.einsum("bthd,hdc->btc", o.astype(cd), wproj)
    part = lax.psum_scatter(part, tp_axis, scatter_dimension=1, tiled=True)
    x = x + part + a["proj"]["bias"].astype(cd)

    y = ln.apply({"params": bp["ln2"]}, x)
    u = allgather_matmul(y.astype(cd), bp["mlp1"]["kernel"].astype(cd),
                         tp_axis) + bp["mlp1"]["bias"].astype(cd)
    u = nn.gelu(u)                               # (B, T, 4C/tp)
    v2 = u @ bp["mlp2"]["kernel"].astype(cd)     # partial (B, T, C)
    v2 = lax.psum_scatter(v2, tp_axis, scatter_dimension=1, tiled=True)
    return x + v2 + bp["mlp2"]["bias"].astype(cd)


def make_overlap_tp_vit_apply(model, mesh: Mesh, *, tp_axis: str = "model",
                              data_axis: Optional[str] = "data"):
    """``apply_fn(split_tp_params, x, train=False) -> logits`` running the
    overlapped-TP schedule in an explicit shard_map.

    Drop-in for ``model.apply`` in a TrainState (the
    ``make_pipelined_tp_vit_apply`` contract): params are the head-major
    split layout, embed/head run replicated over ``tp_axis``, the blocks
    run sequence-sharded with ``allgather_matmul``. The standard
    train/eval step factories consume it unchanged.
    """
    import flax.linen as nn

    from pytorch_distributed_mnist_tpu.models.attention import patchify

    tp = mesh.shape[tp_axis]
    tokens = (28 // model.patch_size) ** 2
    if model.num_heads % tp:
        raise ValueError(
            f"vit heads {model.num_heads} not divisible by "
            f"--tensor-parallel {tp}")
    hidden = model.embed_dim * model.mlp_ratio
    if hidden % tp:
        raise ValueError(
            f"vit MLP hidden dim {hidden} not divisible by "
            f"--tensor-parallel {tp}")
    if tokens % tp:
        raise ValueError(
            f"vit token count {tokens} not divisible by --tensor-parallel "
            f"{tp}; the overlapped schedule shards the sequence")
    cd = model.compute_dtype
    embed_mod = nn.Dense(model.embed_dim, dtype=cd)
    ln_mod = nn.LayerNorm(dtype=cd)
    head_mod = nn.Dense(model.num_classes, dtype=cd)
    rules = overlap_tp_rules(tp_axis)

    def body(split_tp, x):
        h = patchify(x, model.patch_size, cd)
        h = embed_mod.apply({"params": split_tp["embed"]["embed"]}, h)
        h = h + split_tp["embed"]["pos_embed"].astype(cd)
        # Enter the sequence-sharded regime: this rank keeps its T/tp
        # token slice; the exit all_gather below is the inverse.
        tl = tokens // tp
        h = lax.dynamic_slice_in_dim(
            h, lax.axis_index(tp_axis) * tl, tl, axis=1)

        def blk(hh, bp):
            return overlap_block_apply(
                bp, hh, tp_axis=tp_axis, compute_dtype=cd,
                attention_fn=model.attention_fn), None

        if model.remat:
            blk = jax.checkpoint(blk)
        h, _ = lax.scan(blk, h, split_tp["blocks"])
        h = lax.all_gather(h, tp_axis, axis=1, tiled=True)
        h = ln_mod.apply({"params": split_tp["head"]["ln_f"]}, h)
        h = jnp.mean(h, axis=1)
        h = head_mod.apply({"params": split_tp["head"]["head"]}, h)
        return h.astype(jnp.float32)

    def apply_fn(split_tp, x, *, train: bool = False):
        del train
        specs = jax.tree_util.tree_map_with_path(
            lambda path, _: leaf_spec(path, rules), split_tp)
        sharded = jax.shard_map(
            body, mesh=mesh,
            in_specs=(specs, P(data_axis)),
            out_specs=P(data_axis),
            check_vma=False,
        )
        return sharded(split_tp, x)

    return apply_fn


def create_overlap_tp_vit_state(model, rng: jax.Array, mesh: Mesh, *,
                                tp_axis: str = "model",
                                data_axis: Optional[str] = "data",
                                lr: float = 1e-3, optimizer: str = "adam",
                                momentum: float = 0.9,
                                weight_decay: float = 1e-4,
                                place: bool = True):
    """``(state, state_sharding)`` for the overlapped-TP ViT — the same
    pair contract as ``shard_state`` / ``create_pipelined_tp_vit_state``,
    consumed by the standard train/eval steps unchanged. Params are the
    head-major split layout (bitwise-bijective with the standard flax
    tree via ``pipeline_tp.merge_vit_params_tp``)."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import place_state
    from pytorch_distributed_mnist_tpu.parallel.pipeline_tp import (
        split_vit_params_tp,
    )
    from pytorch_distributed_mnist_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    params = split_vit_params_tp(
        model.init(rng, jnp.zeros((1, 28, 28, 1), jnp.float32)),
        model.num_heads,
    )
    tx = make_optimizer(lr, optimizer, momentum, weight_decay)
    apply_fn = make_overlap_tp_vit_apply(
        model, mesh, tp_axis=tp_axis, data_axis=data_axis)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        apply_fn=apply_fn,
        tx=tx,
    )
    sharding = state_shardings(state, mesh, overlap_tp_rules(tp_axis))
    if not place:
        return state, sharding
    return place_state(state, sharding), sharding
