"""Tensor parallelism: param-sharding rules over an N-D mesh (GSPMD).

The reference's only strategy is data parallelism — DDP replicates every
weight (``/root/reference/multi_proc_single_gpu.py:188-189``; SURVEY.md
section 2c marks TP ABSENT). This framework keeps the mesh N-dimensional so
TP is a ``PartitionSpec`` change, not new machinery (SURVEY.md section 2c's
closing note): the functions here produce a sharding pytree for the whole
``TrainState`` from a small table of path-suffix rules, and a jitted step
factory whose in/out shardings carry it. XLA's sharding propagation then
inserts the Megatron-pattern collectives (column-parallel matmul ->
row-parallel matmul -> AllReduce of the partial sums) over the ``model``
mesh axis — on TPU these ride ICI next to the data-axis gradient AllReduce.

Rule matching is by the LAST TWO path keys of each leaf (e.g.
``('qkv', 'kernel')``). Optimizer moments (Adam ``mu``/``nu``) are full
param-tree replicas inside ``opt_state``, so their leaf paths end with the
same two keys — one rule table shards params and both moments consistently,
the property that makes this a ZeRO-free but layout-consistent design.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Megatron-style column->row split for the ViT transformer blocks
# (models/attention.py): qkv/mlp1 shard their OUTPUT feature dim (column
# parallel — activations come out head/feature-sharded), proj/mlp2 shard
# their INPUT dim (row parallel — partial sums AllReduce back to replicated).
def vit_tp_rules(axis: str = "model") -> Dict[Tuple[str, str], P]:
    return {
        ("qkv", "kernel"): P(None, axis),
        ("qkv", "bias"): P(axis),
        ("proj", "kernel"): P(axis, None),
        ("mlp1", "kernel"): P(None, axis),
        ("mlp1", "bias"): P(axis),
        ("mlp2", "kernel"): P(axis, None),
    }


def _path_keys(path) -> Tuple[str, ...]:
    keys = []
    for entry in path:
        key = getattr(entry, "key", getattr(entry, "name", None))
        if key is not None:
            keys.append(str(key))
    return tuple(keys)


def leaf_spec(path, rules) -> P:
    """PartitionSpec for one leaf: match the last two path keys, default P().

    ``rules`` may also be a CALLABLE ``rules(path) -> PartitionSpec`` for
    layouts a two-key suffix table cannot express — the pipeline layout's
    "every leaf under ``blocks``" rule (``parallel/pipeline_vit.py::
    pipeline_stage_rules``) is the motivating case; the serve registry's
    divisibility walk (``serve/programs.py::validate_serve_mode``) feeds
    both forms through here.
    """
    if callable(rules):
        return rules(path)
    keys = _path_keys(path)
    return rules.get(tuple(keys[-2:]), P())


def state_shardings(state, mesh: Mesh, rules: Dict[Tuple[str, str], P]):
    """NamedSharding pytree mirroring ``state`` (params AND optimizer moments).

    Leaves with no matching rule — step counter, hyperparams, Adam ``count``,
    biases of unsharded layers — replicate, which is exactly the DDP layout
    the reference uses for everything (``:188-189``).
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, leaf_spec(path, rules)), state
    )


def shard_state(state, mesh: Mesh, rules: Dict[Tuple[str, str], P]):
    """Place an (unsharded) TrainState onto the mesh per the rule table.

    Returns ``(placed_state, sharding_tree)`` — the same pair contract as
    ``shard_state_zero1`` and ``create_pipelined_vit_state``, so callers
    never recompute the tree. Multi-host safe
    (see ``parallel.mesh.place_state``)."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import place_state

    sharding = state_shardings(state, mesh, rules)
    return place_state(state, sharding), sharding


def make_tp_train_step(mesh: Mesh, state_sharding, data_axis: str = "data"):
    """Jitted DP x TP ``step(state, batch) -> (state, MetricState)``.

    Same program as the pure-DP step — this just forwards the TP layout to
    the shared step factory; XLA propagates the rest (column/row-parallel
    matmul collectives, grad AllReduce over ``data_axis``).
    """
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step

    return make_train_step(mesh, data_axis, state_sharding=state_sharding)


def make_tp_eval_step(mesh: Mesh, state_sharding, data_axis: str = "data"):
    """Jitted DP x TP ``step(state, batch) -> MetricState``."""
    from pytorch_distributed_mnist_tpu.train.steps import make_eval_step

    return make_eval_step(mesh, data_axis, state_sharding=state_sharding)
