"""Expert parallelism: sharding rules for MoE expert weights.

SURVEY.md section 2c marks EP ABSENT in the reference; here it is one more
``PartitionSpec`` table over the same machinery as tensor parallelism
(``parallel/tensor.py``): expert weights carry a leading ``num_experts``
dim, the rules shard it on the ``expert`` mesh axis, and the MoE combine
einsum's sum over experts (``models/moe.py``) becomes XLA's AllReduce over
that axis — every device computes only its local experts, which is the
whole point of EP.

Composes with DP the same way TP does: merge the rule dicts and build a
``('data', 'expert')`` mesh.
"""

from __future__ import annotations

from typing import Dict, Tuple

from jax.sharding import PartitionSpec as P


def moe_ep_rules(axis: str = "expert") -> Dict[Tuple[str, str], P]:
    """Path-suffix rules (see ``parallel.tensor.leaf_spec``) for SwitchMoE.

    The router stays replicated — every device must route identically for
    the one-hot combine to agree.
    """
    return {
        ("moe", "w1"): P(axis, None, None),
        ("moe", "b1"): P(axis, None),
        ("moe", "w2"): P(axis, None, None),
        ("moe", "b2"): P(axis, None),
    }
