"""Parallelism layer: device mesh, shardings, collectives, multi-host bootstrap.

This layer replaces the reference's entire distributed stack —
``dist.init_process_group`` + NCCL + ``DistributedDataParallel``
(``/root/reference/multi_proc_single_gpu.py:167-168, 188-189, 316-317``) —
with the TPU-native equivalents: ``jax.distributed.initialize`` for
multi-host bootstrap, a ``jax.sharding.Mesh`` whose ``data`` axis rides ICI,
and XLA collectives (``lax.psum``) in place of DDP's bucketed allreduce.
"""

from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh, data_sharding, replicated_sharding
from pytorch_distributed_mnist_tpu.parallel.distributed import (
    initialize_distributed,
    process_index,
    process_count,
    is_distributed,
)
from pytorch_distributed_mnist_tpu.parallel.ring import ring_attention, ring_attention_local
from pytorch_distributed_mnist_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_local,
)
from pytorch_distributed_mnist_tpu.parallel.tensor import (
    allgather_matmul,
    create_overlap_tp_vit_state,
    make_overlap_tp_vit_apply,
    make_tp_eval_step,
    make_tp_train_step,
    overlap_tp_rules,
    shard_state,
    state_shardings,
    vit_tp_rules,
)
from pytorch_distributed_mnist_tpu.parallel.expert import moe_ep_rules
from pytorch_distributed_mnist_tpu.parallel.pipeline import (
    pipeline_apply,
    sequential_apply,
    stack_stage_params,
)

__all__ = [
    "make_mesh",
    "data_sharding",
    "replicated_sharding",
    "initialize_distributed",
    "process_index",
    "process_count",
    "is_distributed",
    "ring_attention",
    "ring_attention_local",
    "ulysses_attention",
    "ulysses_attention_local",
    "allgather_matmul",
    "create_overlap_tp_vit_state",
    "make_overlap_tp_vit_apply",
    "make_tp_eval_step",
    "make_tp_train_step",
    "overlap_tp_rules",
    "shard_state",
    "state_shardings",
    "vit_tp_rules",
    "moe_ep_rules",
    "pipeline_apply",
    "sequential_apply",
    "stack_stage_params",
]
