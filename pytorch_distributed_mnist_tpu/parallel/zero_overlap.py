"""Explicit overlapped ZeRO: bucketized reduce-scatter / allgather weight
update with a compiler-visible overlap structure.

``parallel/zero.py`` shards optimizer state (ZeRO-1) and params (ZeRO-3)
purely via ``PartitionSpec``s and leaves every scheduling decision to
XLA's sharding propagation. That is the idiomatic default — but nothing
in it *expresses* the schedule the ZeRO paper ("Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training", arXiv:2004.13336)
actually wants: gradient communication overlapped with the remaining
backward, and the parameter allgather overlapped with the next step's
forward. This module writes that schedule out explicitly:

- **Same state layout as the propagation path.** The step's in/out specs
  are exactly ``zero_state_sharding``'s (per-leaf largest-divisible-dim
  sharding), so checkpoints, ``--resume auto``, and the propagation eval
  step all keep working unchanged — the two paths are interchangeable
  per state, and the equivalence suite pins them numerically equal
  (``tests/test_zero_overlap.py``).
- **Bucketized reduce-scatter** (``bucket_plan``): gradient leaves are
  size-ordered and packed into flat byte-budgeted buckets
  (``--zero-bucket-mb``). Each bucket's reduce-scatters depend only on
  that bucket's gradient leaves plus a barrier token chained from the
  previous bucket — so bucket k's communication can start the moment its
  gradients exist, while the backward still computes other buckets'
  gradients, and XLA's latency-hiding scheduler is free to overlap the
  two. ``lax.optimization_barrier`` (AD shim: ``utils/jax_compat.py``)
  provides the fences: it pins bucket order without inventing data
  dependencies on unrelated compute.
- **Carried allgather** (ZeRO-3): the step takes the previous step's
  gathered (replicated) params as an argument and returns the next
  gathered copy rebuilt from the updated shards — the allgather sits at
  the tail of step N where it can overlap metric math and, across the
  scan carry in ``make_overlap_train_epoch`` (or the Trainer's explicit
  carry in stepwise mode), the head of step N+1's forward. The carry is
  derived state: ``gathered == allgather(state.params)`` always, and is
  rebuilt from the state by ``make_param_gather`` whenever dropped.

Gradient semantics are the per-example-sum form: each device accumulates
the SUM of per-example loss gradients over its local rows (micro-batched
under ``grad_accum``), the reduce-scatter produces global sums, and one
division by the global (psum'd) example count yields exactly the
global-batch masked-mean gradient for any mask distribution — the same
quantity the propagation path's autodiff computes, equal up to float
reduction order.

Scope: the pure data-parallel mesh (``data`` axis only). TP/EP rule
tables and pipeline base shardings stay on the propagation path, which
remains the default (``cli.py`` gates the compositions).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy
from pytorch_distributed_mnist_tpu.ops.metrics import MetricState, metrics_init
from pytorch_distributed_mnist_tpu.parallel.zero import _zero_spec, zero_state_sharding
from pytorch_distributed_mnist_tpu.train.steps import accumulate_metrics


def _leaf_bytes(leaf) -> int:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    dtype = np.dtype(getattr(leaf, "dtype", np.float32))
    return int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape \
        else dtype.itemsize


def bucket_plan(leaves, bucket_mb: float) -> List[List[int]]:
    """Pack flattened-leaf indices into size-ordered byte-budgeted buckets.

    Leaves are ordered largest-first (ties broken by flat index, so the
    plan is deterministic across runs and hosts — the same property the
    ``_zero_spec`` tie-break pins for dim choice) and packed greedily:
    a bucket closes when adding the next leaf would exceed
    ``bucket_mb`` MiB. A single leaf larger than the budget gets its own
    bucket. Each bucket is one communication-issue group in the step:
    its collectives are fenced together and chained after the previous
    bucket's.
    """
    if bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
    budget = int(bucket_mb * (1 << 20))
    order = sorted(range(len(leaves)),
                   key=lambda i: (-_leaf_bytes(leaves[i]), i))
    plan: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in order:
        nbytes = _leaf_bytes(leaves[i])
        if cur and cur_bytes + nbytes > budget:
            plan.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        plan.append(cur)
    return plan


def _shard_dims(param_leaves, axis_size: int, axis: str) -> List[Optional[int]]:
    """Per flattened param leaf: the dim its ZeRO shard (and its moment
    shard) splits over ``axis``, or None for leaves with no divisible dim
    — exactly ``zero._zero_spec``'s choice, so the explicit path can
    never disagree with the propagation layout."""
    dims: List[Optional[int]] = []
    for leaf in param_leaves:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        spec = _zero_spec(shape, axis_size, axis, P())
        dim = None
        for d, entry in enumerate(spec):
            if entry == axis:
                dim = d
                break
        dims.append(dim)
    return dims


def _fenced(values: Tuple, token):
    """One ``optimization_barrier`` over a bucket's values plus the chain
    token. All results of the barrier are scheduled after all operands,
    so consuming the returned values orders this bucket's collectives
    after the previous bucket's — without any data dependence on
    unrelated compute (the backward producing later buckets' gradients
    keeps running)."""
    out = lax.optimization_barrier(tuple(values) + (token,))
    return out[:-1], out[-1]


def _chain(token, anchor):
    """Advance the chain token so it depends on ``anchor`` (a collective
    result): the next bucket's fence is scheduled after this bucket's
    communication was issued."""
    return lax.optimization_barrier((token, anchor))[0]


def _local_grads_and_metrics(state, full_params, batch, grad_accum: int):
    """Per-device loss backward: per-example-SUM gradients over the local
    rows plus local metric sums (loss_sum/correct/count). ``grad_accum``
    micro-batches via ``lax.scan`` against the same params — the local
    twin of ``steps.make_accum_train_step_fn``'s accumulation."""

    def micro(params, images, labels, mask):
        n = (jnp.sum(mask.astype(jnp.float32)) if mask is not None
             else jnp.asarray(float(labels.shape[0])))

        def loss_fn(p):
            logits = state.apply_fn(p, images, train=True)
            ce = cross_entropy(logits, labels, mask)
            return ce * n, (ce, logits)

        (_, (ce, logits)), g = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        if mask is not None:
            hit = hit * mask.astype(jnp.float32)
        m = MetricState(loss_sum=ce.astype(jnp.float32) * n,
                        correct=jnp.sum(hit), count=n)
        return g, m

    mask = batch.get("mask")
    if grad_accum < 2:
        return micro(full_params, batch["image"], batch["label"], mask)

    b = batch["image"].shape[0]
    if b % grad_accum:
        raise ValueError(
            f"per-device batch {b} not divisible by grad_accum {grad_accum}"
        )
    micros = jax.tree_util.tree_map(
        lambda v: v.reshape((grad_accum, b // grad_accum) + v.shape[1:]),
        batch,
    )

    def body(carry, mb):
        g_acc, m_acc = carry
        g, m = micro(full_params, mb["image"], mb["label"], mb.get("mask"))
        return (jax.tree_util.tree_map(jnp.add, g_acc, g),
                accumulate_metrics(m_acc, m)), None

    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p)), full_params)
    (g_sum, metrics), _ = lax.scan(body, (zeros, metrics_init()), micros)
    return g_sum, metrics


def _make_sharded_body(state, mesh: Mesh, axis: str, level: int,
                       bucket_mb: float, grad_accum: int):
    """The per-device step body + its shard_map specs.

    Returns ``(sharded_step, state_specs)`` where ``sharded_step(state,
    gathered, batch) -> (state, gathered, metrics)`` is the shard_map'd
    (unjitted) program — the scan epoch embeds it directly; the step
    factory jits it. For ``level=1`` the ``gathered`` argument carries
    the replicated params redundantly (identical to ``state.params``) so
    both levels share one body; the level-1 public wrappers hide it.
    """
    if level not in (1, 3):
        raise ValueError(f"zero level must be 1 or 3, got {level}")
    axis_size = mesh.shape[axis]
    param_leaves, ptree = jax.tree_util.tree_flatten(state.params)
    dims = _shard_dims(param_leaves, axis_size, axis)
    plan = bucket_plan(param_leaves, bucket_mb)
    sharding = zero_state_sharding(state, mesh, data_axis=axis, level=level)
    state_specs = jax.tree_util.tree_map(lambda ns: ns.spec, sharding)
    repl_params = jax.tree_util.tree_map(lambda _: P(), state.params)

    def body(st, gathered, batch):
        # Forward/backward against the FULL params: the carried gathered
        # copy (ZeRO-3) or the replicated state params (ZeRO-1).
        full_params = gathered if level == 3 else st.params
        g_sum, local_m = _local_grads_and_metrics(
            st, full_params, batch, grad_accum)
        n_global = lax.psum(local_m.count, axis)
        inv_n = 1.0 / jnp.maximum(n_global, 1.0)

        # Bucketized reduce-scatter: bucket k's collectives consume only
        # bucket k's gradient leaves (plus the chain token), so they can
        # issue while the backward's other buckets are still computing;
        # the chain keeps one ordered communication stream.
        g_flat = jax.tree_util.tree_flatten(g_sum)[0]
        g_shards: List = [None] * len(g_flat)
        token = jnp.zeros((), jnp.float32)
        for bucket in plan:
            fenced, token = _fenced(tuple(g_flat[i] for i in bucket), token)
            for leaf, i in zip(fenced, bucket):
                d = dims[i]
                if d is None:
                    red = lax.psum(leaf, axis)
                else:
                    red = lax.psum_scatter(
                        leaf, axis, scatter_dimension=d, tiled=True)
                g_shards[i] = red * inv_n.astype(red.dtype)
            token = _chain(token, jnp.sum(g_shards[bucket[0]]))
        grad_shards = jax.tree_util.tree_unflatten(ptree, g_shards)

        # Owner-shard optimizer update: mu/nu arrive as local shards (the
        # shard_map in_specs ARE the ZeRO layout) and Adam is elementwise,
        # so tx.update on the shard view computes exactly the owned slice
        # of the full update. ZeRO-1 slices its shard out of the
        # replicated params; ZeRO-3 params already are the shards.
        idx = lax.axis_index(axis)

        def param_shard(p, d):
            if d is None or level == 3:
                return p
            size = p.shape[d] // axis_size
            return lax.dynamic_slice_in_dim(p, idx * size, size, axis=d)

        p_shards = jax.tree_util.tree_unflatten(ptree, [
            param_shard(p, d)
            for p, d in zip(jax.tree_util.tree_flatten(st.params)[0], dims)
        ])
        updates, new_opt = st.tx.update(grad_shards, st.opt_state, p_shards)
        new_p_shards = optax.apply_updates(p_shards, updates)

        # Bucketized allgather of the updated shards, same fence chain:
        # sitting at the step's tail, each bucket's gather may overlap
        # the remaining buckets' updates and — through the carry — the
        # next step's forward up to the first use of its leaves.
        np_flat = jax.tree_util.tree_flatten(new_p_shards)[0]
        full: List = [None] * len(np_flat)
        for bucket in plan:
            fenced, token = _fenced(tuple(np_flat[i] for i in bucket), token)
            for leaf, i in zip(fenced, bucket):
                d = dims[i]
                full[i] = leaf if d is None else lax.all_gather(
                    leaf, axis, axis=d, tiled=True)
            token = _chain(token, jnp.sum(full[bucket[0]]))
        new_full = jax.tree_util.tree_unflatten(ptree, full)

        new_state = st.replace(
            step=st.step + 1,
            params=new_p_shards if level == 3 else new_full,
            opt_state=new_opt,
        )
        metrics = MetricState(
            loss_sum=lax.psum(local_m.loss_sum, axis),
            correct=lax.psum(local_m.correct, axis),
            count=n_global,
        )
        return new_state, new_full, metrics

    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, repl_params, P(axis)),
        out_specs=(state_specs, repl_params, P()),
        check_vma=False,
    )
    return sharded, state_specs


def make_overlap_train_step(state, mesh: Mesh, axis: str = "data",
                            level: int = 1, bucket_mb: float = 4.0,
                            grad_accum: int = 1):
    """Jitted overlapped-ZeRO train step.

    ``level=1``: ``step(state, batch) -> (state, MetricState)`` — the
    ``make_train_step`` signature, params replicated in the state.
    ``level=3``: ``step(state, gathered, batch) -> (state, gathered,
    MetricState)`` — ``gathered`` is the carried replicated param copy
    (``make_param_gather`` builds the first one), donated and replaced
    each step.

    ``state`` may be concrete or an ``abstract_spec`` tree — only
    shapes/dtypes, ``tx``, and ``apply_fn`` are read. The state layout
    (in/out shardings) is ``zero_state_sharding(state, mesh, level)``,
    identical to the propagation path's, so the same placed state drives
    either step.
    """
    sharded, _specs = _make_sharded_body(
        state, mesh, axis, level, bucket_mb, grad_accum)
    if level == 3:
        return jax.jit(sharded, donate_argnums=(0, 1))

    def step(st, batch):
        new_state, _full, metrics = sharded(st, st.params, batch)
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,))


def make_overlap_train_epoch(state, mesh: Mesh, axis: str = "data",
                             level: int = 1, bucket_mb: float = 4.0,
                             grad_accum: int = 1):
    """Jitted overlapped-ZeRO scan epoch (``lax.scan`` over pre-staged
    batches, the ``make_train_epoch`` shape).

    ``level=1``: ``epoch(state, batches) -> (state, MetricState)``.
    ``level=3``: ``epoch(state, gathered, batches) -> (state, gathered,
    MetricState)`` — the gathered params ride the scan carry, so step
    N's tail allgather and step N+1's forward live in one program with
    no barrier between them: the overlap the carry exists to enable.
    """
    sharded, _specs = _make_sharded_body(
        state, mesh, axis, level, bucket_mb, grad_accum)

    if level == 3:
        def epoch(st, gathered, batches):
            def body(carry, b):
                st, gp, acc = carry
                st, gp, m = sharded(st, gp, b)
                return (st, gp, accumulate_metrics(acc, m)), None

            (st, gathered, acc), _ = lax.scan(
                body, (st, gathered, metrics_init()), batches)
            return st, gathered, acc

        return jax.jit(epoch, donate_argnums=(0, 1))

    def epoch(st, batches):
        def body(carry, b):
            st, acc = carry
            st, _full, m = sharded(st, st.params, b)
            return (st, accumulate_metrics(acc, m)), None

        (st, acc), _ = lax.scan(body, (st, metrics_init()), batches)
        return st, acc

    return jax.jit(epoch, donate_argnums=(0,))


def make_param_gather(mesh: Mesh):
    """Jitted ``params -> replicated params``: builds (or rebuilds) the
    carried gathered copy from the state's shards. One allgather per
    sharded leaf, multi-host safe (an SPMD program, not a host-side
    ``device_put`` reshard)."""
    return jax.jit(lambda params: params,
                   out_shardings=NamedSharding(mesh, P()))


def make_comm_only_program(state, mesh: Mesh, axis: str = "data",
                           bucket_mb: float = 4.0):
    """Jitted ``params -> scalar`` running EXACTLY the step's collective
    sequence — the bucket-fenced gradient reduce-scatters followed by the
    bucket-fenced shard allgathers, on param-shaped values — with no
    model compute in between. ``bench.py --mode zero`` times this as the
    step's communication cost; the returned scalar folds every result in
    so nothing is dead-code-eliminated."""
    axis_size = mesh.shape[axis]
    param_leaves, ptree = jax.tree_util.tree_flatten(state.params)
    del ptree
    dims = _shard_dims(param_leaves, axis_size, axis)
    plan = bucket_plan(param_leaves, bucket_mb)

    def body(params):
        flat = jax.tree_util.tree_flatten(params)[0]
        shards: List = [None] * len(flat)
        token = jnp.zeros((), jnp.float32)
        for bucket in plan:
            fenced, token = _fenced(tuple(flat[i] for i in bucket), token)
            for leaf, i in zip(fenced, bucket):
                d = dims[i]
                shards[i] = lax.psum(leaf, axis) if d is None else \
                    lax.psum_scatter(leaf, axis, scatter_dimension=d,
                                     tiled=True)
            token = _chain(token, jnp.sum(shards[bucket[0]]))
        acc = jnp.zeros((), jnp.float32)
        for bucket in plan:
            fenced, token = _fenced(tuple(shards[i] for i in bucket), token)
            for leaf, i in zip(fenced, bucket):
                d = dims[i]
                full = leaf if d is None else lax.all_gather(
                    leaf, axis, axis=d, tiled=True)
                acc = acc + jnp.sum(full).astype(jnp.float32)
            token = _chain(token, acc)
        return acc

    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), state.params),),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
