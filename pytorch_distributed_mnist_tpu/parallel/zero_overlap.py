"""Explicit overlapped ZeRO: bucketized reduce-scatter / allgather weight
update with a compiler-visible overlap structure.

``parallel/zero.py`` shards optimizer state (ZeRO-1) and params (ZeRO-3)
purely via ``PartitionSpec``s and leaves every scheduling decision to
XLA's sharding propagation. That is the idiomatic default — but nothing
in it *expresses* the schedule the ZeRO paper ("Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training", arXiv:2004.13336)
actually wants: gradient communication overlapped with the remaining
backward, and the parameter allgather overlapped with the next step's
forward. This module writes that schedule out explicitly:

- **Same state layout as the propagation path.** The step's in/out specs
  are exactly ``zero_state_sharding``'s (per-leaf largest-divisible-dim
  sharding), so checkpoints, ``--resume auto``, and the propagation eval
  step all keep working unchanged — the two paths are interchangeable
  per state, and the equivalence suite pins them numerically equal
  (``tests/test_zero_overlap.py``).
- **Bucketized reduce-scatter** (``bucket_plan``): gradient leaves are
  size-ordered and packed into flat byte-budgeted buckets
  (``--zero-bucket-mb``). Each bucket's reduce-scatters depend only on
  that bucket's gradient leaves plus a barrier token chained from the
  previous bucket — so bucket k's communication can start the moment its
  gradients exist, while the backward still computes other buckets'
  gradients, and XLA's latency-hiding scheduler is free to overlap the
  two. ``lax.optimization_barrier`` (AD shim: ``utils/jax_compat.py``)
  provides the fences: it pins bucket order without inventing data
  dependencies on unrelated compute.
- **Carried allgather** (ZeRO-3): the step takes the previous step's
  gathered (replicated) params as an argument and returns the next
  gathered copy rebuilt from the updated shards — the allgather sits at
  the tail of step N where it can overlap metric math and, across the
  scan carry in ``make_overlap_train_epoch`` (or the Trainer's explicit
  carry in stepwise mode), the head of step N+1's forward. The carry is
  derived state: ``gathered == allgather(state.params)`` always, and is
  rebuilt from the state by ``make_param_gather`` whenever dropped.

Gradient semantics are the per-example-sum form: each device accumulates
the SUM of per-example loss gradients over its local rows (micro-batched
under ``grad_accum``), the reduce-scatter produces global sums, and one
division by the global (psum'd) example count yields exactly the
global-batch masked-mean gradient for any mask distribution — the same
quantity the propagation path's autodiff computes, equal up to float
reduction order.

- **Two-tier (DCN x ICI) schedule** on hierarchical meshes
  (``parallel/mesh.py make_hier_mesh``): the arXiv:2004.13336 multi-pod
  form. Gradients **reduce-scatter within the slice over ``ici``**
  (fast tier, full gradient bytes), then **only the owner's 1/ici_size
  shard all-reduces across slices over ``dcn``** (slow tier — DCN
  traffic shrinks by the slice width), the optimizer updates the shard
  (replicated across slices, deterministically identical), and the
  updated shards **allgather back over ``ici``** — DCN never carries a
  full parameter. Each tier gets its own bucket budget (``bucket_mb``
  for ICI, ``bucket_mb_dcn`` for the shard-sized DCN buckets) and both
  tiers thread through the SAME ``optimization_barrier`` fence chain,
  one ordered communication stream. The state layout is
  ``zero_state_sharding``'s hierarchical resolution (shards over
  ``ici``, replicated over ``dcn``), so checkpoints interop through the
  world-agnostic reshard path exactly like any other layout change.

Scope: the pure data-parallel mesh (``data`` axis only, flat or
hierarchical). TP/EP rule tables and pipeline base shardings stay on
the propagation path, which remains the default (``cli.py`` gates the
compositions).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy
from pytorch_distributed_mnist_tpu.ops.metrics import MetricState, metrics_init
from pytorch_distributed_mnist_tpu.parallel.mesh import (
    HIER_DATA_AXES,
    is_hier_mesh,
)
from pytorch_distributed_mnist_tpu.parallel.zero import _zero_spec, zero_state_sharding
from pytorch_distributed_mnist_tpu.train.steps import accumulate_metrics


def _leaf_bytes(leaf) -> int:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    dtype = np.dtype(getattr(leaf, "dtype", np.float32))
    return int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape \
        else dtype.itemsize


def bucket_plan(leaves, bucket_mb: float) -> List[List[int]]:
    """Pack flattened-leaf indices into size-ordered byte-budgeted buckets.

    Leaves are ordered largest-first (ties broken by flat index, so the
    plan is deterministic across runs and hosts — the same property the
    ``_zero_spec`` tie-break pins for dim choice) and packed greedily:
    a bucket closes when adding the next leaf would exceed
    ``bucket_mb`` MiB. A single leaf larger than the budget gets its own
    bucket. Each bucket is one communication-issue group in the step:
    its collectives are fenced together and chained after the previous
    bucket's.
    """
    if bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
    budget = int(bucket_mb * (1 << 20))
    order = sorted(range(len(leaves)),
                   key=lambda i: (-_leaf_bytes(leaves[i]), i))
    plan: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in order:
        nbytes = _leaf_bytes(leaves[i])
        if cur and cur_bytes + nbytes > budget:
            plan.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        plan.append(cur)
    return plan


def _shard_dims(param_leaves, axis_size: int, axis: str) -> List[Optional[int]]:
    """Per flattened param leaf: the dim its ZeRO shard (and its moment
    shard) splits over ``axis``, or None for leaves with no divisible dim
    — exactly ``zero._zero_spec``'s choice, so the explicit path can
    never disagree with the propagation layout."""
    dims: List[Optional[int]] = []
    for leaf in param_leaves:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        spec = _zero_spec(shape, axis_size, axis, P())
        dim = None
        for d, entry in enumerate(spec):
            if entry == axis:
                dim = d
                break
        dims.append(dim)
    return dims


class _ShardView:
    """Shape/dtype stand-in for one leaf's post-reduce-scatter shard —
    what the DCN tier actually moves, so its bucket plan budgets shard
    bytes, not full-leaf bytes."""

    def __init__(self, leaf, dim: Optional[int], axis_size: int):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if dim is not None:
            shape = (shape[:dim] + (shape[dim] // axis_size,)
                     + shape[dim + 1:])
        self.shape = shape
        self.dtype = np.dtype(getattr(leaf, "dtype", np.float32))


def _dcn_bucket_plan(param_leaves, dims, axis_size: int,
                     bucket_mb: float) -> List[List[int]]:
    """The DCN tier's bucket plan: the same deterministic packing as
    ``bucket_plan``, but over SHARD-sized views (1/axis_size of each
    sharded leaf) — the cross-slice all-reduce only ever carries the
    owner shards, so its buckets budget those bytes independently of
    the ICI tier's full-gradient buckets (``--zero-bucket-mb-dcn``)."""
    views = [_ShardView(leaf, d, axis_size)
             for leaf, d in zip(param_leaves, dims)]
    return bucket_plan(views, bucket_mb)


def _tier_axes(mesh: Mesh, axis):
    """(shard_axis, outer_axis, all_axes) for the mesh: on a flat mesh
    the shard axis IS the whole data axis and there is no outer tier;
    on a hierarchical mesh ZeRO shards over ``ici`` and the owner
    shards cross slices over ``dcn``."""
    if axis == "data" and is_hier_mesh(mesh):
        return "ici", "dcn", HIER_DATA_AXES
    return axis, None, axis


def _fenced(values: Tuple, token):
    """One ``optimization_barrier`` over a bucket's values plus the chain
    token. All results of the barrier are scheduled after all operands,
    so consuming the returned values orders this bucket's collectives
    after the previous bucket's — without any data dependence on
    unrelated compute (the backward producing later buckets' gradients
    keeps running)."""
    out = lax.optimization_barrier(tuple(values) + (token,))
    return out[:-1], out[-1]


def _chain(token, anchor):
    """Advance the chain token so it depends on ``anchor`` (a collective
    result): the next bucket's fence is scheduled after this bucket's
    communication was issued."""
    return lax.optimization_barrier((token, anchor))[0]


def _local_grads_and_metrics(state, full_params, batch, grad_accum: int):
    """Per-device loss backward: per-example-SUM gradients over the local
    rows plus local metric sums (loss_sum/correct/count). ``grad_accum``
    micro-batches via ``lax.scan`` against the same params — the local
    twin of ``steps.make_accum_train_step_fn``'s accumulation."""

    def micro(params, images, labels, mask):
        n = (jnp.sum(mask.astype(jnp.float32)) if mask is not None
             else jnp.asarray(float(labels.shape[0])))

        def loss_fn(p):
            logits = state.apply_fn(p, images, train=True)
            ce = cross_entropy(logits, labels, mask)
            return ce * n, (ce, logits)

        (_, (ce, logits)), g = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        if mask is not None:
            hit = hit * mask.astype(jnp.float32)
        m = MetricState(loss_sum=ce.astype(jnp.float32) * n,
                        correct=jnp.sum(hit), count=n)
        return g, m

    mask = batch.get("mask")
    if grad_accum < 2:
        return micro(full_params, batch["image"], batch["label"], mask)

    b = batch["image"].shape[0]
    if b % grad_accum:
        raise ValueError(
            f"per-device batch {b} not divisible by grad_accum {grad_accum}"
        )
    micros = jax.tree_util.tree_map(
        lambda v: v.reshape((grad_accum, b // grad_accum) + v.shape[1:]),
        batch,
    )

    def body(carry, mb):
        g_acc, m_acc = carry
        g, m = micro(full_params, mb["image"], mb["label"], mb.get("mask"))
        return (jax.tree_util.tree_map(jnp.add, g_acc, g),
                accumulate_metrics(m_acc, m)), None

    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p)), full_params)
    (g_sum, metrics), _ = lax.scan(body, (zeros, metrics_init()), micros)
    return g_sum, metrics


def _make_sharded_body(state, mesh: Mesh, axis: str, level: int,
                       bucket_mb: float, grad_accum: int,
                       bucket_mb_dcn: Optional[float] = None):
    """The per-device step body + its shard_map specs.

    Returns ``(sharded_step, state_specs)`` where ``sharded_step(state,
    gathered, batch) -> (state, gathered, metrics)`` is the shard_map'd
    (unjitted) program — the scan epoch embeds it directly; the step
    factory jits it. For ``level=1`` the ``gathered`` argument carries
    the replicated params redundantly (identical to ``state.params``) so
    both levels share one body; the level-1 public wrappers hide it.

    On a hierarchical mesh the body runs the two-tier schedule: RS over
    ``ici``, the owner shards all-reduced over ``dcn`` in their own
    ``bucket_mb_dcn``-budgeted buckets, AG over ``ici`` — all through
    the one fence chain.
    """
    if level not in (1, 3):
        raise ValueError(f"zero level must be 1 or 3, got {level}")
    shard_axis, outer_axis, all_axes = _tier_axes(mesh, axis)
    axis_size = mesh.shape[shard_axis]
    param_leaves, ptree = jax.tree_util.tree_flatten(state.params)
    dims = _shard_dims(param_leaves, axis_size, shard_axis)
    plan = bucket_plan(param_leaves, bucket_mb)
    dcn_plan = (_dcn_bucket_plan(param_leaves, dims, axis_size,
                                 bucket_mb_dcn or bucket_mb)
                if outer_axis is not None else None)
    sharding = zero_state_sharding(state, mesh, data_axis=axis, level=level)
    state_specs = jax.tree_util.tree_map(lambda ns: ns.spec, sharding)
    repl_params = jax.tree_util.tree_map(lambda _: P(), state.params)

    def body(st, gathered, batch):
        # Forward/backward against the FULL params: the carried gathered
        # copy (ZeRO-3) or the replicated state params (ZeRO-1).
        full_params = gathered if level == 3 else st.params
        g_sum, local_m = _local_grads_and_metrics(
            st, full_params, batch, grad_accum)
        n_global = lax.psum(local_m.count, all_axes)
        inv_n = 1.0 / jnp.maximum(n_global, 1.0)

        # Bucketized reduce-scatter over the shard (ICI) tier: bucket
        # k's collectives consume only bucket k's gradient leaves (plus
        # the chain token), so they can issue while the backward's other
        # buckets are still computing; the chain keeps one ordered
        # communication stream.
        g_flat = jax.tree_util.tree_flatten(g_sum)[0]
        g_shards: List = [None] * len(g_flat)
        token = jnp.zeros((), jnp.float32)
        for bucket in plan:
            fenced, token = _fenced(tuple(g_flat[i] for i in bucket), token)
            for leaf, i in zip(fenced, bucket):
                d = dims[i]
                if d is None:
                    red = lax.psum(leaf, shard_axis)
                else:
                    red = lax.psum_scatter(
                        leaf, shard_axis, scatter_dimension=d, tiled=True)
                g_shards[i] = red * inv_n.astype(red.dtype)
            token = _chain(token, jnp.sum(g_shards[bucket[0]]))

        if outer_axis is not None:
            # DCN tier: each intra-slice reduce-scatter left every
            # (slice, ici-rank) holding its slice's PARTIAL sum of shard
            # i; one all-reduce across slices of just that 1/ici_size
            # shard completes the global sum — DCN moves shard bytes,
            # never full gradients. Shard-sized buckets, same chain.
            for bucket in dcn_plan:
                fenced, token = _fenced(
                    tuple(g_shards[i] for i in bucket), token)
                for leaf, i in zip(fenced, bucket):
                    g_shards[i] = lax.psum(leaf, outer_axis)
                token = _chain(token, jnp.sum(g_shards[bucket[0]]))
        grad_shards = jax.tree_util.tree_unflatten(ptree, g_shards)

        # Owner-shard optimizer update: mu/nu arrive as local shards (the
        # shard_map in_specs ARE the ZeRO layout) and Adam is elementwise,
        # so tx.update on the shard view computes exactly the owned slice
        # of the full update. ZeRO-1 slices its shard out of the
        # replicated params; ZeRO-3 params already are the shards. On the
        # hierarchical mesh the shard index is the ICI coordinate alone:
        # every slice's rank i runs the identical update on identical
        # globally-summed gradients (replicated over dcn by construction).
        idx = lax.axis_index(shard_axis)

        def param_shard(p, d):
            if d is None or level == 3:
                return p
            size = p.shape[d] // axis_size
            return lax.dynamic_slice_in_dim(p, idx * size, size, axis=d)

        p_shards = jax.tree_util.tree_unflatten(ptree, [
            param_shard(p, d)
            for p, d in zip(jax.tree_util.tree_flatten(st.params)[0], dims)
        ])
        updates, new_opt = st.tx.update(grad_shards, st.opt_state, p_shards)
        new_p_shards = optax.apply_updates(p_shards, updates)

        # Bucketized allgather of the updated shards, same fence chain:
        # sitting at the step's tail, each bucket's gather may overlap
        # the remaining buckets' updates and — through the carry — the
        # next step's forward up to the first use of its leaves. Over
        # the shard (ICI) tier only: cross-slice copies of the gathered
        # params are already identical, so DCN carries nothing here.
        np_flat = jax.tree_util.tree_flatten(new_p_shards)[0]
        full: List = [None] * len(np_flat)
        for bucket in plan:
            fenced, token = _fenced(tuple(np_flat[i] for i in bucket), token)
            for leaf, i in zip(fenced, bucket):
                d = dims[i]
                full[i] = leaf if d is None else lax.all_gather(
                    leaf, shard_axis, axis=d, tiled=True)
            token = _chain(token, jnp.sum(full[bucket[0]]))
        new_full = jax.tree_util.tree_unflatten(ptree, full)

        new_state = st.replace(
            step=st.step + 1,
            params=new_p_shards if level == 3 else new_full,
            opt_state=new_opt,
        )
        metrics = MetricState(
            loss_sum=lax.psum(local_m.loss_sum, all_axes),
            correct=lax.psum(local_m.correct, all_axes),
            count=n_global,
        )
        return new_state, new_full, metrics

    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, repl_params, P(all_axes)),
        out_specs=(state_specs, repl_params, P()),
        check_vma=False,
    )
    return sharded, state_specs


def make_overlap_train_step(state, mesh: Mesh, axis: str = "data",
                            level: int = 1, bucket_mb: float = 4.0,
                            grad_accum: int = 1,
                            bucket_mb_dcn: Optional[float] = None):
    """Jitted overlapped-ZeRO train step.

    ``level=1``: ``step(state, batch) -> (state, MetricState)`` — the
    ``make_train_step`` signature, params replicated in the state.
    ``level=3``: ``step(state, gathered, batch) -> (state, gathered,
    MetricState)`` — ``gathered`` is the carried replicated param copy
    (``make_param_gather`` builds the first one), donated and replaced
    each step.

    ``state`` may be concrete or an ``abstract_spec`` tree — only
    shapes/dtypes, ``tx``, and ``apply_fn`` are read. The state layout
    (in/out shardings) is ``zero_state_sharding(state, mesh, level)``,
    identical to the propagation path's, so the same placed state drives
    either step. On a hierarchical mesh the step runs the two-tier
    schedule; ``bucket_mb_dcn`` budgets the cross-slice shard buckets
    (defaults to ``bucket_mb``, ignored on flat meshes).
    """
    sharded, _specs = _make_sharded_body(
        state, mesh, axis, level, bucket_mb, grad_accum,
        bucket_mb_dcn=bucket_mb_dcn)
    if level == 3:
        return jax.jit(sharded, donate_argnums=(0, 1))

    def step(st, batch):
        new_state, _full, metrics = sharded(st, st.params, batch)
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,))


def make_overlap_train_epoch(state, mesh: Mesh, axis: str = "data",
                             level: int = 1, bucket_mb: float = 4.0,
                             grad_accum: int = 1,
                             bucket_mb_dcn: Optional[float] = None):
    """Jitted overlapped-ZeRO scan epoch (``lax.scan`` over pre-staged
    batches, the ``make_train_epoch`` shape).

    ``level=1``: ``epoch(state, batches) -> (state, MetricState)``.
    ``level=3``: ``epoch(state, gathered, batches) -> (state, gathered,
    MetricState)`` — the gathered params ride the scan carry, so step
    N's tail allgather and step N+1's forward live in one program with
    no barrier between them: the overlap the carry exists to enable.
    """
    sharded, _specs = _make_sharded_body(
        state, mesh, axis, level, bucket_mb, grad_accum,
        bucket_mb_dcn=bucket_mb_dcn)

    if level == 3:
        def epoch(st, gathered, batches):
            def body(carry, b):
                st, gp, acc = carry
                st, gp, m = sharded(st, gp, b)
                return (st, gp, accumulate_metrics(acc, m)), None

            (st, gathered, acc), _ = lax.scan(
                body, (st, gathered, metrics_init()), batches)
            return st, gathered, acc

        return jax.jit(epoch, donate_argnums=(0, 1))

    def epoch(st, batches):
        def body(carry, b):
            st, acc = carry
            st, _full, m = sharded(st, st.params, b)
            return (st, accumulate_metrics(acc, m)), None

        (st, acc), _ = lax.scan(body, (st, metrics_init()), batches)
        return st, acc

    return jax.jit(epoch, donate_argnums=(0,))


def make_param_gather(mesh: Mesh):
    """Jitted ``params -> replicated params``: builds (or rebuilds) the
    carried gathered copy from the state's shards. One allgather per
    sharded leaf, multi-host safe (an SPMD program, not a host-side
    ``device_put`` reshard)."""
    return jax.jit(lambda params: params,
                   out_shardings=NamedSharding(mesh, P()))


def make_comm_only_program(state, mesh: Mesh, axis: str = "data",
                           bucket_mb: float = 4.0,
                           bucket_mb_dcn: Optional[float] = None,
                           tier: Optional[str] = None):
    """Jitted ``params -> scalar`` running EXACTLY the step's collective
    sequence — the bucket-fenced gradient reduce-scatters (ICI tier),
    on a hierarchical mesh the bucket-fenced cross-slice shard
    all-reduces (DCN tier), and the bucket-fenced shard allgathers — on
    param-shaped values with no model compute in between. ``bench.py
    --mode zero`` times this as the step's communication cost; the
    returned scalar folds every result in so nothing is
    dead-code-eliminated.

    ``tier`` isolates ONE tier of a hierarchical mesh for the bench's
    per-tier breakdown: ``'ici'`` runs only the intra-slice RS + AG,
    ``'dcn'`` only the cross-slice shard all-reduces (the shard slice
    itself is a local copy, not communication). ``tier`` on a flat mesh
    is an error — a flat mesh has no tiers to isolate.
    """
    shard_axis, outer_axis, _all_axes = _tier_axes(mesh, axis)
    if tier not in (None, "ici", "dcn"):
        raise ValueError(f"tier must be None, 'ici' or 'dcn', got {tier!r}")
    if tier is not None and outer_axis is None:
        raise ValueError(
            f"tier={tier!r} needs a hierarchical ('dcn', 'ici') mesh; "
            f"this flat mesh has no tiers")
    axis_size = mesh.shape[shard_axis]
    param_leaves, ptree = jax.tree_util.tree_flatten(state.params)
    del ptree
    dims = _shard_dims(param_leaves, axis_size, shard_axis)
    plan = bucket_plan(param_leaves, bucket_mb)
    dcn_plan = (_dcn_bucket_plan(param_leaves, dims, axis_size,
                                 bucket_mb_dcn or bucket_mb)
                if outer_axis is not None else None)

    def body(params):
        flat = jax.tree_util.tree_flatten(params)[0]
        shards: List = [None] * len(flat)
        token = jnp.zeros((), jnp.float32)
        if tier == "dcn":
            # The DCN tier alone: slice each leaf down to this rank's
            # shard locally (a copy, not communication) so the timed
            # collectives move exactly the shard bytes the real
            # schedule sends across slices.
            idx = lax.axis_index(shard_axis)
            for i, leaf in enumerate(flat):
                d = dims[i]
                if d is None:
                    shards[i] = leaf
                else:
                    size = leaf.shape[d] // axis_size
                    shards[i] = lax.dynamic_slice_in_dim(
                        leaf, idx * size, size, axis=d)
        else:
            for bucket in plan:
                fenced, token = _fenced(
                    tuple(flat[i] for i in bucket), token)
                for leaf, i in zip(fenced, bucket):
                    d = dims[i]
                    shards[i] = lax.psum(leaf, shard_axis) if d is None \
                        else lax.psum_scatter(
                            leaf, shard_axis, scatter_dimension=d,
                            tiled=True)
                token = _chain(token, jnp.sum(shards[bucket[0]]))
        if outer_axis is not None and tier != "ici":
            for bucket in dcn_plan:
                fenced, token = _fenced(
                    tuple(shards[i] for i in bucket), token)
                for leaf, i in zip(fenced, bucket):
                    shards[i] = lax.psum(leaf, outer_axis)
                token = _chain(token, jnp.sum(shards[bucket[0]]))
        acc = jnp.zeros((), jnp.float32)
        if tier == "dcn":
            # No allgather on this tier — fold the reduced shards. The
            # per-rank folds differ across ici shards, so one scalar
            # psum makes the P() output well-defined (negligible next
            # to the timed shard all-reduces).
            for s in shards:
                acc = acc + jnp.sum(s).astype(jnp.float32)
            return lax.psum(acc, shard_axis)
        for bucket in plan:
            fenced, token = _fenced(tuple(shards[i] for i in bucket), token)
            for leaf, i in zip(fenced, bucket):
                d = dims[i]
                full = leaf if d is None else lax.all_gather(
                    leaf, shard_axis, axis=d, tiled=True)
                acc = acc + jnp.sum(full).astype(jnp.float32)
            token = _chain(token, acc)
        return acc

    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), state.params),),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
