"""Local N-process spawner: the ``mp.spawn`` launch mode, TPU-framework style.

The reference's primary launch path forks one worker per GPU from a single
command (``demo_spawn`` -> ``mp.spawn(run_spawn, nprocs=ngpus)``,
``/root/reference/multi_proc_single_gpu.py:273-285``), with rank = spawned
process id and a loopback TCP rendezvous (``:326``). On TPU the runtime is
one process per *host*, so the faithful analog is spawning N local
*host* processes — each owning one CPU device — that rendezvous through
``jax.distributed.initialize`` on a free loopback port. That is exactly the
world a real N-host pod presents, minus the hardware: every multi-host code
path (``make_array_from_process_local_data``, disjoint per-host sampler
shards, cross-process metric psums, process-0-only checkpoint writes, the
sharded ``.ckpt`` layout) executes for real.

Children are forced onto the CPU backend: N processes cannot share one TPU
chip (the TPU rule is one process per host — on real pods no spawner is
needed at all), so ``--spawn`` is the local-simulation launcher, the moral
equivalent of running the reference on a machine with N GPUs.

Unlike the reference there is no second, comment-toggled launch mode
(``:353-359``): ``--spawn N`` composes with every other flag, and explicit
``--coordinator/--process-id`` remain available for real multi-host runs.
"""

from __future__ import annotations

import os
import re
import socket
import subprocess
import sys
import tempfile
from typing import List, Optional, Sequence


def free_port() -> int:
    """A free loopback port for the coordinator (the reference hard-codes
    ``tcp://127.0.0.1:23456``, ``:326``; a bound-then-released port avoids
    collisions between concurrent runs)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def strip_flags(argv: Sequence[str], flags: dict) -> List[str]:
    """Remove launcher-consumed flags from an argv copy.

    ``flags`` maps flag name -> number of value tokens to drop with it
    (``=``-joined forms are always one token). The ONE argv-stripping
    loop for every spawner-side flag — ``--spawn`` here, the elastic
    supervisor's ``--elastic``/``--min-world``/``--resume`` rewrites
    (``runtime/elastic.py``) — so a flag-syntax fix lands once."""
    out: List[str] = []
    skip = 0
    for a in argv:
        if skip:
            skip -= 1
            continue
        if a in flags:
            skip = flags[a]
            continue
        if any(a.startswith(flag + "=") for flag in flags):
            continue
        out.append(a)
    return out


def strip_spawn_flag(argv: Sequence[str]) -> List[str]:
    """Remove ``--spawn N`` / ``--spawn=N`` from an argv copy."""
    return strip_flags(argv, {"--spawn": 1})


def _child_env() -> dict:
    """Environment for one spawned host process: CPU backend, exactly ONE
    local device (any ``xla_force_host_platform_device_count`` from the
    caller — e.g. the test suite's 8-device conftest — is stripped so the
    N-process world has N global devices, like N one-chip hosts)."""
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", flags
    ).strip()
    env["XLA_FLAGS"] = flags
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def spawn_local(
    nprocs: int,
    argv: Sequence[str],
    *,
    timeout: Optional[float] = None,
) -> int:
    """Fork ``nprocs`` local host processes running the CLI; return max rc.

    Rank 0's output streams to this terminal live (the reference prints
    from every rank, ``:238-242``; here non-zero ranks are mostly silent by
    design — ``log0`` — so their output is captured to temp files and only
    replayed on failure). Rank assignment is spawn order, the reference's
    ``run_spawn(proc_id)`` convention (``:273-276``).
    """
    if nprocs < 2:
        raise ValueError(f"--spawn needs >= 2 processes, got {nprocs}")
    child_argv = strip_spawn_flag(argv)
    port = free_port()
    env = _child_env()

    procs = []
    logs = []
    for rank in range(nprocs):
        cmd = [
            sys.executable, "-m", "pytorch_distributed_mnist_tpu",
            *child_argv,
            "--coordinator", f"127.0.0.1:{port}",
            "--num-processes", str(nprocs),
            "--process-id", str(rank),
        ]
        if rank == 0:
            procs.append(subprocess.Popen(cmd, env=env))
            logs.append(None)
        else:
            # Temp files, not pipes: a filled pipe buffer would deadlock a
            # chatty child against a parent that only reads at the end.
            log = tempfile.TemporaryFile(mode="w+")
            procs.append(subprocess.Popen(
                cmd, env=env, stdout=log, stderr=subprocess.STDOUT))
            logs.append(log)

    rcs = []
    try:
        for p in procs:
            rcs.append(p.wait(timeout=timeout))
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    finally:
        for rank, (rc_p, log) in enumerate(zip(procs, logs)):
            if log is None:
                continue
            if rc_p.returncode not in (0, None):
                log.seek(0)
                tail = log.read()[-4000:]
                print(f"--- spawned process {rank} failed "
                      f"(rc={rc_p.returncode}) ---\n{tail}",
                      file=sys.stderr)
            log.close()
    # A signal-killed child has a NEGATIVE returncode; max() over mixed
    # signs could report 0 despite a crashed rank. Any nonzero rc is a
    # failed run: surface the first one (signals map to the shell's 128+N).
    bad = [rc for rc in rcs if rc != 0]
    if not bad:
        return 0
    return bad[0] if bad[0] > 0 else 128 - bad[0]
