"""ZeRO-1: optimizer-state sharding over the data axis (GSPMD).

The reference's optimizer keeps full Adam moments on every rank
(``/root/reference/multi_proc_single_gpu.py:191``; SURVEY.md section 2c
marks ZeRO/FSDP ABSENT). Here ZeRO-1 is exactly what the N-D-mesh design
promised it would be (SURVEY.md section 2c closing note): a
``PartitionSpec`` change, not new machinery. Adam's ``mu``/``nu`` pytrees
get sharded along the ``data`` mesh axis; params, step counter, and
hyperparams stay replicated (the DDP layout). XLA's sharding propagation
then materializes the ZeRO communication pattern itself — the gradient
AllReduce becomes a ReduceScatter into the moment shards plus an AllGather
of the parameter update — with no hand-written collectives.

Per-leaf placement: moments are sharded along each leaf's LARGEST
axis-size-divisible dimension (conv kernels are small on dim 0 — e.g.
``(3, 3, 1, 32)`` — so a dim-0-only rule would shard almost nothing of a
CNN). Leaves with no divisible dimension, and leaves a TP rule already
lays out (TP moments must share the param layout), replicate/keep as-is.

Composes with the tensor-parallel rule table (``parallel/tensor.py``):
pass its ``rules`` and the base layout is applied first, ZeRO sharding
only claims dimensions TP left unsharded on moment leaves it skipped.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.parallel.mesh import is_hier_mesh
from pytorch_distributed_mnist_tpu.parallel.tensor import leaf_spec, _path_keys


def _is_moment_path(path) -> bool:
    return any(k in ("mu", "nu") for k in _path_keys(path))


def _zero_spec(shape: Tuple[int, ...], axis_size: int, axis: str, base: P) -> P:
    """Shard the largest dimension divisible by ``axis_size`` that ``base``
    leaves unsharded; return ``base`` unchanged if none qualifies.

    Equal-size ties break to the LOWEST dim index, explicitly: the dim
    choice decides shard layout (and the overlapped path's bucket
    contents, parallel/zero_overlap.py), so it must be stable across
    runs, hosts, and interpreter versions — never an accident of which
    maximal candidate an iteration order surfaced first. Pinned by
    ``tests/test_zero1.py::test_zero_spec_tie_breaks_to_lowest_dim``.
    """
    entries = list(base) + [None] * (len(shape) - len(base))
    candidates = [
        d for d in range(len(shape))
        if entries[d] is None and shape[d] >= axis_size and shape[d] % axis_size == 0
    ]
    if not candidates:
        return base
    best = min(candidates, key=lambda d: (-shape[d], d))
    entries[best] = axis
    return P(*entries)


def _is_param_path(path) -> bool:
    keys = _path_keys(path)
    return bool(keys) and keys[0] == "params"


def zero_state_sharding(
    state,
    mesh: Mesh,
    data_axis: str = "data",
    rules: Optional[Dict[Tuple[str, str], P]] = None,
    level: int = 1,
    base_sharding=None,
):
    """NamedSharding pytree for a TrainState with ZeRO-style sharding.

    ``level=1``: Adam ``mu``/``nu`` sharded over ``data_axis``, params
    replicated (the classic optimizer-state partition). ``level=3``:
    params sharded the same way too (FSDP-style) — XLA's sharding
    propagation inserts the AllGather before each use in forward/backward
    and a ReduceScatter for the gradients, so between steps every host
    stores only its 1/N param shard.

    ``rules`` is an optional TP rule table (``parallel/tensor.py``); leaves
    it matches keep the TP layout everywhere (params AND moments — TP
    moments must mirror their params), and ZeRO sharding applies to the
    remaining leaves only.

    ``base_sharding`` is an alternative base: a full NamedSharding pytree
    (e.g. the pipeline layout from ``parallel/pipeline_vit.py``, blocks
    sharded on 'stage'). Unlike the conservative rules path, claimed
    moment leaves get ``data_axis`` ADDED on their largest still-unsharded
    divisible dimension — a stage-sharded block moment becomes
    stage x data sharded, which is exactly the PP x ZeRO-1 partition.
    Mutually exclusive with ``rules``.
    """
    if level not in (1, 3):
        raise ValueError(f"zero level must be 1 or 3, got {level}")
    if data_axis == "data" and "data" not in mesh.axis_names \
            and is_hier_mesh(mesh):
        # Hierarchical (DCN x ICI) mesh: ZeRO shards WITHIN the slice
        # only (the arXiv:2004.13336 multi-pod partition — shard degree
        # = slice size, replicated across slices), so the weight-update
        # collectives it implies ride the fast ICI tier and only the
        # 1/ici_size owner shards ever cross DCN
        # (parallel/zero_overlap.py writes that schedule explicitly).
        data_axis = "ici"
    if rules and base_sharding is not None:
        raise ValueError("pass rules or base_sharding, not both")
    if level == 3 and base_sharding is not None:
        # ZeRO-3 would add a data axis onto the base layout's params —
        # e.g. re-sharding stage-sharded pipeline blocks, a layout no
        # step program expects. Enforced here, not just in the CLI, so
        # library callers hit the same wall.
        raise ValueError(
            "level=3 does not compose with base_sharding: the base "
            "layout owns the param placement; use level=1"
        )
    rules = rules or {}
    axis_size = mesh.shape[data_axis]

    def claimed_spec(shape: Tuple[int, ...], base: P) -> NamedSharding:
        return NamedSharding(mesh, _zero_spec(shape, axis_size, data_axis, base))

    if base_sharding is not None:
        def spec_from_base(path, leaf, base_ns):
            if not isinstance(base_ns, NamedSharding):
                raise ValueError(
                    f"base_sharding leaves must be NamedSharding, got "
                    f"{type(base_ns).__name__} at {jax.tree_util.keystr(path)}"
                )
            # level 3 is rejected above: the base layout owns params, so
            # only moment leaves are ever claimed here.
            if not _is_moment_path(path):
                return base_ns
            shape = tuple(getattr(leaf, "shape", ()) or ())
            return claimed_spec(shape, base_ns.spec)

        return jax.tree_util.tree_map_with_path(
            spec_from_base, state, base_sharding
        )

    def spec_for(path, leaf):
        base = leaf_spec(path, rules)
        claimed = _is_moment_path(path) or (
            level == 3 and _is_param_path(path)
        )
        if not claimed:
            return NamedSharding(mesh, base)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if base != P():
            return NamedSharding(mesh, base)  # TP-ruled leaf: keep layout
        return claimed_spec(shape, base)

    return jax.tree_util.tree_map_with_path(spec_for, state)


def zero1_state_sharding(
    state,
    mesh: Mesh,
    data_axis: str = "data",
    rules: Optional[Dict[Tuple[str, str], P]] = None,
):
    """ZeRO-1 sharding tree (see ``zero_state_sharding``, level 1)."""
    return zero_state_sharding(state, mesh, data_axis, rules, level=1)


def shard_state_zero(state, mesh: Mesh, data_axis: str = "data",
                     rules: Optional[Dict[Tuple[str, str], P]] = None,
                     level: int = 1, base_sharding=None):
    """Place a TrainState onto the mesh with ZeRO-``level`` sharding.

    Multi-host placement goes through ``parallel.mesh.place_state`` (each
    host materializes its shards from its full host copy; ``device_put``
    of committed arrays onto cross-host shardings is unsupported).
    """
    from pytorch_distributed_mnist_tpu.parallel.mesh import place_state

    sharding = zero_state_sharding(state, mesh, data_axis, rules, level,
                                   base_sharding)
    return place_state(state, sharding), sharding


def shard_state_zero1(state, mesh: Mesh, data_axis: str = "data",
                      rules: Optional[Dict[Tuple[str, str], P]] = None):
    """ZeRO-1 placement (see ``shard_state_zero``)."""
    return shard_state_zero(state, mesh, data_axis, rules, level=1)
