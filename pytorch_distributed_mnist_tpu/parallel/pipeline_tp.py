"""Pipeline x tensor parallelism: Megatron collectives inside the GPipe body.

Round-2 VERDICT flagged PP x TP as a rejected composition. The obstacle is
structural: DP x TP alone rides GSPMD (``parallel/tensor.py`` annotates
weights, XLA inserts the column/row-parallel collectives), but the pipeline
is an *explicit* shard_map program (``parallel/pipeline.py``) — and inside
a shard_map body there is no sharding propagation, so the TP matmuls must
close their own partial sums. This module supplies exactly that: the
transformer block re-expressed with explicit ``lax.psum`` over the
``model`` axis, run as the stage body of the unchanged GPipe scan on a
``data x stage x model`` mesh.

Layout note: the GSPMD rule table shards the flat ``(C, 3C)`` qkv kernel on
its output dim, which is *not* head-aligned (the 3C dim unpacks as
(3, H, D) — a contiguous 3C/tp slice straddles q/k/v). Explicit TP gets to
pick the layout, so here the attention kernels are stored head-major —
qkv ``(C, 3, H, D)``, proj ``(H, D, C)`` — and sharded on H: each model
rank owns ``H/tp`` whole heads, attention runs locally per head, and only
proj/mlp2 partial sums cross the axis (one psum each, the classic Megatron
pattern: 2 AllReduces per block per direction, riding ICI).

Parity contract: ``tp_block_apply`` reproduces ``models/attention.py``'s
``TransformerBlock`` math exactly (same flax LayerNorm/gelu modules, same
bf16-compute/f32-param policy); ``split_vit_params_tp`` /
``merge_vit_params_tp`` are bijective reshapes of the standard flax tree
(reference model zoo contrast: ``/root/reference/multi_proc_single_gpu.py
:119-126`` has a single Linear and no parallelism at all, SURVEY.md §2c).
Pinned by tests/test_pipeline_tp.py against the sequential dense model.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import flax.linen as nn

from pytorch_distributed_mnist_tpu.models.attention import (
    VisionTransformer,
    patchify,
)
from pytorch_distributed_mnist_tpu.ops.attention import full_attention
from pytorch_distributed_mnist_tpu.parallel.pipeline import pipeline_apply
from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
    merge_vit_params,
    split_vit_params,
)

__all__ = [
    "split_vit_params_tp",
    "merge_vit_params_tp",
    "make_pipelined_tp_vit_apply",
    "pipelined_tp_state_sharding",
    "create_pipelined_tp_vit_state",
]


def split_vit_params_tp(params, num_heads: int):
    """Standard flax ViT tree -> pipelined layout with head-major attention.

    Same {embed, blocks, head} grouping as ``split_vit_params`` (leading
    (depth,) dim on every blocks leaf), with the attention leaves reshaped
    so the head dim is a real array axis a PartitionSpec can name:
    qkv kernel (depth, C, 3C) -> (depth, C, 3, H, D); qkv bias likewise;
    proj kernel (depth, C, C) -> (depth, H, D, C). Pure reshapes: bitwise
    inverse via ``merge_vit_params_tp``.
    """
    split = split_vit_params(params)
    attn = dict(split["blocks"]["attn"])
    qkv_k = attn["qkv"]["kernel"]
    depth, c, three_c = qkv_k.shape
    h = num_heads
    d = c // h
    assert three_c == 3 * c, (qkv_k.shape, c)
    attn["qkv"] = {
        "kernel": qkv_k.reshape(depth, c, 3, h, d),
        "bias": attn["qkv"]["bias"].reshape(depth, 3, h, d),
    }
    attn["proj"] = {
        "kernel": attn["proj"]["kernel"].reshape(depth, h, d, c),
        "bias": attn["proj"]["bias"],
    }
    blocks = dict(split["blocks"])
    blocks["attn"] = attn
    return {"embed": split["embed"], "blocks": blocks, "head": split["head"]}


def merge_vit_params_tp(split_tp):
    """Pipelined head-major layout -> standard flax tree (exact inverse)."""
    attn = dict(split_tp["blocks"]["attn"])
    qkv_k = attn["qkv"]["kernel"]
    depth, c, three, h, d = qkv_k.shape
    attn["qkv"] = {
        "kernel": qkv_k.reshape(depth, c, 3 * h * d),
        "bias": attn["qkv"]["bias"].reshape(depth, 3 * h * d),
    }
    attn["proj"] = {
        "kernel": attn["proj"]["kernel"].reshape(depth, h * d, c),
        "bias": attn["proj"]["bias"],
    }
    blocks = dict(split_tp["blocks"])
    blocks["attn"] = attn
    return merge_vit_params(
        {"embed": split_tp["embed"], "blocks": blocks,
         "head": split_tp["head"]})


# PartitionSpec per blocks leaf, keyed by its last two path keys. First
# axis entry is the stage dim; 'model' lands on the head dim (attention)
# or the MLP hidden dim — the Megatron column->row split.
def _block_rules(stage_axis: str, tp_axis: str):
    return {
        ("qkv", "kernel"): P(stage_axis, None, None, tp_axis, None),
        ("qkv", "bias"): P(stage_axis, None, tp_axis, None),
        ("proj", "kernel"): P(stage_axis, tp_axis, None, None),
        ("mlp1", "kernel"): P(stage_axis, None, tp_axis),
        ("mlp1", "bias"): P(stage_axis, tp_axis),
        ("mlp2", "kernel"): P(stage_axis, tp_axis, None),
    }


def _last2(path):
    keys = [str(getattr(k, "key", getattr(k, "name", None)))
            for k in path
            if getattr(k, "key", getattr(k, "name", None)) is not None]
    return tuple(keys[-2:])


def block_param_specs(blocks_tree, stage_axis: str, tp_axis: str):
    """PartitionSpec pytree for the (staged) blocks params: every leaf
    gets the stage dim; Megatron-split leaves add the model axis."""
    rules = _block_rules(stage_axis, tp_axis)
    return jax.tree_util.tree_map_with_path(
        lambda path, _: rules.get(_last2(path), P(stage_axis)), blocks_tree)


def tp_block_apply(bp, h, *, tp_axis: str, compute_dtype, mlp_ratio: int,
                   attention_fn=None):
    """One transformer block with model-axis-sharded weights.

    ``bp`` holds this device's shard: whole heads for qkv/proj, a slice of
    the MLP hidden dim for mlp1/mlp2. Residuals, LayerNorms, and ``h``
    itself stay replicated over ``tp_axis``; the two row-parallel matmuls
    (proj, mlp2) produce partial sums closed by one psum each — after
    which every model rank again holds identical activations, which is
    what lets the surrounding GPipe ppermute stay axis-local.

    Math parity with models/attention.py's TransformerBlock: identical
    flax LayerNorm/gelu modules and bf16 policy; the only difference is
    float reassociation in the psum'd partials.
    """
    del mlp_ratio  # implied by the shard shapes; kept for signature clarity
    cd = compute_dtype
    ln = nn.LayerNorm(dtype=cd)

    x = h
    y = ln.apply({"params": bp["ln1"]}, x)
    a = bp["attn"]
    wqkv = a["qkv"]["kernel"].astype(cd)        # (C, 3, Hl, D)
    bqkv = a["qkv"]["bias"].astype(cd)          # (3, Hl, D)
    qkv = jnp.einsum("btc,cahd->btahd", y.astype(cd), wqkv) + bqkv
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attend = attention_fn or full_attention
    o = attend(q, k, v)                          # (B, T, Hl, D), local heads
    wproj = a["proj"]["kernel"].astype(cd)       # (Hl, D, C)
    part = jnp.einsum("bthd,hdc->btc", o.astype(cd), wproj)
    o = lax.psum(part, tp_axis) + a["proj"]["bias"].astype(cd)
    x = x + o

    y = ln.apply({"params": bp["ln2"]}, x)
    u = y.astype(cd) @ bp["mlp1"]["kernel"].astype(cd) \
        + bp["mlp1"]["bias"].astype(cd)          # (B, T, 4C/tp)
    u = nn.gelu(u)
    v2 = u @ bp["mlp2"]["kernel"].astype(cd)     # partial (B, T, C)
    v2 = lax.psum(v2, tp_axis) + bp["mlp2"]["bias"].astype(cd)
    return x + v2


def make_pipelined_tp_vit_apply(
    model: VisionTransformer,
    mesh: Mesh,
    *,
    stage_axis: str = "stage",
    tp_axis: str = "model",
    data_axis: Optional[str] = "data",
    num_microbatches: Optional[int] = None,
):
    """``apply_fn(split_tp_params, x, train=False) -> logits``.

    Drop-in for ``model.apply`` in a TrainState, like
    ``make_pipelined_vit_apply`` — but the stage body runs the explicit-TP
    block, so the same GPipe scan/ppermute schedule now also spans the
    ``model`` axis of a data x stage x model mesh.
    """
    n_stages = mesh.shape[stage_axis]
    tp = mesh.shape[tp_axis]
    if model.depth % n_stages:
        raise ValueError(
            f"vit depth {model.depth} not divisible by {n_stages} pipeline "
            f"stages")
    if model.num_heads % tp:
        raise ValueError(
            f"vit heads {model.num_heads} not divisible by "
            f"--tensor-parallel {tp}")
    hidden = model.embed_dim * model.mlp_ratio
    if hidden % tp:
        raise ValueError(
            f"vit MLP hidden dim {hidden} not divisible by "
            f"--tensor-parallel {tp}")
    cd = model.compute_dtype
    embed_mod = nn.Dense(model.embed_dim, dtype=cd)
    ln_mod = nn.LayerNorm(dtype=cd)
    head_mod = nn.Dense(model.num_classes, dtype=cd)

    def stage_fn(stage_blocks, h):
        def body(h, bp):
            return tp_block_apply(
                bp, h, tp_axis=tp_axis, compute_dtype=cd,
                mlp_ratio=model.mlp_ratio,
                attention_fn=model.attention_fn,
            ), None

        if model.remat:
            body = jax.checkpoint(body)
        h, _ = lax.scan(body, h, stage_blocks)
        return h

    def apply_fn(split_tp, x, *, train: bool = False):
        del train
        h = patchify(x, model.patch_size, cd)
        h = embed_mod.apply({"params": split_tp["embed"]["embed"]}, h)
        h = h + split_tp["embed"]["pos_embed"].astype(cd)
        staged = jax.tree_util.tree_map(
            lambda a: a.reshape((n_stages, a.shape[0] // n_stages)
                                + a.shape[1:]),
            split_tp["blocks"],
        )
        # Specs carry the extra (k = depth/S) dim the reshape introduced
        # between the stage dim and the weight dims.
        def staged_spec(spec):
            return P(spec[0], None, *spec[1:])

        specs = jax.tree_util.tree_map(
            staged_spec,
            block_param_specs(split_tp["blocks"], stage_axis, tp_axis),
            is_leaf=lambda s: isinstance(s, P),
        )
        h = pipeline_apply(
            stage_fn, staged, h, mesh=mesh, axis=stage_axis,
            num_microbatches=num_microbatches, data_axis=data_axis,
            param_specs=specs,
        )
        h = ln_mod.apply({"params": split_tp["head"]["ln_f"]}, h)
        h = jnp.mean(h, axis=1)
        h = head_mod.apply({"params": split_tp["head"]["head"]}, h)
        return h.astype(jnp.float32)

    return apply_fn


def pipelined_tp_state_sharding(state, mesh: Mesh,
                                stage_axis: str = "stage",
                                tp_axis: str = "model"):
    """NamedSharding pytree for the whole TrainState: blocks leaves get
    stage dim 0 plus their Megatron model-axis dims; everything else
    replicates. Adam mu/nu mirror the param tree, so one rule pass covers
    them (same property as ``parallel/tensor.py``)."""
    rules = _block_rules(stage_axis, tp_axis)

    def spec_for(path, leaf):
        keys = [str(getattr(k, "key", getattr(k, "name", None)))
                for k in path
                if getattr(k, "key", getattr(k, "name", None)) is not None]
        if "blocks" in keys and getattr(leaf, "ndim", 0) >= 1:
            return NamedSharding(
                mesh, rules.get(tuple(keys[-2:]), P(stage_axis)))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, state)


def create_pipelined_tp_vit_state(
    model: VisionTransformer,
    rng: jax.Array,
    mesh: Mesh,
    *,
    stage_axis: str = "stage",
    tp_axis: str = "model",
    data_axis: Optional[str] = "data",
    num_microbatches: Optional[int] = None,
    lr: float = 1e-3,
    optimizer: str = "adam",
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    place: bool = True,
):
    """``(state, state_sharding)`` for the PP x TP ViT — the same pair
    contract as ``create_pipelined_vit_state`` / ``shard_state``, consumed
    by the standard train/eval steps unchanged. ``place=False`` defers
    placement for callers composing ZeRO on top (same rationale as
    ``create_pipelined_vit_state``)."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import place_state
    from pytorch_distributed_mnist_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    params = split_vit_params_tp(
        model.init(rng, jnp.zeros((1, 28, 28, 1), jnp.float32)),
        model.num_heads,
    )
    tx = make_optimizer(lr, optimizer, momentum, weight_decay)
    apply_fn = make_pipelined_tp_vit_apply(
        model, mesh, stage_axis=stage_axis, tp_axis=tp_axis,
        data_axis=data_axis, num_microbatches=num_microbatches,
    )
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        apply_fn=apply_fn,
        tx=tx,
    )
    sharding = pipelined_tp_state_sharding(state, mesh, stage_axis, tp_axis)
    if not place:
        return state, sharding
    return place_state(state, sharding), sharding
