"""Device mesh construction and canonical shardings.

The reference's "mesh" is implicit: one process per GPU, rank == device id
(``/root/reference/multi_proc_single_gpu.py:180-181``), world_size asserted
== local GPU count (``:351``), and the only parallel axis is data
(SURVEY.md section 2c). Here the mesh is explicit and N-dimensional from day
one: data parallelism is ``Mesh(devices, ('data',))``, and adding model/fsdp
axes later is a ``PartitionSpec`` change, not new machinery.

On TPU, mesh construction uses ``jax.devices()`` in their default order,
which XLA lays out so that neighboring mesh positions are ICI neighbors —
the gradient AllReduce over ``data`` therefore rides ICI, not DCN, exactly
the property NCCL rings give the reference on NVLink.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axes: Tuple[str, ...] = ("data",),
    shape: Optional[Tuple[int, ...]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all global devices).

    With the default 1-D ``('data',)`` axes and no shape, every device joins
    the data axis — the DDP-equivalent topology. Pass e.g.
    ``axes=('data', 'model'), shape=(4, 2)`` for a 2-D layout.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (devs.size,) if len(axes) == 1 else None
        if shape is None:
            raise ValueError("shape is required for multi-axis meshes")
    if int(np.prod(shape)) != devs.size:
        raise ValueError(f"mesh shape {shape} != device count {devs.size}")
    return Mesh(devs.reshape(shape), axes)


def data_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for a batch: leading (batch) dim split across ``axis``."""
    return NamedSharding(mesh, P(axis))


def data_replica_coords(mesh: Mesh, process_index: Optional[int] = None):
    """How this process's devices partition the leading (data) mesh axis:
    ``(num_replicas, rank)`` for the host-side batch sharder.

    Batch rows shard over the DATA axis, not over processes. In classic
    multi-host DP the two coincide (each host's devices sit at their own
    data coordinates), but when another axis spans hosts — multi-host TP,
    PP, SP: mesh ``data=1 x stage=2`` over 2 processes, say — the batch
    is *replicated* with respect to those processes, and each must feed
    IDENTICAL rows: ``jax.make_array_from_process_local_data`` builds an
    ill-defined global array if nominal replicas disagree (no cross-host
    value check exists, so the divergence is silent). Grouping processes
    by the data coordinates their devices cover makes every composition
    feed consistent input; pure DP degenerates to
    ``(process_count, process_index)``.

    Relies on the data-major device order ``make_mesh`` uses (the data
    axis is axis 0 of every mesh this framework builds), and raises if a
    process's devices do not cover a contiguous uniform block of it.
    """
    if mesh.axis_names[0] != "data":
        # Grouping by axis 0 of a mesh whose data axis lives elsewhere
        # would shard the batch over the wrong axis — the same silent
        # divergence this function exists to prevent. Every mesh this
        # framework builds is data-major; refuse anything else loudly.
        raise ValueError(
            f"data_replica_coords requires a data-major mesh; got axes "
            f"{mesh.axis_names}")
    if process_index is None:
        process_index = jax.process_index()
    return _data_groups(mesh.devices, process_index)


def _data_groups(devices: np.ndarray, process_index: int):
    """Core of ``data_replica_coords`` over a raw device ndarray (axis 0 =
    data); split out so tests can drive it with fake device objects."""
    data_size = devices.shape[0]
    owned = [
        i for i in range(data_size)
        if any(d.process_index == process_index
               for d in np.asarray(devices[i], dtype=object).flat)
    ]
    if not owned:
        raise ValueError(
            f"process {process_index} owns no devices in this mesh")
    span = len(owned)
    # Contiguous, uniform, AND block-aligned: coordinates [1,2] of 4 are
    # contiguous with a dividing span yet straddle the shard boundary —
    # rank 1//2 would feed shard-0 rows for shard-1 devices.
    if (owned[-1] - owned[0] + 1 != span or data_size % span
            or owned[0] % span):
        raise ValueError(
            f"process {process_index}'s devices cover data coordinates "
            f"{owned} of {data_size}: not an aligned contiguous uniform "
            "block — host batch sharding requires the data-major device "
            "order make_mesh produces")
    return data_size // span, owned[0] // span


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for params/opt state: fully replicated (DDP-style weights)."""
    return NamedSharding(mesh, P())


def place_state(state, sharding_tree):
    """Place a pytree onto a sharding tree, multi-host safe.

    Single process: plain ``jax.device_put``. Multi-host: ``device_put`` of
    a committed per-host array onto a cross-host sharding demands backend
    cross-host transfer support, but every caller here holds the FULL value
    on every host (fresh replicated init, or a checkpoint stitched on each
    host), so each host just materializes its own shards from its host copy
    via ``make_array_from_callback`` — no bytes cross the network. Shared by
    the TP/EP (``parallel.tensor.shard_state``) and ZeRO
    (``parallel.zero.shard_state_zero1``) placement paths.
    """
    if jax.process_count() == 1:
        return jax.device_put(state, sharding_tree)

    def place(leaf, sh):
        host = np.asarray(leaf)  # replicated/addressable on every host
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx, a=host: a[idx]
        )

    return jax.tree_util.tree_map(place, state, sharding_tree)
