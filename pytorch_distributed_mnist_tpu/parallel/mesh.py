"""Device mesh construction and canonical shardings.

The reference's "mesh" is implicit: one process per GPU, rank == device id
(``/root/reference/multi_proc_single_gpu.py:180-181``), world_size asserted
== local GPU count (``:351``), and the only parallel axis is data
(SURVEY.md section 2c). Here the mesh is explicit and N-dimensional from day
one: data parallelism is ``Mesh(devices, ('data',))``, and adding model/fsdp
axes later is a ``PartitionSpec`` change, not new machinery.

On TPU, mesh construction uses ``jax.devices()`` in their default order,
which XLA lays out so that neighboring mesh positions are ICI neighbors —
the gradient AllReduce over ``data`` therefore rides ICI, not DCN, exactly
the property NCCL rings give the reference on NVLink.

Multi-slice worlds break that flat picture: chips within a slice talk
over ICI, chips in different slices over DCN, 10-100x slower.
``make_hier_mesh`` builds the two-tier ``('dcn', 'ici', ...)`` mesh for
that topology — data-major like every mesh here, with the DATA axis
*composed* of both tiers (batch rows shard over ``('dcn', 'ici')``
jointly) so tier-aware schedules (``parallel/zero_overlap.py``) can
address each tier by name while tier-oblivious GSPMD paths treat the
pair as one axis. Slice assignment comes from real topology
(``device.slice_index``) when the runtime reports one, else from the
emulated map ``TPUMNIST_DCN_SLICES`` / ``--dcn-slices`` (contiguous
blocks of the device order), so CPU worlds and tests exercise the
hierarchy.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The two tiers of a hierarchical mesh, leading (data-major) — together
# they ARE the data axis; model axes follow.
HIER_DATA_AXES: Tuple[str, str] = ("dcn", "ici")

# Emulated slice map: N contiguous equal blocks of the device order.
DCN_SLICES_ENV = "TPUMNIST_DCN_SLICES"


def make_mesh(
    axes: Tuple[str, ...] = ("data",),
    shape: Optional[Tuple[int, ...]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all global devices).

    With the default 1-D ``('data',)`` axes and no shape, every device joins
    the data axis — the DDP-equivalent topology. Pass e.g.
    ``axes=('data', 'model'), shape=(4, 2)`` for a 2-D layout.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (devs.size,) if len(axes) == 1 else None
        if shape is None:
            raise ValueError("shape is required for multi-axis meshes")
    if int(np.prod(shape)) != devs.size:
        raise ValueError(f"mesh shape {shape} != device count {devs.size}")
    return Mesh(devs.reshape(shape), axes)


def device_slice_index(device) -> Optional[int]:
    """The device's real slice assignment (TPU multi-slice runtimes
    stamp ``slice_index``), or None when the runtime reports none."""
    idx = getattr(device, "slice_index", None)
    return int(idx) if isinstance(idx, (int, np.integer)) else None


def infer_dcn_slices(devices: Optional[Sequence] = None) -> int:
    """How many DCN slices this world spans: the ``TPUMNIST_DCN_SLICES``
    emulation env when set, else the count of distinct real
    ``device.slice_index`` values, else 1 (a flat single-slice world).
    """
    env = os.environ.get(DCN_SLICES_ENV, "")
    if env:
        try:
            return int(env)
        except ValueError:
            raise ValueError(
                f"{DCN_SLICES_ENV}={env!r} is not an integer slice count")
    devs = list(devices) if devices is not None else jax.devices()
    real = {device_slice_index(d) for d in devs}
    if None in real or len(real) < 2:
        return 1
    return len(real)


def _slice_blocks(devices: Sequence, dcn_slices: int) -> list:
    """Order ``devices`` slice-major and validate the slice topology
    (pure: drivable with fake device objects). With real ``slice_index``
    stamps the devices are grouped by slice (equal sizes required, slice
    count must match); without them the given order is the emulated map
    — ``dcn_slices`` contiguous equal blocks."""
    devices = list(devices)
    n = len(devices)
    if dcn_slices < 1:
        raise ValueError(f"dcn_slices must be >= 1, got {dcn_slices}")
    if n % dcn_slices:
        raise ValueError(
            f"{n} device(s) do not split into {dcn_slices} equal DCN "
            f"slices")
    per = n // dcn_slices
    real = [device_slice_index(d) for d in devices]
    if all(r is not None for r in real) and len(set(real)) > 1:
        groups: dict = {}
        for d, r in zip(devices, real):
            groups.setdefault(r, []).append(d)
        if len(groups) != dcn_slices:
            raise ValueError(
                f"devices report {len(groups)} distinct slice_index "
                f"value(s), not the requested {dcn_slices} DCN slices")
        bad = {k: len(v) for k, v in groups.items() if len(v) != per}
        if bad:
            raise ValueError(
                f"unequal slice sizes (expected {per} chips/slice, got "
                f"{bad}): every DCN slice must contribute the same chip "
                f"count")
        return [d for k in sorted(groups) for d in groups[k]]
    return devices


def validate_dcn_slices(dcn_slices: int,
                        devices: Optional[Sequence] = None) -> None:
    """Raise ``ValueError`` unless ``devices`` (default: the world) can
    form ``dcn_slices`` equal slices — the SAME checks ``make_hier_mesh``
    runs (count divisibility AND, with real ``slice_index`` stamps,
    slice-count match and equal sizes), so callers that want flag-level
    rejection (cli.py) or graceful degradation (the elastic flat
    fallback) can decide BEFORE construction; a later ``make_hier_mesh``
    on the same inputs cannot fail for slice reasons."""
    devs = list(devices) if devices is not None else jax.devices()
    _slice_blocks(devs, dcn_slices)


def make_hier_mesh(
    dcn_slices: Optional[int] = None,
    extra_axes: Tuple[str, ...] = (),
    extra_shape: Tuple[int, ...] = (),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the data-major two-tier ``('dcn', 'ici', *extra_axes)`` mesh.

    Axis 0 (``dcn``) indexes the slice, axis 1 (``ici``) the data
    position within it; together they compose the data axis (batch rows
    shard over the pair — ``data_sharding``/``data_replica_coords``
    understand the composition). ``extra_axes``/``extra_shape`` append
    model axes (model/seq/expert), which nest INSIDE one slice: the
    total model width must divide the per-slice chip count, so no
    TP/EP group ever straddles the slow DCN tier — a straddling layout
    is rejected here, not discovered as a slow program.

    ``dcn_slices=None`` resolves via :func:`infer_dcn_slices` (env map,
    then real ``device.slice_index`` topology) and refuses a flat world
    — callers that want flat build ``make_mesh`` instead.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if dcn_slices is None:
        dcn_slices = infer_dcn_slices(devs)
        if dcn_slices < 2:
            raise ValueError(
                f"no DCN slice topology: devices carry no slice_index "
                f"and {DCN_SLICES_ENV} is unset — pass dcn_slices "
                f"explicitly (or build a flat make_mesh)")
    if len(extra_axes) != len(extra_shape):
        raise ValueError(
            f"extra_axes {extra_axes} and extra_shape {extra_shape} "
            f"must pair up")
    for ax in extra_axes:
        if ax in HIER_DATA_AXES + ("data",):
            raise ValueError(
                f"extra axis {ax!r} collides with the hierarchical "
                f"data axes {HIER_DATA_AXES}")
    ordered = _slice_blocks(devs, dcn_slices)
    per_slice = len(ordered) // dcn_slices
    model = int(np.prod(extra_shape, dtype=np.int64)) if extra_shape else 1
    if model < 1 or per_slice % model:
        raise ValueError(
            f"model axes {dict(zip(extra_axes, extra_shape))} (width "
            f"{model}) would straddle the DCN boundary: each slice has "
            f"{per_slice} chip(s), and model-parallel groups must nest "
            f"inside one slice's ICI domain")
    shape = (dcn_slices, per_slice // model) + tuple(extra_shape)
    grid = np.empty(len(ordered), dtype=object)
    grid[:] = ordered
    return Mesh(grid.reshape(shape), HIER_DATA_AXES + tuple(extra_axes))


def is_hier_mesh(mesh: Mesh) -> bool:
    """Whether ``mesh`` is a two-tier ``('dcn', 'ici', ...)`` mesh."""
    return tuple(mesh.axis_names[:2]) == HIER_DATA_AXES


def resolve_data_axis(mesh: Optional[Mesh], axis="data"):
    """The axis (name or composed name tuple) batch rows shard over:
    the requested ``axis`` as-is, except that the default ``'data'`` on
    a hierarchical mesh resolves to the composed ``('dcn', 'ici')``
    pair — so every tier-oblivious call site (steps, loader, staging)
    follows the mesh without knowing about tiers."""
    if mesh is not None and axis == "data" and is_hier_mesh(mesh):
        return HIER_DATA_AXES
    return axis


def device_slice_map(devices: Sequence) -> Optional[list]:
    """Per-device slice assignment for ``devices`` (any subset of the
    world), or None when no slice topology exists. Real ``slice_index``
    stamps win; the emulated ``TPUMNIST_DCN_SLICES`` map assigns by
    global device id (contiguous equal blocks of the world), matching
    ``make_hier_mesh``'s emulated blocks. Serving uses this to prefer
    single-slice mesh groups (``serve/programs.py partition_groups``)
    and to flag groups that straddle slices."""
    devs = list(devices)
    if not devs:
        return None
    real = [device_slice_index(d) for d in devs]
    if all(r is not None for r in real):
        world_real = {device_slice_index(d) for d in jax.devices()}
        if None not in world_real and len(world_real) > 1:
            return real
    env = os.environ.get(DCN_SLICES_ENV, "")
    if not env:
        return None
    try:
        n_slices = int(env)
    except ValueError:
        return None
    world = jax.device_count()
    if n_slices < 2 or world % n_slices:
        return None
    per = world // n_slices
    return [int(getattr(d, "id", 0)) // per for d in devs]


def data_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for a batch: leading (batch) dim split across ``axis``
    (the composed ``('dcn', 'ici')`` pair on hierarchical meshes)."""
    return NamedSharding(mesh, P(resolve_data_axis(mesh, axis)))


def data_replica_coords(mesh: Mesh, process_index: Optional[int] = None):
    """How this process's devices partition the leading (data) mesh axis:
    ``(num_replicas, rank)`` for the host-side batch sharder.

    Batch rows shard over the DATA axis, not over processes. In classic
    multi-host DP the two coincide (each host's devices sit at their own
    data coordinates), but when another axis spans hosts — multi-host TP,
    PP, SP: mesh ``data=1 x stage=2`` over 2 processes, say — the batch
    is *replicated* with respect to those processes, and each must feed
    IDENTICAL rows: ``jax.make_array_from_process_local_data`` builds an
    ill-defined global array if nominal replicas disagree (no cross-host
    value check exists, so the divergence is silent). Grouping processes
    by the data coordinates their devices cover makes every composition
    feed consistent input; pure DP degenerates to
    ``(process_count, process_index)``.

    Relies on the data-major device order ``make_mesh`` uses (the data
    axis is axis 0 of every mesh this framework builds — or, on a
    hierarchical mesh, the composed ``('dcn', 'ici')`` leading pair,
    collapsed here into one data axis before grouping), and raises if a
    process's devices do not cover a contiguous uniform block of it.
    """
    names = tuple(mesh.axis_names)
    devices = mesh.devices
    if names[:2] == HIER_DATA_AXES:
        # The composed data axis: dcn-major x ici-minor is exactly the
        # device order make_hier_mesh laid out, so collapsing the two
        # leading axes yields the flat data axis the sharder needs.
        devices = devices.reshape((-1,) + devices.shape[2:])
    elif names[0] != "data":
        # Grouping by axis 0 of a mesh whose data axis lives elsewhere
        # would shard the batch over the wrong axis — the same silent
        # divergence this function exists to prevent. Every mesh this
        # framework builds is data-major; refuse anything else loudly.
        raise ValueError(
            f"data_replica_coords requires a data-major mesh; got axes "
            f"{mesh.axis_names}")
    if process_index is None:
        process_index = jax.process_index()
    return _data_groups(devices, process_index)


def _data_groups(devices: np.ndarray, process_index: int):
    """Core of ``data_replica_coords`` over a raw device ndarray (axis 0 =
    data); split out so tests can drive it with fake device objects."""
    data_size = devices.shape[0]
    owned = [
        i for i in range(data_size)
        if any(d.process_index == process_index
               for d in np.asarray(devices[i], dtype=object).flat)
    ]
    if not owned:
        raise ValueError(
            f"process {process_index} owns no devices in this mesh")
    span = len(owned)
    # Contiguous, uniform, AND block-aligned: coordinates [1,2] of 4 are
    # contiguous with a dividing span yet straddle the shard boundary —
    # rank 1//2 would feed shard-0 rows for shard-1 devices.
    if (owned[-1] - owned[0] + 1 != span or data_size % span
            or owned[0] % span):
        raise ValueError(
            f"process {process_index}'s devices cover data coordinates "
            f"{owned} of {data_size}: not an aligned contiguous uniform "
            "block — host batch sharding requires the data-major device "
            "order make_mesh produces")
    return data_size // span, owned[0] // span


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for params/opt state: fully replicated (DDP-style weights)."""
    return NamedSharding(mesh, P())


def place_state(state, sharding_tree):
    """Place a pytree onto a sharding tree, multi-host safe.

    Single process: plain ``jax.device_put``. Multi-host: ``device_put`` of
    a committed per-host array onto a cross-host sharding demands backend
    cross-host transfer support, but every caller here holds the FULL value
    on every host (fresh replicated init, or a checkpoint stitched on each
    host), so each host just materializes its own shards from its host copy
    via ``make_array_from_callback`` — no bytes cross the network. Shared by
    the TP/EP (``parallel.tensor.shard_state``) and ZeRO
    (``parallel.zero.shard_state_zero1``) placement paths.
    """
    if jax.process_count() == 1:
        return jax.device_put(state, sharding_tree)

    def place(leaf, sh):
        host = np.asarray(leaf)  # replicated/addressable on every host
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx, a=host: a[idx]
        )

    return jax.tree_util.tree_map(place, state, sharding_tree)
