"""Model zoo.

The reference hard-codes a single model (``Net``, a ``Linear(784, 10)``,
``/root/reference/multi_proc_single_gpu.py:119-126``) and constructs it at a
fixed call site (``:185``). Here the model is pluggable via a registry:
``linear`` is the exact reference-parity model, ``cnn`` is the small convnet
required for the >=99% MNIST accuracy target (BASELINE.md north star).
"""

from pytorch_distributed_mnist_tpu.models.linear import LinearNet
from pytorch_distributed_mnist_tpu.models.cnn import ConvNet
from pytorch_distributed_mnist_tpu.models.attention import VisionTransformer
from pytorch_distributed_mnist_tpu.models.moe import MoEClassifier, SwitchMoE
from pytorch_distributed_mnist_tpu.models.registry import get_model, register_model, list_models, model_accepts

__all__ = [
    "LinearNet",
    "ConvNet",
    "VisionTransformer",
    "MoEClassifier",
    "SwitchMoE",
    "get_model",
    "register_model",
    "list_models",
    "model_accepts",
]
