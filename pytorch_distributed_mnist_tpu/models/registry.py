"""Model registry: name -> constructor.

The reference constructs its model at a hard-coded call site
(``/root/reference/multi_proc_single_gpu.py:185``); the TPU framework makes
the model a named, pluggable component so the CLI (``--model``) and tests can
select architectures without editing source.
"""

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register_model(name: str) -> Callable:
    """Class decorator registering a model constructor under ``name``."""

    def wrap(cls):
        if name in _REGISTRY:
            raise ValueError(f"model {name!r} already registered")
        _REGISTRY[name] = cls
        return cls

    return wrap


def get_model(name: str, **kwargs):
    """Instantiate a registered model by name."""
    try:
        ctor = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    return ctor(**kwargs)


def list_models():
    return sorted(_REGISTRY)
