"""Model registry: name -> constructor.

The reference constructs its model at a hard-coded call site
(``/root/reference/multi_proc_single_gpu.py:185``); the TPU framework makes
the model a named, pluggable component so the CLI (``--model``) and tests can
select architectures without editing source.
"""

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register_model(name: str) -> Callable:
    """Class decorator registering a model constructor under ``name``."""

    def wrap(cls):
        if name in _REGISTRY:
            raise ValueError(f"model {name!r} already registered")
        _REGISTRY[name] = cls
        return cls

    return wrap


def _lookup(name: str) -> Callable:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def get_model(name: str, **kwargs):
    """Instantiate a registered model by name."""
    return _lookup(name)(**kwargs)


def list_models():
    return sorted(_REGISTRY)


def model_field_default(name: str, field: str):
    """A registered model's constructor default for ``field`` — the one
    source for flag-level divisibility checks (head/expert counts in the
    training CLI, mesh-size validation messages in serving). Raises
    ``ValueError`` for an unknown model or field, so a typo fails loudly
    instead of reading as "no default"."""
    import dataclasses
    import inspect

    ctor = _lookup(name)
    if dataclasses.is_dataclass(ctor):
        for f in dataclasses.fields(ctor):
            if f.name == field:
                if f.default is not dataclasses.MISSING:
                    return f.default
                if f.default_factory is not dataclasses.MISSING:
                    return f.default_factory()
                break  # required field: no default to report
    else:
        try:
            param = inspect.signature(ctor).parameters[field]
        except (KeyError, TypeError, ValueError):
            pass
        else:
            if param.default is not inspect.Parameter.empty:
                return param.default
    raise ValueError(f"model {name!r} has no field {field!r} with a default")


def model_accepts(name: str, field: str) -> bool:
    """True if the registered model's constructor takes ``field``.

    Capability probe for CLI flags (e.g. ``--attention`` needs a model
    with an ``attention_fn`` field) — an explicit check, so a genuine
    TypeError from a model constructor is never mistaken for a
    capability mismatch."""
    import dataclasses
    import inspect

    ctor = _lookup(name)
    if dataclasses.is_dataclass(ctor):
        return field in {f.name for f in dataclasses.fields(ctor)}
    try:
        return field in inspect.signature(ctor).parameters
    except (TypeError, ValueError):
        return False
