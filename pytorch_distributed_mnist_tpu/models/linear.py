"""Reference-parity linear model.

Capability parity with ``Net`` in
``/root/reference/multi_proc_single_gpu.py:119-126``: flatten the 28x28 image
to 784 features and apply a single dense 784->10 projection (logistic
regression; no conv, no activation, no dropout). Forward flattening mirrors
``x.view(x.size(0), -1)`` (``:126``).

TPU notes: the single matmul maps straight onto the MXU; ``compute_dtype``
defaults to bfloat16 so the MXU runs at full rate, with params kept in
float32 for a stable optimizer state. Logits are returned in float32 so the
cross-entropy reduction is accurate.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_mnist_tpu.models.registry import register_model


@register_model("linear")
class LinearNet(nn.Module):
    """Flatten -> Dense(num_classes)."""

    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.bfloat16
    # Matmul implementation for the Dense layer (None = lax.dot_general);
    # the int8 serving plane injects ops/pallas int8_dot_general here.
    dot_general: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train  # no train-time-only behavior (parity: reference has none)
        x = x.reshape((x.shape[0], -1)).astype(self.compute_dtype)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     dot_general=self.dot_general, name="fc")(x)
        return x.astype(jnp.float32)
