"""Patch-transformer (ViT) model family with pluggable attention.

The reference's zoo is exactly one hard-coded ``Linear(784, 10)``
(``/root/reference/multi_proc_single_gpu.py:119-126, 185``). This framework
treats the model as a registry entry (SURVEY.md section 0) and carries a
small vision transformer in addition to ``linear``/``cnn`` — it is the model
that actually has a sequence axis, so it is the vehicle for the
sequence-parallel machinery (``parallel/ring.py``, ``parallel/ulysses.py``):
``tests/test_vit.py`` trains it with ring attention swapped in (gradients
flow through shard_map + ppermute) and checks ring/dense forward parity.

TPU notes: bfloat16 compute / float32 params and logits (same policy as
``models/cnn.py``); token count is (28/patch)^2 (49 for the default patch 4) —
tiny for MNIST, but the code path is the same one a long-context model
takes, just with T larger and the ``seq`` axis sharded wider.

``attention_fn`` is a static module field: any ``(q, k, v) -> o`` on
``(B, T, H, D)``. Default is dense ``ops.attention.full_attention``; pass
``partial(ring_attention, mesh=mesh)`` (or the Ulysses variant) to make
every block's attention sequence-parallel with no other model change.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_mnist_tpu.models.registry import register_model
from pytorch_distributed_mnist_tpu.ops.attention import full_attention


def patchify(x: jnp.ndarray, patch_size: int, compute_dtype) -> jnp.ndarray:
    """(B, 784) / (B, 28, 28) / (B, 28, 28, C) -> (B, T, p*p*C) patches.

    Shared by the sequential ViT below and the pipeline-parallel assembly
    (parallel/pipeline_vit.py) so the two paths cannot drift; the
    forward-parity test in tests/test_pipeline_vit.py pins them equal.
    """
    if x.ndim == 2:
        x = x.reshape((x.shape[0], 28, 28, 1))
    elif x.ndim == 3:
        x = x[..., None]
    x = x.astype(compute_dtype)
    p = patch_size
    b, hh, ww, ch = x.shape
    gh, gw = hh // p, ww // p
    # (B, gh, p, gw, p, C) -> (B, gh*gw, p*p*C): non-overlapping patches.
    x = x.reshape(b, gh, p, gw, p, ch).transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, p * p * ch)


class MultiHeadSelfAttention(nn.Module):
    """QKV projection -> pluggable core attention -> output projection."""

    num_heads: int
    attention_fn: Optional[Callable] = None
    compute_dtype: jnp.dtype = jnp.bfloat16
    dot_general: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, t, c = x.shape
        h = self.num_heads
        assert c % h == 0, f"embed dim {c} not divisible by heads {h}"
        d = c // h
        qkv = nn.Dense(3 * c, dtype=self.compute_dtype,
                       dot_general=self.dot_general, name="qkv")(x)
        qkv = qkv.reshape(b, t, 3, h, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attend = self.attention_fn or full_attention
        o = attend(q, k, v)  # (B, T, H, D)
        o = o.reshape(b, t, c).astype(self.compute_dtype)
        return nn.Dense(c, dtype=self.compute_dtype,
                        dot_general=self.dot_general, name="proj")(o)


class TransformerBlock(nn.Module):
    """Pre-LN block: LN -> MHSA -> residual; LN -> MLP -> residual."""

    num_heads: int
    mlp_ratio: int = 4
    attention_fn: Optional[Callable] = None
    compute_dtype: jnp.dtype = jnp.bfloat16
    dot_general: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        y = nn.LayerNorm(dtype=self.compute_dtype, name="ln1")(x)
        x = x + MultiHeadSelfAttention(
            self.num_heads, self.attention_fn, self.compute_dtype,
            dot_general=self.dot_general, name="attn"
        )(y)
        y = nn.LayerNorm(dtype=self.compute_dtype, name="ln2")(x)
        y = nn.Dense(self.mlp_ratio * c, dtype=self.compute_dtype,
                     dot_general=self.dot_general, name="mlp1")(y)
        y = nn.gelu(y)
        y = nn.Dense(c, dtype=self.compute_dtype,
                     dot_general=self.dot_general, name="mlp2")(y)
        return x + y


@register_model("vit")
class VisionTransformer(nn.Module):
    """Small ViT: patchify -> embed (+pos) -> blocks -> LN -> mean-pool -> head."""

    num_classes: int = 10
    patch_size: int = 4
    embed_dim: int = 64
    depth: int = 2
    num_heads: int = 4
    mlp_ratio: int = 4
    attention_fn: Optional[Callable] = None
    compute_dtype: jnp.dtype = jnp.bfloat16
    # Matmul implementation for every Dense in the model (None =
    # lax.dot_general); the int8 serving plane injects the MXU-native
    # int8 kernel (ops/pallas/matmul_i8.py) through this field.
    dot_general: Optional[Callable] = None
    # jax.checkpoint around each block: activations inside a block are
    # recomputed during backward instead of stored, the standard TPU
    # HBM-for-FLOPs trade for long sequences (the FLOPs rerun on an MXU
    # that was stalling on HBM anyway). Param structure is unchanged, so
    # checkpoints round-trip between remat and non-remat models.
    remat: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train
        # Accept flat (B, 784), (B, 28, 28), or (B, 28, 28, 1) like the other
        # zoo models, so the same data pipeline feeds all of them.
        x = patchify(x, self.patch_size, self.compute_dtype)
        x = nn.Dense(self.embed_dim, dtype=self.compute_dtype,
                     dot_general=self.dot_general, name="embed")(x)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.embed_dim),
        )
        x = x + pos.astype(self.compute_dtype)
        block_cls = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        for i in range(self.depth):
            x = block_cls(
                self.num_heads, self.mlp_ratio, self.attention_fn,
                self.compute_dtype, dot_general=self.dot_general,
                name=f"block{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.compute_dtype, name="ln_f")(x)
        x = jnp.mean(x, axis=1)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     dot_general=self.dot_general, name="head")(x)
        return x.astype(jnp.float32)
