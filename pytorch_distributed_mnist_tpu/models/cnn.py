"""Small convnet for the >=99% MNIST target.

The reference's model is a bare ``Linear(784, 10)``
(``/root/reference/multi_proc_single_gpu.py:119-126``) which tops out around
92-93% MNIST test accuracy; BASELINE.md's north star (>=99% in <60s on TPU)
requires a conv model, so the zoo carries this 2-conv CNN in addition to the
parity ``linear`` model (SURVEY.md section 0).

TPU notes: NHWC layout (XLA:TPU's native conv layout), bfloat16 compute so
convs and the dense layers hit the MXU, float32 params/logits. Channel widths
are multiples of 8 to line up with VPU/MXU tiling.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_mnist_tpu.models.registry import register_model


@register_model("cnn")
class ConvNet(nn.Module):
    """conv3x3(32) -> conv3x3(64) -> maxpool2 -> dense(128) -> dense(10)."""

    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.bfloat16
    # Matmul implementation for the Dense layers (None = lax.dot_general).
    # The int8 serving plane injects the MXU-native int8 kernel here
    # (ops/pallas/matmul_i8.py); model_accepts("cnn", "dot_general")
    # gates the wiring.
    dot_general: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train
        # Accept flat (B, 784) or image (B, 28, 28) / (B, 28, 28, 1) input so
        # the CNN is a drop-in for the linear model on the same pipeline.
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 28, 28, 1))
        elif x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.compute_dtype)
        x = nn.Conv(32, (3, 3), dtype=self.compute_dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), dtype=self.compute_dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.compute_dtype,
                     dot_general=self.dot_general, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     dot_general=self.dot_general, name="fc2")(x)
        return x.astype(jnp.float32)
