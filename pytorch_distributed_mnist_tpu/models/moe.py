"""Mixture-of-experts model family (expert parallelism vehicle).

The reference has no experts — its model is one dense layer
(``/root/reference/multi_proc_single_gpu.py:119-126``; SURVEY.md section 2c
marks EP/MoE ABSENT). The framework carries a switch-style MoE layer anyway
because expert parallelism is one of the mesh axes the N-D design supports:
expert weights carry a leading ``num_experts`` dim that
``moe_ep_rules`` (parallel/expert.py) shards on the ``expert`` mesh axis,
and XLA turns the expert-summed combine einsum into an AllReduce over that
axis — each device computes only its local experts' FLOPs.

Routing is top-1 (switch) with a straight-through mask: every expert's MLP
runs on every token algebraically, but the one-hot combine zeroes all but
the routed expert, and under EP sharding each device only materializes its
own experts' activations. At MNIST scale this dense-dispatch form costs
little and keeps the math exactly reproducible across mesh shapes (the
property the EP tests pin); a capacity-factor all_to_all dispatch is the
long-context-scale variant and slots behind the same module interface.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_mnist_tpu.models.registry import register_model


class SwitchMoE(nn.Module):
    """Top-1-routed mixture of expert MLPs: (B, C) -> (B, C)."""

    num_experts: int = 8
    hidden: int = 128
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        e, h, c = self.num_experts, self.hidden, x.shape[-1]
        router = nn.Dense(e, dtype=jnp.float32, name="router")
        # Router math in f32: top-1 selection is a discrete decision; bf16
        # logit noise would make routing (and therefore loss) layout-dependent.
        probs = nn.softmax(router(x.astype(jnp.float32)), axis=-1)  # (B, E)
        top1 = jnp.argmax(probs, axis=-1)  # (B,)
        mask = jnp.eye(e, dtype=probs.dtype)[top1]  # (B, E) one-hot
        gate = (probs * mask).sum(-1, keepdims=True)  # (B, 1) routed prob

        w1 = self.param("w1", nn.initializers.lecun_normal(), (e, c, h))
        b1 = self.param("b1", nn.initializers.zeros, (e, h))
        w2 = self.param("w2", nn.initializers.lecun_normal(), (e, h, c))
        b2 = self.param("b2", nn.initializers.zeros, (e, c))
        xc = x.astype(self.compute_dtype)
        # (B, E, H): per-expert hidden; E shards on the 'expert' mesh axis.
        hdn = nn.relu(
            jnp.einsum("bc,ech->beh", xc, w1.astype(self.compute_dtype))
            + b1.astype(self.compute_dtype)
        )
        y = (
            jnp.einsum("beh,ehc->bec", hdn, w2.astype(self.compute_dtype))
            + b2.astype(self.compute_dtype)
        )  # (B, E, C)
        # One-hot combine: the sum over E is the EP AllReduce.
        out = jnp.einsum("bec,be->bc", y.astype(jnp.float32), mask) * gate
        return out.astype(x.dtype)


@register_model("moe_mlp")
class MoEClassifier(nn.Module):
    """flatten -> embed -> residual SwitchMoE -> head (MNIST classifier)."""

    num_classes: int = 10
    num_experts: int = 8
    embed_dim: int = 64
    hidden: int = 128
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train
        x = x.reshape((x.shape[0], -1)).astype(self.compute_dtype)  # (B, 784)
        x = nn.Dense(self.embed_dim, dtype=self.compute_dtype, name="embed")(x)
        x = nn.relu(x)
        x = x + SwitchMoE(
            self.num_experts, self.hidden, self.compute_dtype, name="moe"
        )(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype, name="head")(x)
        return x.astype(jnp.float32)
