"""Mixture-of-experts model family (expert parallelism vehicle).

The reference has no experts — its model is one dense layer
(``/root/reference/multi_proc_single_gpu.py:119-126``; SURVEY.md section 2c
marks EP/MoE ABSENT). The framework carries a switch-style MoE layer anyway
because expert parallelism is one of the mesh axes the N-D design supports:
expert weights carry a leading ``num_experts`` dim that
``moe_ep_rules`` (parallel/expert.py) shards on the ``expert`` mesh axis,
and XLA turns the expert-summed combine einsum into an AllReduce over that
axis — each device computes only its local experts' FLOPs.

Routing is top-1 (switch). Two dispatch modes behind one interface:

- ``dispatch='dense'`` (default): every expert's MLP runs on every token
  algebraically, the one-hot combine zeroes all but the routed expert, and
  under EP sharding each device only materializes its own experts'
  activations. Layout-independent math — the property the EP equivalence
  tests pin — and cheap at MNIST scale.
- ``dispatch='capacity'``: GShard/switch-transformer physical dispatch
  (parallel/moe_dispatch.py) — tokens go to one expert buffer bounded by
  ``capacity_factor``, crossing the ``expert`` mesh axis via
  ``lax.all_to_all``; over-capacity tokens drop (the classifier's residual
  carries them). Equal to dense dispatch when nothing drops.

Both modes sow the switch load-balancing auxiliary loss under
``intermediates/aux_loss`` (E * sum_e f_e p_e; 1.0 = uniform): top-1
routing can collapse onto one expert under real training, so trainers that
optimize the MoE for accuracy should add ``aux_weight * aux_loss`` to the
objective (pull it out with ``capture_intermediates``).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import Mesh

from pytorch_distributed_mnist_tpu.models.registry import register_model
from pytorch_distributed_mnist_tpu.parallel.moe_dispatch import (
    load_balance_loss,
    moe_capacity_forward,
    top1_mask_gate,
)


class SwitchMoE(nn.Module):
    """Top-1-routed mixture of expert MLPs: (B, C) -> (B, C)."""

    num_experts: int = 8
    hidden: int = 128
    compute_dtype: jnp.dtype = jnp.float32
    dispatch: str = "dense"
    capacity_factor: float = 1.25
    mesh: Optional[Mesh] = None
    expert_axis: str = "expert"
    data_axis: Optional[str] = "data"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        e, h, c = self.num_experts, self.hidden, x.shape[-1]
        router = nn.Dense(e, dtype=jnp.float32, name="router")
        # Router math in f32: top-1 selection is a discrete decision; bf16
        # logit noise would make routing (and therefore loss) layout-dependent.
        probs = nn.softmax(router(x.astype(jnp.float32)), axis=-1)  # (B, E)
        self.sow("intermediates", "aux_loss", load_balance_loss(probs))

        w1 = self.param("w1", nn.initializers.lecun_normal(), (e, c, h))
        b1 = self.param("b1", nn.initializers.zeros, (e, h))
        w2 = self.param("w2", nn.initializers.lecun_normal(), (e, h, c))
        b2 = self.param("b2", nn.initializers.zeros, (e, c))

        if self.dispatch == "capacity":
            out = moe_capacity_forward(
                x.astype(self.compute_dtype), probs, w1, b1, w2, b2,
                capacity_factor=self.capacity_factor,
                compute_dtype=self.compute_dtype, mesh=self.mesh,
                expert_axis=self.expert_axis, data_axis=self.data_axis,
            )
            return out.astype(x.dtype)
        if self.dispatch != "dense":
            raise ValueError(f"unknown dispatch {self.dispatch!r}")

        mask, gate = top1_mask_gate(probs)  # (B, E) one-hot, (B,) prob
        gate = gate[:, None]
        xc = x.astype(self.compute_dtype)
        # (B, E, H): per-expert hidden; E shards on the 'expert' mesh axis.
        hdn = nn.relu(
            jnp.einsum("bc,ech->beh", xc, w1.astype(self.compute_dtype))
            + b1.astype(self.compute_dtype)
        )
        y = (
            jnp.einsum("beh,ehc->bec", hdn, w2.astype(self.compute_dtype))
            + b2.astype(self.compute_dtype)
        )  # (B, E, C)
        # One-hot combine: the sum over E is the EP AllReduce.
        out = jnp.einsum("bec,be->bc", y.astype(jnp.float32), mask) * gate
        return out.astype(x.dtype)


@register_model("moe_mlp")
class MoEClassifier(nn.Module):
    """flatten -> embed -> residual SwitchMoE -> head (MNIST classifier)."""

    num_classes: int = 10
    num_experts: int = 8
    embed_dim: int = 64
    hidden: int = 128
    compute_dtype: jnp.dtype = jnp.float32
    dispatch: str = "dense"
    capacity_factor: float = 1.25
    mesh: Optional[Mesh] = None
    expert_axis: str = "expert"
    data_axis: Optional[str] = "data"

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train
        x = x.reshape((x.shape[0], -1)).astype(self.compute_dtype)  # (B, 784)
        x = nn.Dense(self.embed_dim, dtype=self.compute_dtype, name="embed")(x)
        x = nn.relu(x)
        x = x + SwitchMoE(
            self.num_experts, self.hidden, self.compute_dtype,
            dispatch=self.dispatch, capacity_factor=self.capacity_factor,
            mesh=self.mesh, expert_axis=self.expert_axis,
            data_axis=self.data_axis, name="moe",
        )(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype, name="head")(x)
        return x.astype(jnp.float32)
