"""Dataset acquisition: fetch + verify the four IDX files.

The reference gets this from torchvision's ``datasets.MNIST(root,
download=True)`` (``/root/reference/multi_proc_single_gpu.py:137-138``;
``README.md:42-48`` documents the world-size-1 pre-download run). This is
the first-party equivalent: stdlib-only HTTP(S) fetch of the gzipped IDX
files into ``root/<name>/``, checksum verification, atomic writes
(tmp + ``os.replace``), and skip-if-present idempotence.

Design notes:

- ``urllib`` also serves ``file://`` URLs, so the whole path is testable
  offline with a local mirror directory (tests/test_download.py) — the
  no-egress analog of torchvision's mirror list.
- Checksums are MD5 (the values every MNIST mirror publishes and
  torchvision pins); callers can pass their own ``checksums`` for private
  mirrors. Verification failure deletes the file and raises — a truncated
  or tampered download never becomes load-bearing.
- Only process 0 of a multi-host job should download (the reference gets
  the same property manually via its world-size-1 pre-download run);
  ``download_dataset`` takes ``process_index`` for that gate.
"""

from __future__ import annotations

import gzip
import hashlib
import os
import urllib.error
import urllib.request
from typing import Dict, Iterable, Optional, Sequence

_GZ_FILES = (
    "train-images-idx3-ubyte.gz",
    "train-labels-idx1-ubyte.gz",
    "t10k-images-idx3-ubyte.gz",
    "t10k-labels-idx1-ubyte.gz",
)

# Public mirror lists; first reachable wins.
MIRRORS: Dict[str, Sequence[str]] = {
    "mnist": (
        "https://ossci-datasets.s3.amazonaws.com/mnist/",
        "http://yann.lecun.com/exdb/mnist/",
    ),
    "fashion_mnist": (
        "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/",
    ),
}

# MD5 of each .gz as published by the mirrors (and pinned by torchvision).
CHECKSUMS: Dict[str, Dict[str, str]] = {
    "mnist": {
        "train-images-idx3-ubyte.gz": "f68b3c2dcbeaaa9fbdd348bbdeb94873",
        "train-labels-idx1-ubyte.gz": "d53e105ee54ea40749a09fcbcd1e9432",
        "t10k-images-idx3-ubyte.gz": "9fb629c4189551a2d022fa330f9573f3",
        "t10k-labels-idx1-ubyte.gz": "ec29112dd5afa0611ce80d1b7f02629c",
    },
    "fashion_mnist": {
        "train-images-idx3-ubyte.gz": "8d4fb7e6c68d591d4c3dfef9ec88bf0d",
        "train-labels-idx1-ubyte.gz": "25c81989df183df01b3e8a0aad5dffbe",
        "t10k-images-idx3-ubyte.gz": "bef4ecab320f06d8554ea6380940ec79",
        "t10k-labels-idx1-ubyte.gz": "bb300cfdad3c16e7a12a480ee83cd310",
    },
}


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _looks_like_idx_gz(path: str) -> bool:
    """Cheap sanity check used when no checksum is pinned: gunzips and has
    an IDX magic (0x0000 08xx)."""
    try:
        with gzip.open(path, "rb") as f:
            head = f.read(4)
    except Exception:
        # Broad on purpose (tpumnist-lint audit): this predicate answers
        # "is the published file usable?" — truncated-after-header
        # (EOFError), unreadable (OSError), AND corrupt mid-stream
        # (zlib.error, not an OSError subclass) must all answer False so
        # the fetch loop deletes and retries, never crashes.
        return False
    return len(head) == 4 and head[0] == 0 and head[1] == 0 and head[2] == 8


class _PermanentFetchError(Exception):
    """A per-URL failure retrying cannot fix (HTTP 4xx: the mirror is up
    and definitively does not serve this file) — fail over to the next
    mirror immediately instead of burning backoff attempts."""


def _fetch(url: str, dest: str, timeout: float) -> None:
    from pytorch_distributed_mnist_tpu.runtime.supervision import maybe_fault

    maybe_fault("download_fetch")
    # pid-unique tmp: concurrent downloaders (multiple hosts sharing a
    # filesystem) each publish atomically instead of interleaving writes.
    tmp = f"{dest}.tmp{os.getpid()}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r, open(tmp, "wb") as f:
            # getattr: test doubles (and file:// responses on some
            # platforms) expose a bare file object without headers.
            headers = getattr(r, "headers", None)
            expected = headers.get("Content-Length") if headers else None
            received = 0
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                received += len(chunk)
                f.write(chunk)
            # A connection torn mid-body ends the chunk loop exactly like
            # a complete one (read() reports EOF either way); only the
            # byte count knows. OSError feeds _fetch_verified's
            # delete-and-retry path instead of publishing a truncated
            # file the gzip gate must then catch.
            if expected is not None and received != int(expected):
                raise OSError(f"short read from {url}: got {received} "
                              f"of {expected} bytes")
        os.replace(tmp, dest)  # atomic publish, like checkpoint writes
    finally:
        if os.path.exists(tmp):  # mid-stream failure: no orphan partials
            os.remove(tmp)


def _fetch_verified(url: str, dest: str, timeout: float,
                    want_md5: Optional[str], attempts: int = 3) -> None:
    """Fetch ``url`` and verify it, retrying with exponential backoff.

    One mirror used to get exactly one shot: a transient reset (or a
    proxy serving one truncated body) failed the file over to the next
    mirror — or, for the single-mirror datasets, failed the download
    outright. Each attempt now re-verifies the published file (pinned
    md5, else the gunzip+IDX-magic sanity gate — a truncated-but-
    well-formed gzip prefix passes a naive existence check but not this)
    and a verification failure deletes the file and retries like any
    network error, with backoff + jitter so multiple hosts hammering a
    shared mirror de-synchronize. Raises the last error when ``attempts``
    are exhausted; the caller's mirror loop then moves on.
    """
    from pytorch_distributed_mnist_tpu.utils.profiling import failure_events
    from pytorch_distributed_mnist_tpu.utils.watchdog import (
        retry_with_backoff,
    )

    def attempt() -> None:
        try:
            _fetch(url, dest, timeout)
        except urllib.error.HTTPError as exc:
            if exc.code < 500:
                # Deterministic refusal (404 on a dead mirror layout,
                # 403): identical on every retry — move on now.
                raise _PermanentFetchError(f"{exc}") from exc
            raise
        if want_md5:
            got = _md5(dest)
            if got != want_md5:
                os.remove(dest)
                raise ValueError(
                    f"checksum mismatch (got {got}, want {want_md5})")
        elif not _looks_like_idx_gz(dest):
            os.remove(dest)
            raise ValueError("not a gzipped IDX file")

    retry_with_backoff(
        attempt, attempts=attempts,
        retry_on=(urllib.error.URLError, OSError, ValueError),
        on_retry=lambda n, exc, delay: failure_events.record(
            "download_retry",
            f"{url} attempt {n} failed ({exc!r}); retrying in "
            f"{delay:.2f}s"),
    )


def dataset_present(directory: str, files: Iterable[str] = _GZ_FILES) -> bool:
    """True when every IDX file exists (gzipped or already decompressed)."""
    for name in files:
        raw = name[: -len(".gz")]
        if not (
            os.path.isfile(os.path.join(directory, name))
            or os.path.isfile(os.path.join(directory, raw))
        ):
            return False
    return True


def download_dataset(
    root: str,
    name: str = "mnist",
    mirrors: Optional[Sequence[str]] = None,
    checksums: Optional[Dict[str, str]] = None,
    timeout: float = 60.0,
    process_index: int = 0,
    attempts: int = 3,
) -> str:
    """Fetch ``name``'s four IDX .gz files into ``root/<name>/``.

    Returns the directory holding the files. Idempotent: files already
    present (and passing verification when a checksum is pinned) are kept.
    Non-zero ``process_index`` returns immediately — one downloader per
    filesystem, the multi-host analog of the reference's world-size-1
    pre-download run (``README.md:42-48``).

    Each mirror gets ``attempts`` tries with exponential backoff + jitter,
    and every attempt re-verifies what landed (``_fetch_verified``).
    Raises ``OSError`` when no mirror can serve a file after all retries.
    """
    directory = os.path.join(root, name)
    if process_index != 0:
        return directory
    if mirrors is None:
        mirrors = MIRRORS.get(name, ())
    if checksums is None:
        checksums = CHECKSUMS.get(name, {})
    os.makedirs(directory, exist_ok=True)

    for filename in _GZ_FILES:
        dest = os.path.join(directory, filename)
        want = checksums.get(filename)
        if os.path.isfile(dest) and (
            (want and _md5(dest) == want) or (not want and _looks_like_idx_gz(dest))
        ):
            continue
        if os.path.isfile(os.path.join(directory, filename[: -len(".gz")])):
            continue  # already decompressed (e.g. hand-placed raw IDX)
        errors = []
        for mirror in mirrors:
            url = mirror.rstrip("/") + "/" + filename
            try:
                _fetch_verified(url, dest, timeout, want, attempts=attempts)
            except (urllib.error.URLError, OSError, ValueError,
                    _PermanentFetchError) as exc:
                errors.append(f"{url}: {exc}")
                continue
            break
        else:
            raise OSError(
                f"could not download {filename} for {name!r}: "
                + ("; ".join(errors) if errors else "no mirrors configured")
            )
    return directory
