"""MNIST-family dataset IO.

Capability parity with the reference's data layer
(``/root/reference/multi_proc_single_gpu.py:129-161``):

- ``datasets.MNIST(root, train, transform, download=True)`` (``:137-138``)
  becomes a first-party IDX-format reader (the on-disk format torchvision
  downloads) over ``--root``, with gzip support;
- the ``ToTensor`` + ``Normalize((0.1307,), (0.3081,))`` transform
  (``:132-135``) becomes ``normalize_images`` using the same constants;
- ``download=True`` has no network analog in this environment, so the
  fallback is a deterministic **synthetic** MNIST-shaped dataset
  (procedurally rendered digit glyphs with jitter + noise) that exercises
  the identical pipeline and is learnable to high accuracy — used by tests
  and by runs without real data. Real IDX files in ``--root`` always win.
- the dataset is a constructor argument, not hard-coded as in the reference
  (``:137``): ``fashion_mnist`` (BASELINE.json config 5) is the same IDX
  format under a different root/subdir.

This module is the pure-NumPy implementation; an optional native C++ loader
(``native/``) can back the hot path when built.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Tuple

import numpy as np

# Reference transform constants (multi_proc_single_gpu.py:134).
MNIST_MEAN = 0.1307
MNIST_STD = 0.3081

_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}

_FILES = {
    True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def parse_idx(path: str) -> np.ndarray:
    """Parse an IDX file (the MNIST on-disk format), transparently gunzipping.

    Uses the native C++ reader when built (uint8 payloads, the MNIST case);
    falls back to pure NumPy for other dtypes or when the library is absent.
    """
    from pytorch_distributed_mnist_tpu.data import native

    got = native.parse_idx(path) if native.available() else None
    if got is not None:
        return got
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    zero, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    if zero != 0 or dtype_code not in _IDX_DTYPES:
        raise ValueError(f"{path}: not an IDX file (magic {data[:4]!r})")
    dims = struct.unpack(f">{ndim}I", data[4 : 4 + 4 * ndim])
    dtype = _IDX_DTYPES[dtype_code]
    arr = np.frombuffer(data, dtype, offset=4 + 4 * ndim).reshape(dims)
    return arr.astype(arr.dtype.newbyteorder("=")) if arr.dtype.byteorder == ">" else arr


def write_idx(path: str, arr: np.ndarray) -> None:
    """Write ``arr`` (uint8) in IDX format; inverse of ``parse_idx``."""
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, arr.ndim))
        f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
        f.write(arr.tobytes())


# --- Synthetic dataset -----------------------------------------------------

# 5x7 bitmap glyphs for digits 0-9; rendered, jittered, and noised into
# 28x28 uint8 images. Deterministic in (n, seed, train-split offset).
_GLYPHS = [
    "01110 10001 10011 10101 11001 10001 01110",
    "00100 01100 00100 00100 00100 00100 01110",
    "01110 10001 00001 00010 00100 01000 11111",
    "11111 00010 00100 00010 00001 10001 01110",
    "00010 00110 01010 10010 11111 00010 00010",
    "11111 10000 11110 00001 00001 10001 01110",
    "00110 01000 10000 11110 10001 10001 01110",
    "11111 00001 00010 00100 01000 01000 01000",
    "01110 10001 10001 01110 10001 10001 01110",
    "01110 10001 10001 01111 00001 00010 01100",
]


def _glyph_array(digit: int) -> np.ndarray:
    rows = _GLYPHS[digit].split()
    return np.array([[int(c) for c in row] for row in rows], dtype=np.float32)


def synthetic_dataset(
    n: int, seed: int = 0, num_classes: int = 10
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped synthetic data: (images u8 (n,28,28), labels u8)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.uint8)
    images = np.zeros((n, 28, 28), dtype=np.uint8)
    glyphs = [np.kron(_glyph_array(d), np.ones((3, 3), np.float32)) for d in range(10)]
    gh, gw = glyphs[0].shape  # 21 x 15
    offs = rng.integers(0, [28 - gh + 1, 28 - gw + 1], size=(n, 2))
    intensity = rng.uniform(0.6, 1.0, size=n)
    noise = rng.normal(0.0, 12.0, size=(n, 28, 28))
    for i in range(n):
        r, c = offs[i]
        canvas = np.zeros((28, 28), np.float32)
        canvas[r : r + gh, c : c + gw] = glyphs[labels[i]] * 255.0 * intensity[i]
        images[i] = np.clip(canvas + noise[i], 0, 255).astype(np.uint8)
    return images, labels


def dataset_dir(root: str, name: str) -> str:
    """Directory holding the IDX files for dataset ``name`` under ``root``.

    Accepts both torchvision's layout (``root/MNIST/raw``) and a flat
    ``root/`` or ``root/<name>/`` layout.
    """
    tv = {"mnist": "MNIST/raw", "fashion_mnist": "FashionMNIST/raw"}.get(name, name)
    for sub in (tv, name, ""):
        d = os.path.join(root, sub) if sub else root
        if os.path.isfile(os.path.join(d, _FILES[True][0])) or os.path.isfile(
            os.path.join(d, _FILES[True][0] + ".gz")
        ):
            return d
    return os.path.join(root, name)


def load_dataset(
    root: str,
    name: str = "mnist",
    train: bool = True,
    synthesize_if_missing: bool = True,
    synthetic_train_size: int = 60000,
    synthetic_test_size: int = 10000,
    seed: int = 0,
    download: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load (images u8 (N,28,28), labels u8) from IDX files, or synthesize.

    Real files under ``root`` always win. ``download=True`` is the analog of
    the reference's ``datasets.MNIST(..., download=True)`` (``:137-138``):
    fetch + checksum-verify the IDX files from the public mirrors
    (data/download.py) when absent. The synthetic fallback remains for
    no-egress environments. Train and test splits draw from disjoint seed
    streams so memorizing train does not trivially solve test.
    """
    d = dataset_dir(root, name)
    split_incomplete = not all(
        any(os.path.isfile(os.path.join(d, f + sfx)) for sfx in ("", ".gz"))
        for f in _FILES[train]
    )
    if download and split_incomplete:
        from pytorch_distributed_mnist_tpu.data.download import download_dataset
        from pytorch_distributed_mnist_tpu.runtime.supervision import (
            InjectedFault,
        )

        try:
            download_dataset(root, name)
        except InjectedFault:
            # The chaos harness targets the download_fetch point to
            # exercise the host-local-failure path — absorbing it into
            # the warn-and-fall-through below would neuter the injection
            # whenever files are already on disk.
            raise
        except Exception as exc:
            # Broad on purpose (tpumnist-lint audit): any download
            # failure — not just the OSError/ValueError pair this once
            # enumerated — falls through to the existing missing-file
            # policy (synthesize or raise FileNotFoundError) with the
            # cause surfaced. A zlib.error from a torn gzip here used to
            # escape the tuple and kill the caller outright.
            print(f"WARNING: download of {name!r} failed: {exc!r}")
        d = dataset_dir(root, name)
    img_name, lbl_name = _FILES[train]
    for suffix in ("", ".gz"):
        ip, lp = os.path.join(d, img_name + suffix), os.path.join(d, lbl_name + suffix)
        if os.path.isfile(ip) and os.path.isfile(lp):
            images, labels = parse_idx(ip), parse_idx(lp)
            if images.shape[0] != labels.shape[0]:
                raise ValueError(f"{ip}: image/label count mismatch")
            return images, labels
    if not synthesize_if_missing:
        raise FileNotFoundError(
            f"no {name} IDX files under {root!r} (looked in {d!r}); "
            "place train-images-idx3-ubyte[.gz] etc. there, or enable the "
            "synthetic fallback"
        )
    n = synthetic_train_size if train else synthetic_test_size
    return synthetic_dataset(n, seed=seed + (0 if train else 1_000_003))


def normalize_images(images: np.ndarray, workers: int = 4) -> np.ndarray:
    """uint8 (N,28,28) -> float32 (N,28,28,1), reference transform ``:132-135``.

    Multithreaded in native C++ when built; NumPy otherwise.
    """
    from pytorch_distributed_mnist_tpu.data import native

    if images.dtype == np.uint8 and native.available():
        got = native.normalize_images(images, MNIST_MEAN, MNIST_STD, workers)
        if got is not None:
            return got
    x = images.astype(np.float32) / 255.0
    x = (x - MNIST_MEAN) / MNIST_STD
    return x[..., None]
