"""Data pipeline: dataset IO, distributed shard sampling, host-sharded loading."""

from pytorch_distributed_mnist_tpu.data.mnist import (
    MNIST_MEAN,
    MNIST_STD,
    load_dataset,
    synthetic_dataset,
    normalize_images,
    parse_idx,
    write_idx,
)
from pytorch_distributed_mnist_tpu.data.sampler import DistributedShardSampler
from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader, make_global_batch

__all__ = [
    "MNIST_MEAN",
    "MNIST_STD",
    "load_dataset",
    "synthetic_dataset",
    "normalize_images",
    "parse_idx",
    "write_idx",
    "DistributedShardSampler",
    "MNISTDataLoader",
    "make_global_batch",
]
