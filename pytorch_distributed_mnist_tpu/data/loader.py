"""Host-sharded batch loading.

Capability parity with ``MNISTDataLoader``
(``/root/reference/multi_proc_single_gpu.py:129-161``), redesigned for the
TPU input path:

- the reference's per-process ``DataLoader`` + ``DistributedSampler`` +
  per-batch ``.cuda()`` H2D copies (``:84-85``) become: a per-*host* loader
  that yields this host's shard of each global batch as NumPy, plus
  ``make_global_batch`` which assembles the device-sharded ``jax.Array``
  (``device_put`` with a NamedSharding on one host;
  ``jax.make_array_from_process_local_data`` across hosts);
- ``set_sample_epoch(epoch)`` keeps its name and semantics (``:159-161``);
- the sampler-only-for-train policy (``:143-144``) is *configurable* here:
  the reference replicates eval on every rank (SURVEY.md section 3.3); the
  TPU default shards eval too, but ``shard_eval=False`` reproduces the
  reference behavior exactly;
- ``stacked_epoch()`` pre-stages a whole epoch as (steps, batch, ...) arrays
  for the ``lax.scan`` fast path — no per-batch host work at all.

Batch-size semantics: ``batch_size`` here is the **global** batch; each host
yields ``batch_size / num_hosts`` rows, and the array is further sharded
across that host's devices by the mesh. This makes the reference's
"``--batch-size`` is per-node total, divided among workers" rule (``:174``,
``:297-300``) explicit and host-count-invariant.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.data.sampler import DistributedShardSampler


class MNISTDataLoader:
    """Iterates (image, label) batches over this process's shard."""

    def __init__(
        self,
        images: np.ndarray,  # float32 (N, 28, 28, 1), already normalized
        labels: np.ndarray,  # int (N,)
        batch_size: int,
        train: bool = True,
        num_replicas: int = 1,
        rank: int = 0,
        seed: int = 0,
        shard: Optional[bool] = None,
        drop_last: Optional[bool] = None,
        workers: int = 4,
    ) -> None:
        if batch_size % num_replicas != 0:
            raise ValueError(
                f"global batch_size {batch_size} not divisible by "
                f"{num_replicas} processes"
            )
        self.images = images
        self.labels = np.asarray(labels, np.int32)
        self.workers = workers
        self.global_batch_size = batch_size
        self.local_batch_size = batch_size // num_replicas
        self.train = train
        # Parity default: shard train, replicate eval (reference :143-144);
        # pass shard=True on the eval loader for the faster sharded eval.
        shard = train if shard is None else shard
        # Train drops the ragged last batch so every step has a static shape
        # (XLA recompiles per shape); eval pads instead so all samples count.
        self.drop_last = train if drop_last is None else drop_last
        self.sampler = DistributedShardSampler(
            dataset_len=images.shape[0],
            num_replicas=num_replicas if shard else 1,
            rank=rank if shard else 0,
            shuffle=train,
            seed=seed,
        )

    def set_sample_epoch(self, epoch: int) -> None:
        """Reference-parity name (``:159-161``): reseed this epoch's shuffle."""
        self.sampler.set_epoch(epoch)

    @property
    def steps_per_epoch(self) -> int:
        n = len(self.sampler)
        return n // self.local_batch_size if self.drop_last else -(-n // self.local_batch_size)

    def epoch_ticks(self, epoch: Optional[int] = None):
        """(steps, local_batch) int index matrix + 0/1 validity mask —
        the public index-space form of an epoch, consumed by the
        device-gather path (``train/steps.py make_train_epoch_indexed``)
        the way ``stacked_epoch`` serves the host-gather path.

        Padding (wrapping from the front) keeps shapes static for XLA; the
        mask marks padded positions so metrics never double-count them.
        ``epoch`` selects a specific epoch's shuffle without mutating the
        sampler (see ``DistributedShardSampler.indices_and_mask``).
        """
        idx, valid = self.sampler.indices_and_mask(epoch)
        steps = self.steps_per_epoch
        need = steps * self.local_batch_size
        mask = np.ones(need, np.float32)
        mask[: min(idx.size, need)] = valid[:need]
        if need > idx.size:
            mask[idx.size :] = 0.0
            idx = np.concatenate([idx, idx[: need - idx.size]])
        shape = (steps, self.local_batch_size)
        return idx[:need].reshape(shape), mask.reshape(shape)

    def host_batch(self, row: np.ndarray, mrow: np.ndarray) -> Dict[str, np.ndarray]:
        """One batch's host-side rows for an ``epoch_ticks`` row — THE
        gather both ``__iter__`` and the pipelined feeder
        (``data/staging.py``) run, so the two paths cannot drift."""
        return {"image": self.images[row], "label": self.labels[row], "mask": mrow}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        m, mask = self.epoch_ticks()
        for row, mrow in zip(m, mask):
            yield self.host_batch(row, mrow)

    def __len__(self) -> int:
        return self.steps_per_epoch

    def batch_spec(self) -> Dict[str, jax.ShapeDtypeStruct]:
        """Abstract (shape, dtype) form of one assembled GLOBAL batch —
        what ``make_global_batch`` yields for one ``__iter__`` item. The
        AOT precompile path (``train/steps.py precompile``) lowers against
        this, so it lives HERE next to the code whose output it mirrors:
        a loader change that altered batch layout would break the spec in
        the same file."""
        b = self.global_batch_size
        return {
            "image": jax.ShapeDtypeStruct((b,) + self.images.shape[1:],
                                          self.images.dtype),
            "label": jax.ShapeDtypeStruct((b,), self.labels.dtype),
            "mask": jax.ShapeDtypeStruct((b,), np.float32),
        }

    def epoch_spec(self) -> Dict[str, jax.ShapeDtypeStruct]:
        """Abstract form of a whole staged GLOBAL epoch — ``stacked_epoch``
        assembled by ``make_global_batch(..., leading_replicated=True)``:
        every ``batch_spec`` leaf gains the leading steps axis."""
        s = self.steps_per_epoch
        return {
            k: jax.ShapeDtypeStruct((s,) + v.shape, v.dtype)
            for k, v in self.batch_spec().items()
        }

    def ticks_spec(self) -> Dict[str, jax.ShapeDtypeStruct]:
        """Abstract form of a GLOBAL ``epoch_ticks`` index matrix + mask —
        the device-gather path's per-epoch upload."""
        shape = (self.steps_per_epoch, self.global_batch_size)
        return {
            "idx": jax.ShapeDtypeStruct(shape, np.int32),
            "mask": jax.ShapeDtypeStruct(shape, np.float32),
        }

    def stacked_epoch(self, epoch: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Whole epoch as {'image': (S, B, ...), 'label': (S, B), 'mask': (S, B)}
        for lax.scan.

        The gather is the host-side hot path (one full-dataset permutation
        copy per epoch); it runs in multithreaded C++ when the native
        backend is built (``-j/--workers`` controls the thread count).
        ``epoch`` gathers a specific epoch's shuffle purely (no sampler
        mutation) — the trainer's background prefetch path.
        """
        from pytorch_distributed_mnist_tpu.data import native

        m, mask = self.epoch_ticks(epoch)
        if self.images.dtype == np.float32 and native.available():
            got = native.gather_epoch(self.images, self.labels, m, self.workers)
            if got is not None:
                images, labels = got
                return {"image": images, "label": labels, "mask": mask}
        return {
            "image": self.images[m.reshape(-1)].reshape(m.shape + self.images.shape[1:]),
            "label": self.labels[m.reshape(-1)].reshape(m.shape),
            "mask": mask,
        }


def make_replicated(data: Dict[str, np.ndarray], mesh: Optional[Mesh]):
    """Place host arrays on device fully replicated (every device, every
    host, the whole array) — the layout the device-gather epoch path uses
    for the resident dataset (train/steps.py make_train_epoch_indexed)."""
    return make_global_batch(data, mesh, spec=P())


def make_global_batch(
    batch: Dict[str, np.ndarray],
    mesh: Optional[Mesh],
    axis: str = "data",
    leading_replicated: bool = False,
    spec: Optional[P] = None,
) -> Dict[str, jax.Array]:
    """Assemble this host's local batch into a (possibly) global jax.Array.

    Single host: a ``device_put`` with NamedSharding splits the batch across
    local devices. Multi-host: ``jax.make_array_from_process_local_data``
    builds the global array from per-host shards — the TPU analog of each
    DDP rank holding its own sampler shard (``:143-144``).

    ``leading_replicated=True`` is for stacked epochs (steps axis first):
    shards dim 1 instead of dim 0. ``spec`` overrides the PartitionSpec
    entirely (``P()`` = fully replicated, every host passing the full
    array — ``make_replicated``).
    """
    if mesh is None:
        return {k: jax.device_put(v) for k, v in batch.items()}
    if spec is None:
        from pytorch_distributed_mnist_tpu.parallel.mesh import (
            resolve_data_axis,
        )

        # Hierarchical meshes shard rows over the composed ('dcn',
        # 'ici') pair — same rows per composed coordinate either way.
        axis = resolve_data_axis(mesh, axis)
        spec = P(None, axis) if leading_replicated else P(axis)
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, sharding) for k, v in batch.items()}
    return {
        k: jax.make_array_from_process_local_data(sharding, v) for k, v in batch.items()
    }
