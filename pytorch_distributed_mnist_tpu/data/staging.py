"""Pipelined host->device input staging for the per-batch trainer modes.

The reference hides input latency behind torch DataLoader worker
processes and still pays a per-batch ``.cuda()`` copy on the critical
path (``/root/reference/multi_proc_single_gpu.py:84-85, 156``). The scan
trainer already beat that by staging whole epochs; the per-batch modes
(``stepwise``/``explicit``) kept the reference's shape — every step
blocks on ``make_global_batch`` (host gather + sharded ``device_put``)
before it can dispatch. :class:`BatchFeeder` is the train twin of the
serve plane's pipelined dispatch (``serve/batcher.py`` form/dispatch vs
completion): a feeder thread performs batch N+1's host gather and H2D
transfer while the jitted step for batch N executes, bounded by a
window.

Window semantics (mirroring ``--max-inflight``): ``window`` counts the
batch the consumer holds plus at most ``window - 1`` existing beyond it
(staged or mid-staging — the batch in the feeder's hands counts against
the bound). ``window=1`` disables the feeder thread entirely — staging
runs inline on the consumer thread, today's strict gather->put->step
alternation, bit-for-bit (pinned by test). ``window=2`` is classic
double buffering: one batch consumed while one stages ahead.

Correctness rules, in the house style:

- **Purity.** The feeder thread never mutates the shared sampler: the
  epoch's index matrix is snapshotted via ``loader.epoch_ticks()`` on
  the CONSUMER thread before the feeder starts, so a concurrent
  ``set_sample_epoch`` (resume jump) cannot race it — the next
  ``epoch()`` call simply snapshots the new epoch. Within one epoch
  there is no staleness to rule on.
- **No collectives on the feeder thread.** Supervision's
  no-concurrent-collectives invariant: multi-process assembly
  (``jax.make_array_from_process_local_data``) stays off the feeder, so
  pipelined feeding engages only in single-process worlds
  (``jax.process_count() == 1``); multi-host runs degenerate to the
  inline window-1 path, exactly the behavior they had. Nothing on the
  feeder thread is conditioned on ``process_index()``.
- **Bitwise invariance.** The staged batches are the same NumPy rows
  through the same ``make_global_batch`` in the same order whichever
  thread runs it; pipelining is a latency optimization, never a
  semantics change (the ``prefetch_enabled`` rule, extended).

Every stage records into a :class:`~pytorch_distributed_mnist_tpu.
utils.profiling.StagingLog` when one is attached: host-gather ms, H2D
ms, and how long the consumer actually blocked — the overlap evidence
``bench.py --mode input`` and the cli summary surface.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Iterator, Optional

import jax

from pytorch_distributed_mnist_tpu.data.loader import make_global_batch


class _EpochRun:
    """One epoch's feeder thread + bounded staged-batch conduit.

    The conduit is a deque guarded by one condition variable
    (``BatchFeeder._cv`` idiom, same as the serve batcher's ``_cv``):
    the feeder stages OUTSIDE the lock — gather and ``device_put`` are
    the slow parts, and blocking work under a held lock is exactly what
    the lock-discipline checker forbids — then appends under it;
    the consumer waits under it and pops. ``close()`` unblocks both
    sides so an abandoned epoch (consumer raised mid-step) never leaks
    a thread blocked on a full conduit.
    """

    def __init__(self, feeder: "BatchFeeder", m, mask) -> None:
        self.feeder = feeder
        self._cv = threading.Condition()
        self._staged: collections.deque = collections.deque()
        self._error: Optional[BaseException] = None
        self._done = False
        self._cancelled = False
        self._thread = threading.Thread(
            target=self._feed, args=(m, mask), daemon=True,
            name="input-feeder")
        self._thread.start()

    def _feed(self, m, mask) -> None:
        feeder = self.feeder
        try:
            for row, mrow in zip(m, mask):
                # Wait for conduit room BEFORE staging: the batch being
                # staged counts against the window too, so window W keeps
                # at most W-1 staged batches beyond the one the consumer
                # holds (W=2 = one ahead, classic double buffering) —
                # staging first would silently hold one extra full
                # global batch resident in device memory.
                with self._cv:
                    while (len(self._staged) >= feeder.window - 1
                           and not self._cancelled):
                        self._cv.wait()
                    if self._cancelled:
                        return
                staged = feeder._stage(row, mrow, pipelined=True)
                with self._cv:
                    if self._cancelled:
                        return
                    self._staged.append(staged)
                    self._cv.notify_all()
        except BaseException as exc:  # noqa: BLE001 - re-raised at next()
            with self._cv:
                self._error = exc
                self._cv.notify_all()
        else:
            with self._cv:
                self._done = True
                self._cv.notify_all()

    def next_batch(self):
        """Pop the next staged batch, blocking until the feeder delivers
        one (the blocked time is the un-overlapped staging cost and is
        recorded as such). Raises the feeder's error, or StopIteration
        when the epoch is drained."""
        t0 = time.perf_counter()
        with self._cv:
            while not self._staged and not self._done \
                    and self._error is None and not self._cancelled:
                self._cv.wait()
            wait_ms = (time.perf_counter() - t0) * 1e3
            if self._staged:
                batch = self._staged.popleft()
                self._cv.notify_all()
            elif self._error is not None:
                raise self._error
            else:
                # Done and drained — or cancelled: a close() from
                # ANOTHER thread (teardown hooks) must unblock a
                # consumer parked on the cv, not strand it; cancelled
                # reads as end-of-epoch.
                batch = None
        log = self.feeder.staging_log
        if log is not None:
            log.record_wait(wait_ms)
        if batch is None:
            raise StopIteration
        return batch

    def close(self) -> None:
        """Cancel and join the feeder (idempotent): a consumer that
        abandons the epoch mid-way must not strand a thread blocked on
        the full conduit."""
        with self._cv:
            self._cancelled = True
            self._staged.clear()
            self._cv.notify_all()
        self._thread.join()


class BatchFeeder:
    """Double-buffered host->device staging for one loader.

    ``epoch()`` yields the same device-sharded global batches the
    synchronous ``make_global_batch(batch, mesh)`` loop produced, in the
    same order, for the loader's CURRENT sampler epoch — with the
    staging of batch N+1 overlapped against whatever the caller does
    with batch N (dispatching a jitted step, under JAX async dispatch)
    when ``window > 1``.
    """

    def __init__(self, loader, mesh, window: int = 2,
                 staging_log=None) -> None:
        if window < 1:
            raise ValueError(f"feed window must be >= 1, got {window}")
        self.loader = loader
        self.mesh = mesh
        self.window = int(window)
        self.staging_log = staging_log
        self._active_run: Optional[_EpochRun] = None

    @property
    def pipelined(self) -> bool:
        """Whether epochs will actually run the feeder thread: a window
        of 1 is the inline path by definition, and multi-process worlds
        stay inline so no array assembly (a cross-host-visible
        operation) ever runs off the main thread (supervision's
        no-concurrent-collectives rule)."""
        return self.window > 1 and jax.process_count() == 1

    def _stage(self, row, mrow, pipelined: bool):
        """Gather one batch's rows and assemble the global array,
        recording host vs H2D wall into the staging log."""
        t0 = time.perf_counter()
        batch = self.loader.host_batch(row, mrow)
        t1 = time.perf_counter()
        staged = make_global_batch(batch, self.mesh)
        if self.staging_log is not None:
            t2 = time.perf_counter()
            self.staging_log.record_stage(
                host_ms=(t1 - t0) * 1e3, h2d_ms=(t2 - t1) * 1e3,
                images=len(row), pipelined=pipelined)
        return staged

    def epoch(self) -> Iterator[dict]:
        """Iterate one epoch of staged global batches.

        The index matrix is snapshotted HERE, on the consumer thread,
        before any background work starts — the feeder never reads the
        (mutable) sampler, so epoch jumps between ``epoch()`` calls are
        trivially safe."""
        # A previous epoch abandoned via exception may still be live
        # (the traceback pins its generator — and the finally that
        # would close it — until GC): join it BEFORE starting the next
        # run, or reassigning _active_run below would orphan its feeder
        # thread beyond close()'s reach.
        self.close()
        m, mask = self.loader.epoch_ticks()
        if not self.pipelined or len(m) == 0:
            return self._inline_epoch(m, mask)
        return self._pipelined_epoch(m, mask)

    def _inline_epoch(self, m, mask) -> Iterator[dict]:
        """Window 1 / multi-process: stage on the consumer thread —
        today's strict alternation, bit-for-bit. The whole staging wall
        is un-overlapped by construction, recorded as consumer wait so
        the overlap fraction honestly reads 0."""
        for row, mrow in zip(m, mask):
            t0 = time.perf_counter()
            staged = self._stage(row, mrow, pipelined=False)
            if self.staging_log is not None:
                self.staging_log.record_wait(
                    (time.perf_counter() - t0) * 1e3)
            yield staged

    def close(self) -> None:
        """Cancel and join the in-flight epoch's feeder thread, if any
        (idempotent). A consumer that abandons ``epoch()`` via an
        exception does NOT run the generator's ``finally`` promptly —
        the traceback keeps the frame (and iterator) alive until GC —
        so teardown paths (``Trainer.close``, cli's ``closing``) call
        this to join the feeder before the runtime goes away."""
        run = self._active_run
        if run is not None:
            self._active_run = None
            run.close()

    def _pipelined_epoch(self, m, mask) -> Iterator[dict]:
        run = _EpochRun(self, m, mask)
        self._active_run = run
        try:
            while True:
                try:
                    batch = run.next_batch()
                except StopIteration:
                    return
                yield batch
        finally:
            if self._active_run is run:
                self._active_run = None
            run.close()
