"""ctypes binding for the native C++ data backend (``native/``).

The reference leans on torch's native DataLoader workers for its host-side
data path (``/root/reference/multi_proc_single_gpu.py:156``); this module is
the TPU framework's first-party equivalent: IDX parsing (raw + gzip),
normalize, and epoch gather run in multithreaded C++ when
``libtpumnist_native.so`` is built (``make -C native``), with the worker
count coming from the CLI's ``-j/--workers`` flag. Every entry point has a
pure-NumPy fallback in ``data/mnist.py`` / ``data/loader.py``; the native
path is an optimization, never a requirement.

Serving note (DESIGN.md §7k): on a FUSED serve plane the per-request
``tm_cast_f32``/``tm_normalize``/``tm_quant_i8`` calls disappear — raw
uint8 requests stage as bytes and that math runs inside the fused XLA
program. These kernels remain the training input path and the split
(``--no-fuse`` / float-input) serve plane, which is the bitwise
reference the fused programs are pinned against.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

_LIB_NAME = "libtpumnist_native.so"


def _find_library() -> Optional[str]:
    if os.environ.get("TPUMNIST_NATIVE", "") == "0":
        # Explicit fallback switch: equivalence tests and the input bench
        # time the pure-NumPy path in a process that HAS the library.
        return None
    # TPUMNIST_ is the house env prefix (compile cache, faults,
    # timeouts); the historical TPU_MNIST_ spelling keeps working.
    override = (os.environ.get("TPUMNIST_NATIVE_LIB")
                or os.environ.get("TPU_MNIST_NATIVE_LIB"))
    candidates = [override] if override else []
    here = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(os.path.dirname(here))
    candidates += [
        os.path.join(repo_root, "native", _LIB_NAME),
        os.path.join(here, _LIB_NAME),
    ]
    for c in candidates:
        if c and os.path.isfile(c):
            return c
    return None


_lib = None
#: Negative-cache sentinel: pad_into/cast_f32 run PER DISPATCHED BATCH
#: on the serve hot path, so a fallback environment must not re-walk
#: the filesystem probe (env reads + two stat()s) on every batch.
#: ``_lib = None`` stays the one reset switch (tests and the input
#: bench's in-process A/B flip rely on it) — it clears this cache too.
_MISSING = object()


def _load():
    global _lib
    if _lib is not None:
        return None if _lib is _MISSING else _lib
    path = _find_library()
    if path is None:
        _lib = _MISSING
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        _lib = _MISSING
        return None
    lib.tm_idx_load.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.tm_idx_load.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.tm_free.restype = None
    lib.tm_free.argtypes = [ctypes.c_void_p]
    lib.tm_normalize.restype = ctypes.c_int
    lib.tm_normalize.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_int,
    ]
    lib.tm_gather.restype = ctypes.c_int
    lib.tm_gather.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
    ]
    lib.tm_version.restype = ctypes.c_int
    if lib.tm_version() < 4:
        # A stale library is rejected WHOLE, not just its missing
        # symbols: v3 rewrote tm_normalize to the fallback's exact f32 op
        # sequence (the old fused kernel is ~1ulp off the bits every
        # trajectory/equivalence pin asserts), and v4 added the
        # quant/dequant entry points the int8 serving plane stages
        # through — a partial surface would silently mix native and
        # fallback behavior per call site. Stale (pre-v4) -> fallback,
        # per DESIGN.md 4b's matrix.
        _lib = _MISSING
        return None
    # v3 entry points (serve dispatch path) — guaranteed present past
    # the version gate above. void-pointer argtypes on purpose: these
    # two run PER DISPATCHED BATCH on the serve hot path, and
    # ``ndarray.ctypes.data_as`` costs ~5us per cast while the raw
    # ``.ctypes.data`` integer is sub-microsecond — at bucket sizes the
    # cast overhead alone exceeded the copy.
    lib.tm_pad_copy.restype = ctypes.c_int
    lib.tm_pad_copy.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
    ]
    lib.tm_cast_f32.restype = ctypes.c_int
    lib.tm_cast_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
    ]
    # v4 entry points (int8 serving plane): activation quantization runs
    # PER DISPATCHED BATCH on the serve hot path — same raw-pointer
    # argtypes rationale as pad_copy/cast_f32 above.
    lib.tm_quant_i8.restype = ctypes.c_int
    lib.tm_quant_i8.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_int,
    ]
    lib.tm_dequant_f32.restype = ctypes.c_int
    lib.tm_dequant_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_int,
    ]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def parse_idx(path: str) -> Optional[np.ndarray]:
    """Native IDX parse (uint8 only), one read+inflate pass; None if
    unavailable or unsupported (the NumPy path then produces the real error)."""
    lib = _load()
    if lib is None:
        return None
    dims = (ctypes.c_int64 * 8)()
    ndim = ctypes.c_int(0)
    count = ctypes.c_int64(0)
    buf = lib.tm_idx_load(path.encode(), dims, ctypes.byref(ndim), 8,
                          ctypes.byref(count))
    if not buf:
        return None
    try:
        shape = tuple(int(dims[i]) for i in range(ndim.value))
        arr = np.ctypeslib.as_array(buf, shape=(int(count.value),)).copy()
    finally:
        lib.tm_free(buf)
    return arr.reshape(shape)


def normalize_images(images: np.ndarray, mean: float, std: float,
                     workers: int = 4) -> Optional[np.ndarray]:
    """Native (x/255 - mean)/std; returns (N,28,28,1) f32 or None."""
    lib = _load()
    if lib is None:
        return None
    flat = np.ascontiguousarray(images, np.uint8).reshape(-1)
    out = np.empty(flat.size, np.float32)
    lib.tm_normalize(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        flat.size, mean, std, workers,
    )
    return out.reshape(images.shape + (1,))


def gather_epoch(
    images: np.ndarray, labels: np.ndarray, index_matrix: np.ndarray,
    workers: int = 4,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native stacked-epoch gather: images (N, ...) f32, labels (N,) i32,
    index_matrix (S, B) -> ((S, B, ...) images, (S, B) labels), or None."""
    lib = _load()
    if lib is None:
        return None
    images = np.ascontiguousarray(images, np.float32)
    labels = np.ascontiguousarray(labels, np.int32)
    idx = np.ascontiguousarray(index_matrix, np.int64).reshape(-1)
    row = int(np.prod(images.shape[1:]))
    out_images = np.empty((idx.size, row), np.float32)
    out_labels = np.empty(idx.size, np.int32)
    rc = lib.tm_gather(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        idx.size, row, images.shape[0],
        out_images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        workers,
    )
    if rc != 0:
        return None
    shape = index_matrix.shape + images.shape[1:]
    return out_images.reshape(shape), out_labels.reshape(index_matrix.shape)


def pad_into(dst: np.ndarray, src: np.ndarray, workers: int = 4) -> bool:
    """Native serve-dispatch staging fill: ``dst[:len(src)] = src;
    dst[len(src):] = 0`` in multithreaded C++. Returns False (caller runs
    the bitwise-identical NumPy fallback) when the library is absent/old
    or either array is not float32 C-contiguous with matching rows."""
    lib = _load()
    if lib is None:  # absent, unloadable, or pre-v3 (rejected whole)
        return False
    if dst.dtype != np.float32 or src.dtype != np.float32:
        return False
    if not (dst.flags["C_CONTIGUOUS"] and src.flags["C_CONTIGUOUS"]):
        return False
    if not dst.flags["WRITEABLE"]:
        # The C kernel writes through the raw pointer; a frozen dst must
        # fall back so NumPy's slice-assign raises like it always did.
        return False
    if dst.ndim < 1 or src.shape[1:] != dst.shape[1:] \
            or src.shape[0] > dst.shape[0]:
        return False
    row = 1
    for d in dst.shape[1:]:
        row *= d
    rc = lib.tm_pad_copy(src.ctypes.data, src.shape[0], row,
                         dst.ctypes.data, dst.shape[0], workers)
    return rc == 0


def quant_i8(arr: np.ndarray, scale: float,
             workers: int = 4) -> Optional[np.ndarray]:
    """Native float32 -> int8 symmetric quantization:
    ``clip(rint(x * (1/scale)), -127, 127)`` with round-to-nearest-even —
    BITWISE-identical to the NumPy fallback (which must multiply by the
    same precomputed f32 reciprocal, not divide; ``serve/programs.py``
    does). None when the library is absent/old, the dtype/layout is
    wrong, or the scale is not positive."""
    lib = _load()
    if lib is None:  # absent, unloadable, or pre-v4 (rejected whole)
        return None
    if arr.dtype != np.float32 or not arr.flags["C_CONTIGUOUS"]:
        return None
    if not (scale > 0.0):
        return None
    out = np.empty(arr.shape, np.int8)
    rc = lib.tm_quant_i8(arr.ctypes.data, out.ctypes.data, arr.size,
                         scale, workers)
    return out if rc == 0 else None


def dequant_f32(arr: np.ndarray, scale: float,
                workers: int = 4) -> Optional[np.ndarray]:
    """Native int8 -> float32 dequantization (``float(q) * scale``, the
    NumPy fallback's exact op — bitwise-identical); None when the
    library is absent/old or the dtype/layout is wrong."""
    lib = _load()
    if lib is None:  # absent, unloadable, or pre-v4 (rejected whole)
        return None
    if arr.dtype != np.int8 or not arr.flags["C_CONTIGUOUS"]:
        return None
    out = np.empty(arr.shape, np.float32)
    rc = lib.tm_dequant_f32(arr.ctypes.data, out.ctypes.data, arr.size,
                            scale, workers)
    return out if rc == 0 else None


def cast_f32(arr: np.ndarray, workers: int = 4) -> Optional[np.ndarray]:
    """Native float64 -> float32 (round-to-nearest-even, the same C
    conversion NumPy's ``astype`` performs — bitwise-identical); None for
    any other dtype/layout or when the library is absent/old."""
    lib = _load()
    if lib is None:  # absent, unloadable, or pre-v3 (rejected whole)
        return None
    if arr.dtype != np.float64 or not arr.flags["C_CONTIGUOUS"]:
        return None
    out = np.empty(arr.shape, np.float32)
    rc = lib.tm_cast_f32(arr.ctypes.data, out.ctypes.data,
                         arr.size, workers)
    return out if rc == 0 else None
