"""Distributed shard sampler.

Semantic parity with ``torch.utils.data.DistributedSampler`` as the
reference uses it (``/root/reference/multi_proc_single_gpu.py:143-144,
159-161``):

- each of ``num_replicas`` participants gets a **disjoint** 1/num_replicas
  shard of the dataset;
- shards are padded (by wrapping from the front) so every replica sees the
  same number of samples — required so every device runs the same number of
  steps (in SPMD, a replica running an extra step would deadlock the
  collective, the same way an extra NCCL allreduce hangs DDP);
- per-epoch reshuffle via ``set_epoch(epoch)``: the permutation is seeded
  with ``seed + epoch``, deterministic but different each epoch (``:159-161``
  calls this from the job driver at ``:231``);
- with ``shuffle=False`` the order is sequential (the reference's test
  loader path, ``:148-149``).

Pure index arithmetic over (dataset_len, num_replicas, rank) — unit-testable
without any devices (SURVEY.md section 4 "multi-host logic").
"""

from __future__ import annotations

import numpy as np


class DistributedShardSampler:
    """Disjoint per-replica index shards with epoch-seeded reshuffle."""

    def __init__(
        self,
        dataset_len: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ) -> None:
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = -(-dataset_len // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shuffle for ``epoch`` (parity: sampler.set_epoch, ``:161``)."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        """This replica's index shard for the current epoch."""
        return self.indices_and_mask()[0]

    def indices_and_mask(self, epoch: int | None = None):
        """(indices, valid) for this replica; ``valid`` is 0.0 on pad entries.

        Pad entries exist when the dataset size is not divisible by
        ``num_replicas`` (wrap-padding, torch DistributedSampler policy).
        torch counts the duplicates in eval; the mask lets this framework
        report exact whole-dataset metrics instead.

        ``epoch`` overrides ``self.epoch`` without mutating it — the pure
        form the trainer's background prefetch uses so it never races a
        concurrent ``set_epoch`` from the caller.
        """
        if epoch is None:
            epoch = self.epoch
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            order = rng.permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        valid = np.ones(self.dataset_len, np.float32)
        if self.drop_last:
            order = order[: self.total_size]
            valid = valid[: self.total_size]
        elif self.total_size > self.dataset_len:
            pad = self.total_size - self.dataset_len
            order = np.concatenate([order, order[:pad]])
            valid = np.concatenate([valid, np.zeros(pad, np.float32)])
        sl = slice(self.rank, self.total_size, self.num_replicas)
        return order[sl], valid[sl]

    def __len__(self) -> int:
        return self.num_samples
