"""MPMD pipeline serving: independent per-stage programs, streamed
micro-batches.

The SPMD serving planes (``serve/programs.py``) lower ONE program over
the whole mesh — which is exactly why the pipeline layout could not
serve: a pipeline-trained checkpoint's params are stage-stacked, and a
single spanning program would hold every stage's weights everywhere,
forfeiting the one thing pipeline parallelism buys (params bigger than
one chip's HBM). Following the MPMD pipeline-parallelism direction in
PAPERS.md — and in contrast to the one-program-over-the-mesh pjit
approach — this module compiles each stage as an INDEPENDENT program on
its own chip:

- **Stage split.** ``parallel/pipeline_vit.py::split_stage_params`` cuts
  the checkpoint's ``{embed, blocks, head}`` tree at the SAME block
  boundaries training's stage axis used; stage 0 carries the patch
  embedding, the last stage the head. Each stage's params commit to that
  stage's chip only — no chip ever holds another stage's weights.
- **Per-stage AOT programs.** One compiled forward per batch bucket PER
  STAGE (``CompileLog`` names ``serve_forward_b{b}@pipeline.s{k}``;
  ``@pipeline.g{i}.s{k}`` on multi-chain pools), built through the same
  ``precompile`` path as every other serve program — zero steady-state
  recompiles per bucket x stage, params an ARGUMENT of every program so
  hot-reload stays swap-only.
- **Streaming.** ``dispatch_logits`` stages the batch onto stage 0's
  chip and enqueues the whole chain — stage k's program, then an async
  device-to-device hop of the activation to stage k+1 — and returns
  without waiting (JAX async dispatch: every device runs its own
  execution stream). With the batcher's in-flight window >= stages, the
  chain fills like a GPipe schedule: stage k runs batch N while stage
  k+1 runs batch N-1, and steady-state throughput approaches the
  SLOWEST stage's clock rather than the sum of stages. Window 1
  degenerates to strict fill-and-drain (every batch pays the full chain
  latency serially) — the ``bench.py --mode serve``
  ``pipeline_serving.stage_overlap_speedup`` measurement is exactly
  window >= stages vs window 1.

Hot-reload swaps are COORDINATED across stages: ``swap_params`` splits
and places every stage's slice off-lock, then installs the whole
per-stage list under one lock together with the epoch; dispatch captures
the full list under the same lock once per batch — so one batch can
never run stage 0 on epoch E and stage 1 on epoch E+1 (the no-mixed-
epoch guarantee, now per-chain instead of per-device).

The engine surface (``warmup`` / ``swap_params`` / ``dispatch_logits``
/ ``complete`` / ``preprocess`` / ``buckets`` / ``params_epoch``)
mirrors :class:`~pytorch_distributed_mnist_tpu.serve.engine.
InferenceEngine`, so ``EnginePool`` treats a pipeline CHAIN as one
replica spanning its stage chips: least-loaded dispatch across chains,
quarantine/regroup of the WHOLE chain (a pipeline with a dead stage can
serve nothing — the pool's group machinery is already chain-shaped),
and the reload fan-out all work unchanged. Registered as serve mode
``pipeline`` via ``register_serve_mode``, which is what routes the boot
gate, the divisibility walk, ``/stats``, and the bench through it
without special-casing.
"""

from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
    make_stage_forward_fns,
    split_stage_params,
    split_vit_params,
)
from pytorch_distributed_mnist_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    StagingPool,
    _InFlightBatch,
    _quiet_donation,
    as_raw_images,
    bucket_for,
    preprocess_images,
    stage_batch,
)
from pytorch_distributed_mnist_tpu.train.steps import abstract_spec, precompile

__all__ = ["PipelineEngine", "make_pipeline_template",
           "pipeline_engine_factory"]


class _StageProgram:
    """One pipeline stage: its forward jitted for its own chip, one AOT
    executable per batch bucket. Holds no params — the engine owns the
    per-stage params list so the cross-stage swap stays atomic."""

    __slots__ = ("index", "device", "sharding", "name", "forward", "fused",
                 "_jit", "_compiled")

    def __init__(self, index: int, forward, device, name: str,
                 fused: bool = False) -> None:
        self.index = index
        self.device = device
        self.name = name  # e.g. "pipeline.s0" / "pipeline.g1.s0"
        self.forward = forward
        self.fused = fused
        self.sharding = jax.sharding.SingleDeviceSharding(device)
        jit_kwargs = dict(in_shardings=self.sharding,
                          out_shardings=self.sharding)
        if fused:
            # The fused stage-0 program consumes the raw uint8 staging
            # buffer and DONATES it — the chain's only H2D transfer is
            # the raw bytes, and XLA owns them afterwards.
            jit_kwargs["donate_argnums"] = (1,)
        self._jit = jax.jit(forward, **jit_kwargs)
        self._compiled = {}  # bucket -> Compiled executable

    def program_name(self, bucket: int) -> str:
        tag = ".fused" if self.fused else ""
        return f"serve_forward_b{bucket}{tag}@{self.name}"

    def warmup(self, params_spec, in_specs: dict) -> dict:
        """AOT-compile every bucket's program (idempotent; measured
        under ``program_name`` so the zero-recompile verdict stays
        attributable per bucket x stage). Returns the bucket -> output
        spec map — the next stage's input specs, chained by the engine
        so no stage ever guesses an activation shape."""
        out_specs = {}
        for bucket, spec in in_specs.items():
            if bucket not in self._compiled:
                quiet = (_quiet_donation() if self.fused
                         else contextlib.nullcontext())
                with quiet:
                    self._compiled[bucket] = precompile(
                        self._jit, params_spec, spec,
                        program=self.program_name(bucket))
            out_specs[bucket] = jax.eval_shape(self.forward, params_spec,
                                               spec)
        return out_specs

    def run(self, params, x):
        """Enqueue this stage's program on its chip (async dispatch).
        ``x`` must already be committed to this stage's device."""
        compiled = self._compiled.get(x.shape[0])
        if compiled is not None:
            return compiled(params, x)
        # Lazy fallback (warmup skipped or failed): same program via
        # jit — correctness preserved; the no-recompile guarantee is
        # what warmup buys.
        quiet = _quiet_donation() if self.fused else contextlib.nullcontext()
        with quiet:
            return self._jit(params, x)


class PipelineEngine:
    """S independent per-stage programs behind the one-engine surface.

    ``devices`` gives one chip per stage (stage k pinned to
    ``devices[k]``); ``params`` is the FULL pipelined checkpoint tree
    (``{embed, blocks, head}``) — the engine splits it by stage itself,
    at construction and on every ``swap_params``, so callers (pool
    fan-out, reload watcher, regroup) never learn the stage layout.
    ``model`` is the :class:`VisionTransformer` config the stage
    forwards are built from (per-stage programs cannot be derived from a
    bare ``apply_fn``: the stage boundary cuts THROUGH it).
    """

    def __init__(
        self,
        model,
        params,
        devices: Sequence,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        input_shape: Tuple[int, ...] = (28, 28, 1),
        serve_log=None,
        params_epoch: Optional[int] = None,
        name: str = "pipeline",
        workers: int = 4,
        precision: Optional[str] = None,
        fuse: bool = False,
    ) -> None:
        devices = list(devices)
        if not devices:
            raise ValueError("PipelineEngine needs at least one device")
        buckets = sorted({int(b) for b in buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        self.buckets = tuple(buckets)
        self.input_shape = tuple(input_shape)
        self.serve_log = serve_log
        self.workers = workers
        self.name = name
        self.n_stages = len(devices)
        self.devices = tuple(devices)
        # The precision plane, per stage: each stage's param slice
        # quantizes independently (its own per-leaf scales), the FIRST
        # stage consumes the host-staged input dtype (int8 activations),
        # inter-stage D2D hops ride the precision's hop dtype (bf16
        # stays bf16 — half the hop bytes), and only the LAST stage
        # casts logits back to f32. f32 resolves to the identity spec:
        # every path below is byte-identical to the pre-precision chain.
        from pytorch_distributed_mnist_tpu.serve.programs import get_precision

        self._precision_spec = get_precision(precision)
        self.precision = self._precision_spec.name
        stage_fwds = list(make_stage_forward_fns(model, self.n_stages))
        forwards = [
            self._precision_spec.wrap_stage_forward(
                fwd, first=(k == 0), last=(k == self.n_stages - 1))
            for k, fwd in enumerate(stage_fwds)
        ]
        self._stages = [
            _StageProgram(k, fwd, dev, f"{name}.s{k}")
            for k, (fwd, dev) in enumerate(zip(forwards, devices))
        ]
        # Whole-program fusion cuts in at the chain's ONLY host boundary
        # — stage 0: a second stage-0 program consumes the raw staged
        # uint8 bytes (normalize + int8 activation quant inside XLA,
        # bitwise twins of the host path) and donates its buffer. Later
        # stages see the identical activation contract either way, so
        # they need no fused variant — the split chain past stage 0 IS
        # the fused chain past stage 0.
        self.fuse = bool(fuse)
        self.raw_shape = self.input_shape[:-1]
        if self.fuse:
            fused0 = self._precision_spec.wrap_fused_stage_forward(
                stage_fwds[0], first=True, last=(self.n_stages == 1))
            self._fused_stage0 = _StageProgram(
                0, fused0, devices[0], f"{name}.s0", fused=True)
            self._fused_staging = StagingPool(self.buckets, self.raw_shape,
                                              dtype=np.uint8)
        self._lock = threading.Lock()
        self._stage_params = self._place_stages(params)
        self._params_epoch = params_epoch
        self._staging = StagingPool(self.buckets, self.input_shape,
                                    dtype=self._precision_spec.input_dtype)

    def _place_stages(self, params) -> List:
        """Split the full pipelined tree by stage, quantize each slice
        (per-stage scales — the split runs on the f32 tree the stage
        boundaries are defined over), and commit each slice to its
        stage's chip — stage k's weights live on ``devices[k]`` ONLY
        (the HBM story: no chip holds the whole model)."""
        split = split_stage_params(params, self.n_stages)
        return [jax.device_put(
                    self._precision_spec.quantize(tree, workers=self.workers),
                    stage.sharding)
                for tree, stage in zip(split, self._stages)]

    # -- lifecycle ---------------------------------------------------------

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    @property
    def params_epoch(self) -> Optional[int]:
        with self._lock:
            return self._params_epoch

    def stage_names(self) -> List[str]:
        return [s.name for s in self._stages]

    def warmup(self) -> None:
        """AOT-compile every bucket x stage program (idempotent). Input
        specs CHAIN: stage 0 lowers against the image buckets, each later
        stage against the previous stage's ``eval_shape`` output — the
        activation contract between independently-compiled programs is
        derived, never assumed."""
        with self._lock:
            stage_params = list(self._stage_params)
        specs = {
            b: jax.ShapeDtypeStruct((b,) + self.input_shape,
                                    self._precision_spec.input_dtype)
            for b in self.buckets
        }
        for stage, params in zip(self._stages, stage_params):
            specs = stage.warmup(abstract_spec(params), specs)
        if not self.fuse:
            return
        # The fused stage-0 programs warm alongside: raw uint8 buckets
        # in, the SAME activation spec out as split stage 0 (the fused
        # wrapper prepends in-XLA normalize/quant to the identical
        # post-normalize math), so stages 1..S-1 — already warmed above
        # — cover both planes and the fused chain adds exactly one
        # program per bucket.
        raw_specs = {
            b: jax.ShapeDtypeStruct((b,) + self.raw_shape, np.uint8)
            for b in self.buckets
        }
        self._fused_stage0.warmup(abstract_spec(stage_params[0]), raw_specs)

    def swap_params(self, params, epoch: Optional[int] = None,
                    path: Optional[str] = None) -> bool:
        """Coordinated per-stage hot-reload swap; the signature is the
        reload watcher's ``on_params`` callback, the return the engine
        swap-ordering contract (False == rejected as stale).

        The split + per-stage ``device_put`` run OUTSIDE the lock (the
        slow part); the install writes the WHOLE per-stage list and the
        epoch under one lock, and dispatch snapshots that list under the
        same lock once per batch — so a batch either runs every stage on
        the old epoch or every stage on the new one, never mixed.
        """
        del path  # provenance lives on the watcher (current_path)
        placed = self._place_stages(params)
        with self._lock:
            if (epoch is not None and self._params_epoch is not None
                    and epoch < self._params_epoch):
                return False  # a newer checkpoint already installed
            self._stage_params = placed
            self._params_epoch = epoch
            return True

    # -- inference ---------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        return bucket_for(self.buckets, n)

    def preprocess(self, images) -> np.ndarray:
        if self.fuse:
            raw = as_raw_images(images, self.input_shape)
            if raw is not None:
                return raw  # validated raw bytes: the fused plane's input
        return preprocess_images(images, self.input_shape, self.workers)

    def staging_allocated(self) -> dict:
        return self._staging.allocated()

    def _retire_fused_staging(self,
                              buffers: List[Tuple[int, np.ndarray]]) -> None:
        # Retirement-only twin of the split plane's release path: a
        # donated buffer must never reach release() (the analyzer's
        # donation-discipline rule pins that retire and release never
        # share a routing function).
        self._fused_staging.retire(buffers)

    def fused_staging_retired(self) -> dict:
        """Donated-and-dropped buffer counts per bucket (empty when the
        fused plane is off)."""
        if not self.fuse:
            return {}
        return self._fused_staging.retired()

    def _dispatch_bucket(self, stage_params: List, images: np.ndarray,
                         buffers) -> Tuple:
        """Stage one chunk onto stage 0's chip and enqueue the whole
        chain: stage k's program, then the async device-to-device hop of
        its activation onto stage k+1's chip. Nothing here blocks — the
        returned logits are futures, and with several batches in flight
        every stage chip works a different batch concurrently."""
        n = images.shape[0]
        bucket = self.bucket_for(n)
        staged = stage_batch(images, bucket, self._staging, self.workers,
                             buffers)
        x = jax.device_put(staged, self._stages[0].sharding)
        for stage, params in zip(self._stages, stage_params):
            if stage.index:
                x = jax.device_put(x, stage.sharding)  # D2D hop
            x = stage.run(params, x)
        if self.serve_log is not None:
            self.serve_log.record_batch(n, bucket, replica=self.name)
        return x

    def _dispatch_fused(self, raw: np.ndarray) -> _InFlightBatch:
        """Whole-program chain dispatch: one bytes-copy into the raw
        uint8 staging buffer, the fused stage-0 program (normalize/quant
        inside XLA, buffer DONATED and retired at dispatch), then the
        ordinary stage 1..S-1 chain — identical activations, identical
        programs. The in-flight batch pins no buffers."""
        with self._lock:
            stage_params = list(self._stage_params)  # captured ONCE
            epoch = self._params_epoch
        chunks = []
        for start in range(0, raw.shape[0], self.max_batch):
            chunk = raw[start:start + self.max_batch]
            n = chunk.shape[0]
            bucket = self.bucket_for(n)
            buf = self._fused_staging.acquire(bucket)
            buf[:n] = chunk
            if n < bucket:
                buf[n:] = 0  # pad rows sliced off at complete()
            x = jax.device_put(buf, self._stages[0].sharding)
            self._retire_fused_staging([(bucket, buf)])
            x = self._fused_stage0.run(stage_params[0], x)
            for stage, params in zip(self._stages[1:], stage_params[1:]):
                x = jax.device_put(x, stage.sharding)  # D2D hop
                x = stage.run(params, x)
            if self.serve_log is not None:
                self.serve_log.record_batch(n, bucket, replica=self.name)
            chunks.append((x, n))
        return _InFlightBatch(self, chunks, epoch, [])

    def dispatch_logits(self, images) -> _InFlightBatch:
        """Preprocess + stage + enqueue the per-stage chain WITHOUT
        waiting (the PR 4 two-phase API): the returned batch holds
        device futures that materialize while the caller forms the next
        batch. The per-stage params and the epoch are captured together
        under the lock, once per batch — the cross-stage swap-atomicity
        boundary. Batches larger than the top bucket are chunked.

        A FUSED chain routes validated raw uint8 input through the fused
        stage-0 programs (:meth:`_dispatch_fused`); float input keeps
        the split path below — the ``--no-fuse`` reference plane."""
        if self.fuse:
            raw = as_raw_images(images, self.input_shape)
            if raw is not None:
                return self._dispatch_fused(raw)
        x = self.preprocess(images)
        # Host-side activation transform (int8 plane: quantize once with
        # the fixed scale before chunking — the staged buffers and the
        # stage-0 H2D transfer are int8).
        x = self._precision_spec.stage_host(x, workers=self.workers)
        with self._lock:
            stage_params = list(self._stage_params)  # captured ONCE
            epoch = self._params_epoch
        chunks, buffers = [], []
        try:
            for start in range(0, x.shape[0], self.max_batch):
                chunk = x[start:start + self.max_batch]
                chunks.append(
                    (self._dispatch_bucket(stage_params, chunk, buffers),
                     chunk.shape[0]))
        except BaseException:
            self._staging.release(buffers)
            raise
        return _InFlightBatch(self, chunks, epoch, buffers)

    def complete(self, inflight: _InFlightBatch) \
            -> Tuple[np.ndarray, Optional[int]]:
        """Block on the last stage's device results, release the staging
        buffers, and return ``(logits (N, classes), epoch)`` — exactly
        the single-engine contract, so pool failover and the batcher's
        completion stage treat a chain like any replica."""
        try:
            out = [np.asarray(dev)[:n] for dev, n in inflight.chunks]
        finally:
            self._staging.release(inflight.buffers)
            inflight.buffers = []
        return np.concatenate(out, axis=0), inflight.epoch

    def logits_with_epoch(self, images) -> Tuple[np.ndarray, Optional[int]]:
        return self.dispatch_logits(images).complete()

    def logits(self, images) -> np.ndarray:
        return self.logits_with_epoch(images)[0]

    def predict(self, images) -> np.ndarray:
        return np.argmax(self.logits(images), axis=-1)

    def predict_with_epoch(self, images) -> Tuple[np.ndarray, Optional[int]]:
        logits, epoch = self.logits_with_epoch(images)
        return np.argmax(logits, axis=-1), epoch

    # -- bench instrumentation --------------------------------------------

    def stage_step_ms(self, bucket: int, reps: int = 5) -> dict:
        """Per-stage SYNCHRONOUS step walls (stage name -> best-of-reps
        ms) at one bucket: each stage's program run alone on its chip
        with a blocking fetch, zero activations in flight. This is the
        bench's occupancy probe — under full streaming the pipe's clock
        is the SLOWEST stage's wall, and every other stage idles the
        difference (``utils/profiling.py::stage_occupancy`` turns these
        into the occupancy fractions) — not a serving-path measurement.
        """
        import time

        with self._lock:
            stage_params = list(self._stage_params)
        walls: dict = {}
        x = np.zeros((bucket,) + self.input_shape,
                     self._precision_spec.input_dtype)
        x = jax.device_put(x, self._stages[0].sharding)
        for stage, params in zip(self._stages, stage_params):
            if stage.index:
                x = jax.device_put(x, stage.sharding)
            jax.block_until_ready(stage.run(params, x))  # warm transfer
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                y = jax.block_until_ready(stage.run(params, x))
                best = min(best, time.perf_counter() - t0)
            walls[stage.name.rsplit(".", 1)[-1]] = round(best * 1e3, 3)
            x = y
        return walls


def make_pipeline_template(model, rng):
    """The template state a pipeline-trained checkpoint restores onto:
    params in the PIPELINED ``{embed, blocks, head}`` layout (leaves
    stacked on the depth dim — what training saved), optimizer moments
    mirroring it, host-side and meshless (the serve plane splits by
    stage itself; it never builds the training mesh). The serve boot and
    every hot reload load through this, the same
    ``load_checkpoint``-onto-template validation as every other mode."""
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    params = split_vit_params(
        model.init(rng, jnp.zeros((1, 28, 28, 1), jnp.float32)))
    tx = make_optimizer()
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
    )


def pipeline_engine_factory(*, model, model_name, params, devices, name,
                            buckets, input_shape, serve_log, params_epoch,
                            workers, apply_fn=None, precision=None,
                            fuse=False):
    """The registry's engine hook (``serve/programs.py`` registers mode
    ``pipeline`` with it): one pipeline CHAIN spanning ``devices``
    (stage k on chip k). Needs the model CONFIG, not just an apply_fn —
    the stage boundary cuts through the forward."""
    del apply_fn  # the chain rebuilds the forward per stage
    if model is None:
        raise ValueError(
            "--serve-mode pipeline needs the model object (stage "
            f"programs are built from --model {model_name}'s structure, "
            "not an apply_fn); pass model= to the pool")
    return PipelineEngine(
        model, params, devices, buckets=buckets, input_shape=input_shape,
        serve_log=serve_log, params_epoch=params_epoch, name=name,
        workers=workers, precision=precision, fuse=fuse)
