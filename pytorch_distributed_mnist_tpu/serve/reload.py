"""Checkpoint hot-reload: a serve process tracking a live training run.

The training CLI publishes ``checkpoint_{e}.npz`` / ``.ckpt`` atomically
(tmp + rename, ``train/checkpoint.py``) and prunes with a window keyed to
the latest published epoch — which is exactly what makes polling safe: a
watcher that resolves ``latest_checkpoint()`` sees only fully-published
files, and the one it starts loading survives at least ``--keep-last``
further publishes (the ordering guarantee documented on
``prune_checkpoints``). So a trainer and a serve process can share one
checkpoint directory with no coordination channel beyond the filesystem.

The watcher polls on its own daemon thread, loads through the SAME
``load_checkpoint``-onto-template path resume uses (shape/leaf-count
validation included — a checkpoint from a different model aborts the
reload, not the server; the template itself is per serve mode, so a
pipeline server restores onto the stage-stacked tree), and installs
params via ``engine.swap_params``-style callback: an atomic reference
swap, so the in-flight batch finishes on the old params and the next
batch sees the new ones. The callback owns whatever fan-out the data
plane needs — per replica on a pool, per STAGE inside an MPMD pipeline
chain (``serve/pipeline.py`` splits and installs all stages under one
lock, so a batch never spans two epochs across stages), and to BOTH
planes of a shadow canary (``serve/canary.py`` additionally resets the
promotion cycle, so every publish re-earns its quantized precision).
A multi-model server (``--model-set``) runs one watcher PER model
plane over that model's own checkpoint directory — one model's publish
swaps only its own plane; the others' programs and epochs are
untouched (isolation pinned by tests/test_serve_multimodel.py).
Failures are contained: a corrupt or vanished checkpoint is
recorded (``serve_reload_failed`` in the stats/JSONL stream) and the
server keeps answering on the params it has — serving availability never
depends on the newest file being readable.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from pytorch_distributed_mnist_tpu.train.checkpoint import latest_checkpoint


class CheckpointWatcher:
    """Polls ``directory`` and hands newly published params to ``on_params``.

    ``on_params(params, epoch, path)`` runs on the watcher thread and must
    be cheap + thread-safe (the engine's ``swap_params`` is both; the
    pool's fans the ONE host-side load out to a per-replica swap). A
    falsy non-None return means the swap was refused as stale — every
    engine behind the callback already serves a newer epoch — and is not
    recorded as a reload.
    ``current_path`` marks the checkpoint already loaded at boot so the
    first poll doesn't redundantly reload it. ``poll_once`` is public and
    thread-free so tests drive the state machine deterministically.
    """

    def __init__(
        self,
        directory: str,
        template_state,
        on_params: Callable,
        poll_interval_s: float = 2.0,
        serve_log=None,
        current_path: Optional[str] = None,
        validate_fn: Optional[Callable] = None,
        loader: Optional[Callable] = None,
    ) -> None:
        self.directory = directory
        self.poll_interval_s = float(poll_interval_s)
        self.serve_log = serve_log
        self._template = template_state
        self._on_params = on_params
        # Pre-load gate (``validate_fn(path)`` raising rejects the
        # file): the server passes the serve-mode/parallel-layout check
        # here, so a checkpoint published with a mismatched training
        # layout is SKIPPED — permanently for that file, a ValueError —
        # instead of being installed under the wrong serving mode. A
        # mesh-committed (sharded) pool especially must never receive
        # params whose training layout contradicts its serve mode.
        self._validate = validate_fn
        # The loader seam: ``loader(path, template) -> (params, epoch)``.
        # Default is the whole-file ``load_params_for_serving``; the
        # delta-distribution plane passes ``DeltaFetcher.load`` here so
        # manifests are satisfied by fetching only missing chunks —
        # resolution, the failure taxonomy below, and the install
        # callback are identical either way.
        self._loader = loader
        self._current = current_path
        # Last path that failed to load: retried only once the listing
        # moves past it, so one corrupt file can't hot-loop the log.
        self._failed: Optional[str] = None
        # Serializes polls: the background loop and a concurrent caller
        # (tests drive poll_once directly; /healthz handlers could too)
        # must not both pass the path==current check and double-install
        # the same publish — the params swap is epoch-idempotent, but
        # the second install is a wasted host load + device_put and a
        # phantom +1 in the reload stats.
        self._poll_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def current_path(self) -> Optional[str]:
        return self._current

    def poll_once(self) -> bool:
        """One resolution + (maybe) reload; returns True when new params
        were installed. Serialized against the watcher thread's own
        polls: a concurrent caller either performs the reload itself or
        finds ``_current`` already advanced and returns False."""
        with self._poll_lock:
            return self._poll_once()

    def _poll_once(self) -> bool:
        path = latest_checkpoint(self.directory)
        if not path or path == self._current or path == self._failed:
            return False
        from pytorch_distributed_mnist_tpu.serve.engine import (
            load_params_for_serving,
        )

        loader = self._loader or load_params_for_serving
        try:
            if self._validate is not None:
                self._validate(path)  # ValueError routes to "permanent"
            params, epoch = loader(path, self._template)
        except Exception as exc:  # noqa: BLE001 - serving must survive
            # Serving always survives a failed reload — but retry policy
            # follows the PR-2 damage taxonomy
            # (``is_corrupt_checkpoint_error``): content-level corruption
            # and template mismatches (shape/leaf-count ValueErrors — the
            # CALLER's model is wrong for this directory) are permanent
            # for this file, so the path is remembered and only a NEWER
            # publish is tried. Anything else (EIO off a flaky NFS
            # export, a momentary device_put OOM) is transient: the next
            # poll retries the same path, because after training's final
            # publish no newer path will ever appear to clear a
            # wrongly-pinned blacklist.
            from pytorch_distributed_mnist_tpu.train.checkpoint import (
                is_corrupt_checkpoint_error,
            )

            # _load_sharded's missing-shards ValueError is ABSENCE-level
            # (a stale NFS readdir view of a directory whose atomic
            # publish means it WAS complete) — the same reasoning
            # is_corrupt_checkpoint_error documents for excluding it from
            # quarantine. It must stay retryable here too.
            stale_view = (isinstance(exc, ValueError)
                          and "missing shards" in str(exc))
            permanent = not stale_view and (
                is_corrupt_checkpoint_error(exc)
                or isinstance(exc, ValueError))
            if permanent:
                self._failed = path
            if self.serve_log is not None:
                self.serve_log.record_reload_failure(path, repr(exc))
            policy = ("skipping until a newer checkpoint appears"
                      if permanent else "will retry next poll")
            print(f"serve reload: failed to load {path!r} ({policy}; "
                  f"still serving current params): {exc!r}", flush=True)
            return False
        installed = self._on_params(params, epoch, path)
        self._current = path
        self._failed = None
        if installed is not None and not installed:
            # The engine/pool applied its swap-ordering rule and refused:
            # every replica already serves a NEWER epoch than this file
            # (e.g. a slow load raced a faster one). The file itself was
            # fine — mark it current so it isn't re-loaded, but it never
            # served, so no reload is recorded.
            print(f"serve reload: {path!r} (epoch {epoch}) is staler than "
                  f"the serving params; skipped", flush=True)
            return False
        if self.serve_log is not None:
            self.serve_log.record_reload(path, epoch)
        print(f"serve reload: now serving {path!r} (epoch {epoch})",
              flush=True)
        return True

    def start(self) -> "CheckpointWatcher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serve-reload")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as exc:  # noqa: BLE001 - watcher never dies
                # poll_once already contains load errors; this catches
                # listing-level surprises (directory deleted, EIO). The
                # watcher thread must outlive them all.
                print(f"serve reload: poll failed: {exc!r}", flush=True)
