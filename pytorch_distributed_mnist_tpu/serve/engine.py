"""Bucketed AOT inference engine.

Serving on TPU has one cardinal rule: a request must NEVER trigger an XLA
compile. A compile is 20-40 s of wall-clock on a real chip — against a
p99 budget of milliseconds — and jit keys programs by input shape, so a
naive ``jit(forward)(params, batch)`` recompiles for every distinct batch
size the batcher happens to form. The engine therefore owns a FIXED set
of batch buckets (default 1/8/32/128), AOT-compiles one forward program
per bucket at startup (``.lower().compile()`` through the same
``precompile`` path the trainer uses, so compiles land in ``CompileLog``
and the persistent cache applies), and pads every batch up to the
nearest bucket. Steady-state serving touches only those executables:
zero recompiles, asserted by test via ``CompileLog``.

The forward program is built by ``train/steps.py make_forward_program``
— the SAME builder the ``-e/--evaluate`` eval step traces — so serving
can never disagree with evaluation on forward math or dtype policy, and
preprocessing goes through the same ``normalize_images`` the training
loaders use. Params are an explicit argument of the compiled programs
(not a closure capture), which is what makes checkpoint hot-reload free:
``swap_params`` is an atomic reference swap between batches; an in-flight
batch keeps the params it captured at call entry, the next batch sees the
new ones, and no executable is invalidated.

Two data-plane mechanisms serve the multi-chip pool (``serve/pool.py``):

- **Device pinning.** An engine built with ``device=`` commits params
  and compiles its bucket programs for THAT device
  (``SingleDeviceSharding`` on params, inputs, and outputs), so N
  engines on N local chips execute concurrently instead of contending
  for ``devices()[0]``. ``device=None`` keeps today's default placement
  bit-for-bit.
- **Dispatch/complete split.** ``dispatch_logits`` stages the batch,
  enqueues the device execution, and returns immediately with an
  :class:`_InFlightBatch` (JAX async dispatch: the returned arrays are
  futures); ``complete`` blocks on the result fetch. The pipelined
  batcher overlaps batch N+1's host-side preprocessing and padding with
  batch N's device execution through exactly this seam —
  ``logits_with_epoch`` is just dispatch immediately followed by
  complete, so the synchronous path cannot drift from the pipelined one.

The ``precision=`` plane (``serve/programs.py``): a quantized precision
wraps the forward (on-chip dequant/cast, pure jnp), turns ``_place``
into quantize-then-commit (per-leaf symmetric scales computed once per
install, OUTSIDE the lock, riding the quantized tree as ARGUMENTS of
the compiled programs — hot reload still swaps a reference and
recompiles nothing), and sets the staging dtype (the int8 plane stages
and transfers int8, a quarter of the f32 bytes). ``f32`` — the default
— resolves to the identity spec: every path below is byte-identical to
the pre-precision engine.

Staging-buffer lifecycle: padding a batch up to its bucket reuses a
per-bucket float32 buffer from a free-list instead of allocating per
batch. A buffer is acquired at dispatch, referenced by the in-flight
batch until its completion fetch proves the device has consumed the
input, then returned to the free-list — so the steady-state pool depth
equals the in-flight window and per-batch allocation drops to zero, and
the reuse is safe even on backends that alias host buffers into device
arrays. Exact-fit float32 C-contiguous batches skip the staging copy
entirely (the bitwise-exactness tests pin that path).
"""

from __future__ import annotations

import contextlib
import threading
import warnings
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from pytorch_distributed_mnist_tpu.data import native
from pytorch_distributed_mnist_tpu.data.mnist import normalize_images
from pytorch_distributed_mnist_tpu.train.steps import (
    abstract_spec,
    make_forward_program,
    precompile,
)

DEFAULT_BUCKETS = (1, 8, 32, 128)


@contextlib.contextmanager
def _quiet_donation():
    """Backends that cannot alias a donated host buffer (CPU — the test
    and interpret-mode world) warn once per fused-program compile that
    the donation was unusable. The fused plane is DESIGNED to run there
    (correctness is backend-independent; the aliasing is a TPU win), so
    the warning is expected noise around fused compiles, not a bug."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


class StagingPool:
    """Per-bucket float32 staging free-lists (the lifecycle in the module
    docstring), factored out so every serving engine shares ONE
    implementation: the single/pooled/sharded ``InferenceEngine`` and the
    MPMD per-stage plane (``serve/pipeline.py``) acquire at dispatch, pin
    until the completion fetch, and release for reuse through the same
    code."""

    def __init__(self, buckets: Sequence[int],
                 input_shape: Tuple[int, ...],
                 dtype=np.float32) -> None:
        self.input_shape = tuple(input_shape)
        # float32 everywhere except the int8-activation serving plane,
        # whose staged batches (and H2D transfers) are int8 — a quarter
        # of the bytes. The lifecycle is dtype-oblivious.
        self.dtype = np.dtype(dtype)
        self._lock = threading.Lock()
        self._free: dict = {b: [] for b in buckets}
        self._allocated = {b: 0 for b in buckets}
        self._retired = {b: 0 for b in buckets}

    def acquire(self, bucket: int) -> np.ndarray:
        """Pop a free staging buffer for ``bucket`` (allocate only when
        the free-list is dry — i.e. only until the pool has grown to the
        in-flight window's depth)."""
        with self._lock:
            free = self._free[bucket]
            if free:
                return free.pop()
            self._allocated[bucket] += 1
        return np.zeros((bucket,) + self.input_shape, self.dtype)

    def release(self, buffers: List[Tuple[int, np.ndarray]]) -> None:
        with self._lock:
            for bucket, buf in buffers:
                self._free[bucket].append(buf)

    def retire(self, buffers: List[Tuple[int, np.ndarray]]) -> None:
        """Permanently drop buffers whose bytes were DONATED to a
        compiled program (``donate_argnums``): XLA owns that memory now
        — on backends that alias host buffers into device arrays,
        re-appending a donated buffer to the free-list would hand a
        future batch memory the program may already have overwritten (a
        use-after-free in staging clothing). Retired buffers are counted
        so tests can pin the lifecycle; the free-list never sees them
        again."""
        with self._lock:
            for bucket, _buf in buffers:
                self._retired[bucket] += 1

    def retired(self) -> dict:
        """Total buffers retired (donated, dropped) per bucket."""
        with self._lock:
            return dict(self._retired)

    def allocated(self) -> dict:
        """Total buffers ever allocated per bucket — the steady-state
        invariant (no per-batch allocation) is that this stops growing
        once the in-flight window is warm; tests pin it."""
        with self._lock:
            return dict(self._allocated)


def stage_batch(images: np.ndarray, bucket: int, staging: StagingPool,
                workers: int, buffers: List) -> np.ndarray:
    """Stage one chunk into its bucket: the exact-fit no-copy fast path,
    or a pad-into-staging fill (multithreaded native kernel with the
    bitwise-identical NumPy fallback — padded rows are zeros, as they
    always were). Any buffer acquired is appended to ``buffers`` so the
    in-flight batch pins it until completion proves the device consumed
    the input. Shared by ``InferenceEngine`` and the per-stage MPMD
    plane so the staging bytes can never drift between them."""
    n = images.shape[0]
    if (n == bucket and images.dtype == staging.dtype
            and images.flags["C_CONTIGUOUS"]):
        # Exact fit, already contiguous at the staging dtype: no pad, no
        # copy — the array goes to the device as-is (bitwise-pinned
        # equal to the padded path by the exactness tests).
        return images
    buf = staging.acquire(bucket)
    # Anything not already C-contiguous at the staging dtype goes
    # straight to the fallback's one converting copy — a pre-conversion
    # just to feed the native kernel would cost a second full-batch
    # copy. (The native pad kernel is f32-only; int8 staging pads via
    # NumPy — a quarter of the bytes, so the copy it skips is smaller
    # than the one the f32 kernel earns its keep on.)
    filled = (staging.dtype == np.float32
              and images.dtype == np.float32
              and images.flags["C_CONTIGUOUS"]
              and native.pad_into(buf, images, workers=workers))
    if not filled:
        buf[:n] = images
        if n < bucket:
            buf[n:] = 0.0
    buffers.append((bucket, buf))
    return buf


def preprocess_images(images, input_shape: Tuple[int, ...],
                      workers: int) -> np.ndarray:
    """Raw request pixels -> the float32 normalized layout training
    uses. Accepts uint8 ``(N, 28, 28)`` raw images (normalized with the
    SAME ``normalize_images`` the training loaders apply) or
    already-normalized float32 ``(N,) + input_shape`` arrays; a single
    example may drop its leading axis either way.

    Zero Python-side array math on the dispatch path when the native
    library is built: normalize and the f64->f32 cast run in
    multithreaded C++ over ``workers`` threads, with the NumPy
    expressions as the mandatory bitwise-identical fallback."""
    arr = np.asarray(images)
    if arr.size == 0:
        raise ValueError("at least one image required")
    raw_shape = input_shape[:-1]  # e.g. (28, 28): pre-channel
    if arr.dtype == np.uint8:
        if arr.shape == raw_shape:
            arr = arr[None]
        if arr.ndim == len(raw_shape) + 1 and arr.shape[1:] == raw_shape:
            return normalize_images(arr, workers=workers)
    elif np.issubdtype(arr.dtype, np.floating):
        cast = native.cast_f32(arr, workers=workers) \
            if arr.dtype == np.float64 else None
        arr = cast if cast is not None \
            else arr.astype(np.float32, copy=False)
        if arr.shape == input_shape:
            arr = arr[None]
        if arr.ndim == len(input_shape) + 1 \
                and arr.shape[1:] == input_shape:
            return arr
    raise ValueError(
        f"expected uint8 (N, {', '.join(map(str, raw_shape))}) raw "
        f"images or float32 (N, {', '.join(map(str, input_shape))})"
        f" normalized images; got {arr.dtype} {arr.shape}")


def as_raw_images(images, input_shape: Tuple[int, ...]) \
        -> Optional[np.ndarray]:
    """The fused plane's validation: raw uint8 ``(N, 28, 28)`` request
    pixels (a single example may drop its leading axis) pass through
    UNNORMALIZED — the fused bucket programs take the bytes themselves.
    Returns ``None`` for anything else (already-normalized float input,
    wrong shape), which routes the caller to the split plane — the split
    path stays the one place float inputs are validated and served."""
    arr = np.asarray(images)
    if arr.dtype != np.uint8 or arr.size == 0:
        return None
    raw_shape = input_shape[:-1]  # e.g. (28, 28): pre-channel
    if arr.shape == raw_shape:
        arr = arr[None]
    if arr.ndim == len(raw_shape) + 1 and arr.shape[1:] == raw_shape:
        return arr
    return None


def bucket_for(buckets: Sequence[int], n: int) -> int:
    """Smallest bucket >= n (n must not exceed the largest bucket — the
    dispatch paths chunk oversized batches before calling this)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds largest bucket {buckets[-1]}")


class _InFlightBatch:
    """One dispatched-but-not-fetched batch: the device arrays (futures
    under JAX async dispatch), the epoch of the params that computed
    them, and the staging buffers the batch still pins. ``complete()``
    blocks on the fetch and releases the buffers."""

    __slots__ = ("engine", "chunks", "epoch", "buffers")

    def __init__(self, engine: "InferenceEngine", chunks, epoch,
                 buffers) -> None:
        self.engine = engine
        self.chunks = chunks  # [(device_logits, real_rows), ...]
        self.epoch = epoch
        self.buffers = buffers  # staging buffers pinned until complete

    def complete(self) -> Tuple[np.ndarray, Optional[int]]:
        return self.engine.complete(self)


class InferenceEngine:
    """Params + one AOT-compiled forward executable per batch bucket.

    Threading contract: ``logits``/``predict``/``dispatch_logits`` are
    normally called from ONE thread at a time (the batcher's dispatch
    worker serializes device submission — concurrent forward calls to
    one chip would just contend for it); ``complete`` runs on the
    batcher's completion worker, which only touches the in-flight
    batch's own state plus the staging free-list (its own lock);
    ``swap_params`` may be called from any thread (the reload watcher)
    at any moment. One-thread dispatch is a contention guideline, not a
    correctness invariant: per-batch dispatch state is function-local
    (chunks, buffers) or lock-protected (the params+epoch capture, the
    staging free-list), so the pool's failover path may re-dispatch a
    failed batch from its completion thread concurrently with the
    dispatch worker. The only shared mutable state is the params
    reference + epoch, read together once per batch under the lock.

    ``device``: pin this engine to one local device — params are
    committed there and every bucket program is AOT-compiled for it
    (the replica-pool placement). ``None`` keeps jax's default
    placement, identical to the single-device data plane this engine
    shipped with. ``name`` suffixes the per-bucket ``CompileLog``
    program names (``serve_forward_b8@r2``) so a pool's compile stats
    and the zero-recompile check stay attributable per replica.

    ``placement``: a :class:`~pytorch_distributed_mnist_tpu.serve.
    programs.MeshPlacement` — the SHARDED plane. The engine then spans
    the placement's mesh: params commit with the mode's ``NamedSharding``
    tree (derived from the training rule tables by the program
    registry), each bucket program pjit-lowers with those in/out
    shardings (``serve_forward_b{b}@{mode}`` in ``CompileLog``), inputs
    replicate over the mesh, and outputs come back replicated so
    ``complete`` reads them exactly as it reads single-device results.
    Everything else — buckets, staging free-lists, the dispatch/complete
    split, the swap-ordering rule — is mode-agnostic and unchanged.
    Mutually exclusive with ``device``.
    """

    def __init__(
        self,
        apply_fn,
        params,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        input_shape: Tuple[int, ...] = (28, 28, 1),
        serve_log=None,
        params_epoch: Optional[int] = None,
        device=None,
        name: Optional[str] = None,
        workers: int = 4,
        placement=None,
        precision: Optional[str] = None,
        fuse: bool = False,
    ) -> None:
        buckets = sorted({int(b) for b in buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        self.buckets = tuple(buckets)
        self.input_shape = tuple(input_shape)
        self.serve_log = serve_log
        # Host-side preprocessing thread count (the serve analog of the
        # training loaders' -j/--workers): normalize, f64->f32 cast, and
        # the pad-into-staging copy run in multithreaded C++ when the
        # native library is built, over this many threads.
        self.workers = workers
        self.device = device
        self.placement = placement
        self.name = name
        # The precision plane (serve/programs.py): f32 — the default —
        # resolves to the identity spec and every path below stays
        # byte-identical to the pre-precision engine. A quantized
        # precision wraps the forward (dequant/cast in-program), turns
        # _place into quantize-then-device_put, and sets the staging
        # dtype (int8 activations stage as int8).
        from pytorch_distributed_mnist_tpu.serve.programs import get_precision

        self._precision_spec = get_precision(precision)
        self.precision = self._precision_spec.name
        self._forward = self._precision_spec.wrap_forward(
            make_forward_program(apply_fn))
        if placement is not None:
            if device is not None:
                raise ValueError(
                    "pass device= (single-chip pinning) or placement= "
                    "(sharded mesh), not both")
            # Sharded plane: the placement owns commit + lowering —
            # params with the mode's NamedSharding tree, inputs/outputs
            # replicated over the mesh (serve/programs.py).
            self._sharding = None
            self._jit = placement.jit_forward(self._forward)
        elif device is not None:
            # Pin params, inputs, and outputs to THIS device so the AOT
            # executables land there (default lowering would compile for
            # devices()[0] and reject arguments committed elsewhere).
            self._sharding = jax.sharding.SingleDeviceSharding(device)
            self._jit = jax.jit(self._forward, in_shardings=self._sharding,
                                out_shardings=self._sharding)
        else:
            self._sharding = None
            self._jit = jax.jit(self._forward)  # lazy fallback, same program
        # The FUSED (whole-program) plane: one additional program per
        # bucket taking the raw staged uint8 bytes — normalize (and int8
        # activation quantization) runs inside XLA, bitwise-pinned to
        # the host twins (serve/programs.py), and the staged batch is
        # DONATED (its buffer is retired from the free-list, never
        # re-pinned). The split programs above stay compiled alongside:
        # they serve float (already-normalized) inputs, and they are the
        # bitwise reference --no-fuse pins against.
        self.fuse = bool(fuse)
        self.raw_shape = self.input_shape[:-1]
        self._fused_compiled = {}  # bucket -> Compiled executable
        if self.fuse:
            fused = self._precision_spec.wrap_fused_forward(
                make_forward_program(apply_fn))
            if placement is not None:
                self._fused_jit = placement.jit_fused_forward(fused)
            elif device is not None:
                self._fused_jit = jax.jit(
                    fused, in_shardings=self._sharding,
                    out_shardings=self._sharding, donate_argnums=(1,))
            else:
                self._fused_jit = jax.jit(fused, donate_argnums=(1,))
            # Raw uint8 staging, one buffer per dispatch: acquired, always
            # COPIED into (donating a request's own array would corrupt
            # the pool's failover redispatch, which re-sends the same
            # rows), then retired at dispatch because donation hands the
            # bytes to XLA.
            self._fused_staging = StagingPool(self.buckets, self.raw_shape,
                                              dtype=np.uint8)
        self._lock = threading.Lock()
        # Committed to device once per swap, not once per request.
        self._params = self._place(params)
        self._params_epoch = params_epoch
        # Swap hooks (ISSUE 19): called UNDER _lock right after an
        # install, so cache-generation bumps are atomic with the params
        # swap — no request can hit a pre-swap cache entry after the
        # new params are visible. Hooks must be O(1) arithmetic
        # (ResponseCache.bump_generation is one integer increment).
        self._swap_hooks: List[Callable] = []
        self._compiled = {}  # bucket -> Compiled executable
        # bucket -> free staging buffers (see module docstring lifecycle).
        self._staging = StagingPool(self.buckets, self.input_shape,
                                    dtype=self._precision_spec.input_dtype)

    def _place(self, tree):
        """Commit a PARAMS tree to this engine's device(s): the mesh
        placement's sharding tree on the sharded plane, the pinned
        device's ``SingleDeviceSharding`` on the pooled one, default
        placement when unpinned.

        On a quantized precision the tree is QUANTIZED first (per-leaf
        symmetric scales, computed once per install, host-side) — this
        runs from ``__init__`` and from ``swap_params`` BEFORE the lock
        is taken, so quantization rides the same slow-part-outside-the-
        lock discipline as the ``device_put`` it precedes, and the
        installed reference swap stays what in-flight batches race
        against."""
        tree = self._precision_spec.quantize(tree, workers=self.workers)
        if self.placement is not None:
            return self.placement.place_params(tree)
        if self._sharding is not None:
            return jax.device_put(tree, self._sharding)
        return jax.device_put(tree)

    def _place_input(self, staged):
        """Commit one staged input batch: replicated over the mesh on
        the sharded plane; otherwise exactly the pre-sharding behavior
        (committed to the pinned device, or left to jax's default)."""
        if self.placement is not None:
            return self.placement.place_input(staged)
        if self._sharding is not None:
            return jax.device_put(staged, self._sharding)
        return jax.numpy.asarray(staged)

    # -- lifecycle ---------------------------------------------------------

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    @property
    def params_epoch(self) -> Optional[int]:
        with self._lock:
            return self._params_epoch

    def program_name(self, bucket: int) -> str:
        """The ``CompileLog`` program name of one bucket's executable —
        ``serve_forward_b{bucket}``, suffixed ``@{name}`` on a named
        (pool-replica) engine so compile stats stay per-replica."""
        base = f"serve_forward_b{bucket}"
        return f"{base}@{self.name}" if self.name else base

    def fused_program_name(self, bucket: int) -> str:
        """The fused program's ``CompileLog`` name: the ``.fused`` tag
        rides the bucket segment (``serve_forward_b{bucket}.fused@{name}``)
        so every ``serve_forward_`` prefix filter — /stats' compile
        block, the bench recompile verdicts — covers both planes."""
        base = f"serve_forward_b{bucket}.fused"
        return f"{base}@{self.name}" if self.name else base

    def warmup(self) -> None:
        """AOT-compile every bucket's forward program (idempotent).

        Each program is measured under ``program_name(bucket)`` in the
        process ``CompileLog``, so startup cost is attributable per bucket
        (and per replica) and the zero-steady-state-recompiles acceptance
        check has an anchor to diff against. With a warm persistent
        compile cache these degenerate to executable fetches.
        """
        with self._lock:
            params_spec = abstract_spec(self._params)
        for bucket in self.buckets:
            if bucket in self._compiled:
                continue
            image_spec = jax.ShapeDtypeStruct(
                (bucket,) + self.input_shape,
                self._precision_spec.input_dtype)
            self._compiled[bucket] = precompile(
                self._jit, params_spec, image_spec,
                program=self.program_name(bucket))
        if not self.fuse:
            return
        # The fused plane warms alongside the split one: BOTH are
        # steady-state programs (raw uint8 requests ride fused, float
        # ones ride split), so both must be executables before the
        # socket opens for the zero-recompile guarantee to cover them.
        for bucket in self.buckets:
            if bucket in self._fused_compiled:
                continue
            raw_spec = jax.ShapeDtypeStruct(
                (bucket,) + self.raw_shape, np.uint8)
            with _quiet_donation():
                self._fused_compiled[bucket] = precompile(
                    self._fused_jit, params_spec, raw_spec,
                    program=self.fused_program_name(bucket))

    def add_swap_hook(self, hook: Callable) -> None:
        """Register ``hook(epoch)`` to run UNDER the params lock each
        time a swap installs (hot reload / precision swap): the
        response cache's ``bump_generation`` seam — atomic with the
        install, O(1) arithmetic only."""
        with self._lock:
            self._swap_hooks.append(hook)

    def swap_params(self, params, epoch: Optional[int] = None,
                    path: Optional[str] = None) -> bool:
        """Atomically install new params (checkpoint hot-reload); the
        signature is exactly the reload watcher's ``on_params`` callback.
        Returns True when installed, False when rejected as stale.

        The device_put runs OUTSIDE the lock (it is the slow part); the
        installed reference swap is what in-flight batches race against,
        and they only ever read the reference once, at call entry.
        Because the slow part is unlocked, two concurrent swaps can reach
        the install point in either order — so the install compares
        epochs UNDER the lock and refuses to put an older checkpoint over
        a newer one (the swap-ordering guarantee; a pool fan-out applies
        this rule per replica). Epoch-less swaps (fresh-init params, unit
        tests) always install: the ordering rule is about checkpoint
        provenance, and they have none.
        """
        del path  # provenance lives on the watcher (current_path)
        placed = self._place(params)
        with self._lock:
            if (epoch is not None and self._params_epoch is not None
                    and epoch < self._params_epoch):
                return False  # a newer checkpoint already installed
            self._params = placed
            self._params_epoch = epoch
            for hook in self._swap_hooks:
                hook(epoch)
            return True

    # -- inference ---------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n must not exceed the largest bucket —
        ``logits`` chunks oversized batches before calling this)."""
        return bucket_for(self.buckets, n)

    def preprocess(self, images: np.ndarray) -> np.ndarray:
        """Raw request pixels -> the float32 normalized layout training
        uses (module-level :func:`preprocess_images`, shared with the
        per-stage MPMD plane).

        On a FUSED engine, validated raw uint8 input passes through
        unnormalized — the whole point of the fused plane is that the
        normalize runs inside the compiled program, so the batcher
        coalesces uint8 rows and dispatch routes them to the fused
        bucket programs. Float (already-normalized) input still takes
        the split path either way."""
        if self.fuse:
            raw = as_raw_images(images, self.input_shape)
            if raw is not None:
                return raw
        return preprocess_images(images, self.input_shape, self.workers)

    # -- staging-buffer lifecycle -----------------------------------------

    def _release_staging(self, buffers: List[Tuple[int, np.ndarray]]) -> None:
        self._staging.release(buffers)

    def _retire_fused_staging(self,
                              buffers: List[Tuple[int, np.ndarray]]) -> None:
        # Deliberately a SEPARATE function from _release_staging: a
        # donated buffer must never reach release() (the analyzer's
        # donation-discipline rule fires on any function that can route
        # one buffer to both).
        self._fused_staging.retire(buffers)

    def staging_allocated(self) -> dict:
        """Total buffers ever allocated per bucket (see
        :meth:`StagingPool.allocated`)."""
        return self._staging.allocated()

    def fused_staging_retired(self) -> dict:
        """Donated-and-dropped fused staging buffers per bucket (the
        donation lifecycle's observable; zeros on an unfused engine)."""
        if not self.fuse:
            return {}
        return self._fused_staging.retired()

    # -- dispatch / complete ----------------------------------------------

    def _dispatch_bucket(self, params, images: np.ndarray, buffers):
        """Stage one chunk into its bucket and enqueue the forward on the
        device (JAX async dispatch: returns the un-fetched device logits
        without waiting). Any staging buffer used is appended to
        ``buffers`` so the in-flight batch pins it until completion."""
        n = images.shape[0]
        bucket = self.bucket_for(n)
        staged = stage_batch(images, bucket, self._staging, self.workers,
                             buffers)
        compiled = self._compiled.get(bucket)
        x = self._place_input(staged)
        if compiled is not None:
            out = compiled(params, x)
        else:
            # Lazy fallback (warmup skipped or failed): same program via
            # jit — correctness preserved, the no-recompile guarantee is
            # what warmup buys.
            out = self._jit(params, x)
        if self.serve_log is not None:
            self.serve_log.record_batch(n, bucket, replica=self.name)
        return out

    def _dispatch_fused(self, raw: np.ndarray) -> _InFlightBatch:
        """The whole-program hot path: host work is ONE bytes-copy into
        a raw uint8 staging buffer per chunk; normalize/quantize/forward
        all run inside the fused bucket program. The staging buffer is
        ALWAYS copied into (never the split path's exact-fit zero-copy:
        the program donates its input, and donating a request's own
        array would corrupt the pool's failover redispatch, which
        re-sends the same rows) and RETIRED at dispatch — donation hands
        the bytes to XLA, so the free-list must never see the buffer
        again. The in-flight batch therefore pins nothing."""
        with self._lock:
            params = self._params  # captured ONCE: swap-atomicity boundary
            epoch = self._params_epoch
        chunks = []
        for start in range(0, raw.shape[0], self.max_batch):
            chunk = raw[start:start + self.max_batch]
            n = chunk.shape[0]
            bucket = self.bucket_for(n)
            buf = self._fused_staging.acquire(bucket)
            buf[:n] = chunk
            if n < bucket:
                # Raw-zero padding: the program normalizes pad rows to
                # (0-mean)/std rather than the split plane's 0.0 — the
                # real rows' logits are unaffected (the forward is
                # row-independent) and pad rows are sliced off at
                # complete(); DESIGN.md §7k names the one exception
                # (batch-coupled capacity routing) as a --no-fuse case.
                buf[n:] = 0
            x = self._place_input(buf)
            self._retire_fused_staging([(bucket, buf)])
            compiled = self._fused_compiled.get(bucket)
            if compiled is not None:
                out = compiled(params, x)
            else:
                with _quiet_donation():
                    out = self._fused_jit(params, x)
            if self.serve_log is not None:
                self.serve_log.record_batch(n, bucket, replica=self.name)
            chunks.append((out, n))
        return _InFlightBatch(self, chunks, epoch, [])

    def dispatch_logits(self, images) -> _InFlightBatch:
        """Preprocess + stage + enqueue the forward WITHOUT waiting for
        the result: the returned :class:`_InFlightBatch` holds device
        arrays that materialize under JAX async dispatch while the caller
        goes on to form/stage the next batch. Params and epoch are
        captured together under the lock, once for every chunk — the same
        swap-atomicity boundary the synchronous path has. Batches larger
        than the top bucket are chunked through it.

        A FUSED engine routes validated raw uint8 input to the fused
        bucket programs (:meth:`_dispatch_fused`); float input — already
        normalized upstream — keeps the split path below, which is also
        the ``--no-fuse`` reference plane."""
        if self.fuse:
            raw = as_raw_images(images, self.input_shape)
            if raw is not None:
                return self._dispatch_fused(raw)
        x = self.preprocess(images)
        # Host-side activation transform (int8 plane: quantize the whole
        # normalized batch once with the fixed scale — native v4 kernel,
        # bitwise NumPy fallback — BEFORE chunking/staging, so the
        # staged buffers and the H2D transfers are int8).
        x = self._precision_spec.stage_host(x, workers=self.workers)
        with self._lock:
            params = self._params  # captured ONCE: swap-atomicity boundary
            epoch = self._params_epoch
        chunks, buffers = [], []
        try:
            for start in range(0, x.shape[0], self.max_batch):
                chunk = x[start:start + self.max_batch]
                chunks.append((self._dispatch_bucket(params, chunk, buffers),
                               chunk.shape[0]))
        except BaseException:
            self._release_staging(buffers)
            raise
        return _InFlightBatch(self, chunks, epoch, buffers)

    def complete(self, inflight: _InFlightBatch) \
            -> Tuple[np.ndarray, Optional[int]]:
        """Block on an in-flight batch's device results, release its
        staging buffers, and return ``(logits (N, classes), epoch)``."""
        try:
            out = [np.asarray(dev)[:n] for dev, n in inflight.chunks]
        finally:
            self._release_staging(inflight.buffers)
            inflight.buffers = []
        return np.concatenate(out, axis=0), inflight.epoch

    def logits_with_epoch(self, images) -> Tuple[np.ndarray, Optional[int]]:
        """Forward ``images`` (raw uint8 or normalized float32) through
        the bucketed programs; returns ``(logits (N, classes), epoch)``
        where ``epoch`` is the checkpoint epoch of the params that
        ACTUALLY computed these logits. Dispatch immediately followed by
        complete: the synchronous path and the pipelined one are the same
        code."""
        return self.dispatch_logits(images).complete()

    def logits(self, images) -> np.ndarray:
        return self.logits_with_epoch(images)[0]

    def predict(self, images) -> np.ndarray:
        """Class labels (int64) for ``images``. The argmax stays on the
        host so the device program remains byte-identical to the eval
        forward pass."""
        return np.argmax(self.logits(images), axis=-1)

    def predict_with_epoch(self, images) -> Tuple[np.ndarray, Optional[int]]:
        logits, epoch = self.logits_with_epoch(images)
        return np.argmax(logits, axis=-1), epoch


def load_params_for_serving(path: str, template_state) -> Tuple[object, int]:
    """Restore just ``(params, epoch)`` from a published checkpoint onto
    ``template_state``'s layout — the serve-side restore used at boot and
    by every hot reload. ``epoch`` is the checkpoint's own epoch number
    (the file's ``checkpoint_{e}`` index), not the stored resume epoch."""
    from pytorch_distributed_mnist_tpu.train.checkpoint import load_checkpoint

    state, next_epoch, _best = load_checkpoint(path, template_state)
    return state.params, next_epoch - 1
