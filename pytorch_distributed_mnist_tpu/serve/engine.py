"""Bucketed AOT inference engine.

Serving on TPU has one cardinal rule: a request must NEVER trigger an XLA
compile. A compile is 20-40 s of wall-clock on a real chip — against a
p99 budget of milliseconds — and jit keys programs by input shape, so a
naive ``jit(forward)(params, batch)`` recompiles for every distinct batch
size the batcher happens to form. The engine therefore owns a FIXED set
of batch buckets (default 1/8/32/128), AOT-compiles one forward program
per bucket at startup (``.lower().compile()`` through the same
``precompile`` path the trainer uses, so compiles land in ``CompileLog``
and the persistent cache applies), and pads every batch up to the
nearest bucket. Steady-state serving touches only those executables:
zero recompiles, asserted by test via ``CompileLog``.

The forward program is built by ``train/steps.py make_forward_program``
— the SAME builder the ``-e/--evaluate`` eval step traces — so serving
can never disagree with evaluation on forward math or dtype policy, and
preprocessing goes through the same ``normalize_images`` the training
loaders use. Params are an explicit argument of the compiled programs
(not a closure capture), which is what makes checkpoint hot-reload free:
``swap_params`` is an atomic reference swap between batches; an in-flight
batch keeps the params it captured at call entry, the next batch sees the
new ones, and no executable is invalidated.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from pytorch_distributed_mnist_tpu.data.mnist import normalize_images
from pytorch_distributed_mnist_tpu.train.steps import (
    abstract_spec,
    make_forward_program,
    precompile,
)

DEFAULT_BUCKETS = (1, 8, 32, 128)


class InferenceEngine:
    """Params + one AOT-compiled forward executable per batch bucket.

    Threading contract: ``logits``/``predict`` are called from ONE thread
    at a time (the batcher worker serializes device work — concurrent
    forward calls would just contend for the same chips); ``swap_params``
    may be called from any thread (the reload watcher) at any moment.
    The only shared mutable state is the params reference, read once per
    batch under the lock.
    """

    def __init__(
        self,
        apply_fn,
        params,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        input_shape: Tuple[int, ...] = (28, 28, 1),
        serve_log=None,
        params_epoch: Optional[int] = None,
    ) -> None:
        buckets = sorted({int(b) for b in buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        self.buckets = tuple(buckets)
        self.input_shape = tuple(input_shape)
        self.serve_log = serve_log
        self._forward = make_forward_program(apply_fn)
        self._jit = jax.jit(self._forward)  # lazy fallback, identical program
        self._lock = threading.Lock()
        # Committed to device once per swap, not once per request.
        self._params = jax.device_put(params)
        self._params_epoch = params_epoch
        self._compiled = {}  # bucket -> Compiled executable

    # -- lifecycle ---------------------------------------------------------

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    @property
    def params_epoch(self) -> Optional[int]:
        with self._lock:
            return self._params_epoch

    def warmup(self) -> None:
        """AOT-compile every bucket's forward program (idempotent).

        Each program is measured under ``serve_forward_b{bucket}`` in the
        process ``CompileLog``, so startup cost is attributable per bucket
        and the zero-steady-state-recompiles acceptance check has an
        anchor to diff against. With a warm persistent compile cache these
        degenerate to executable fetches.
        """
        with self._lock:
            params_spec = abstract_spec(self._params)
        for bucket in self.buckets:
            if bucket in self._compiled:
                continue
            image_spec = jax.ShapeDtypeStruct(
                (bucket,) + self.input_shape, np.float32)
            self._compiled[bucket] = precompile(
                self._jit, params_spec, image_spec,
                program=f"serve_forward_b{bucket}")

    def swap_params(self, params, epoch: Optional[int] = None,
                    path: Optional[str] = None) -> None:
        """Atomically install new params (checkpoint hot-reload); the
        signature is exactly the reload watcher's ``on_params`` callback.

        The device_put runs OUTSIDE the lock (it is the slow part); the
        installed reference swap is what in-flight batches race against,
        and they only ever read the reference once, at call entry.
        """
        del path  # provenance lives on the watcher (current_path)
        placed = jax.device_put(params)
        with self._lock:
            self._params = placed
            self._params_epoch = epoch

    # -- inference ---------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n must not exceed the largest bucket —
        ``logits`` chunks oversized batches before calling this)."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} exceeds largest bucket "
                         f"{self.max_batch}")

    def preprocess(self, images: np.ndarray) -> np.ndarray:
        """Raw request pixels -> the float32 normalized layout training
        uses. Accepts uint8 ``(N, 28, 28)`` raw images (normalized with
        the SAME ``normalize_images`` the training loaders apply) or
        already-normalized float32 ``(N,) + input_shape`` arrays; a single
        example may drop its leading axis either way."""
        arr = np.asarray(images)
        if arr.size == 0:
            raise ValueError("at least one image required")
        raw_shape = self.input_shape[:-1]  # e.g. (28, 28): pre-channel
        if arr.dtype == np.uint8:
            if arr.shape == raw_shape:
                arr = arr[None]
            if arr.ndim == len(raw_shape) + 1 and arr.shape[1:] == raw_shape:
                return normalize_images(arr)
        elif np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(np.float32, copy=False)
            if arr.shape == self.input_shape:
                arr = arr[None]
            if arr.ndim == len(self.input_shape) + 1 \
                    and arr.shape[1:] == self.input_shape:
                return arr
        raise ValueError(
            f"expected uint8 (N, {', '.join(map(str, raw_shape))}) raw "
            f"images or float32 (N, {', '.join(map(str, self.input_shape))})"
            f" normalized images; got {arr.dtype} {arr.shape}")

    def _run_bucket(self, params, images: np.ndarray) -> np.ndarray:
        """One padded forward on one bucket executable; returns logits for
        the real rows only."""
        n = images.shape[0]
        bucket = self.bucket_for(n)
        if n < bucket:
            pad = np.zeros((bucket - n,) + images.shape[1:], images.dtype)
            images = np.concatenate([images, pad], axis=0)
        compiled = self._compiled.get(bucket)
        x = jax.numpy.asarray(images)
        if compiled is not None:
            out = compiled(params, x)
        else:
            # Lazy fallback (warmup skipped or failed): same program via
            # jit — correctness preserved, the no-recompile guarantee is
            # what warmup buys.
            out = self._jit(params, x)
        if self.serve_log is not None:
            self.serve_log.record_batch(n, bucket)
        return np.asarray(out)[:n]

    def logits_with_epoch(self, images) -> Tuple[np.ndarray, Optional[int]]:
        """Forward ``images`` (raw uint8 or normalized float32) through
        the bucketed programs; returns ``(logits (N, classes), epoch)``
        where ``epoch`` is the checkpoint epoch of the params that
        ACTUALLY computed these logits — params and epoch are captured
        together under the lock, so a hot reload landing mid-call can
        never mislabel a batch's provenance. Batches larger than the top
        bucket are chunked through it (one capture for all chunks)."""
        x = self.preprocess(images)
        with self._lock:
            params = self._params  # captured ONCE: swap-atomicity boundary
            epoch = self._params_epoch
        out = []
        for start in range(0, x.shape[0], self.max_batch):
            out.append(self._run_bucket(params, x[start:start + self.max_batch]))
        return np.concatenate(out, axis=0), epoch

    def logits(self, images) -> np.ndarray:
        return self.logits_with_epoch(images)[0]

    def predict(self, images) -> np.ndarray:
        """Class labels (int64) for ``images``. The argmax stays on the
        host so the device program remains byte-identical to the eval
        forward pass."""
        return np.argmax(self.logits(images), axis=-1)

    def predict_with_epoch(self, images) -> Tuple[np.ndarray, Optional[int]]:
        logits, epoch = self.logits_with_epoch(images)
        return np.argmax(logits, axis=-1), epoch


def load_params_for_serving(path: str, template_state) -> Tuple[object, int]:
    """Restore just ``(params, epoch)`` from a published checkpoint onto
    ``template_state``'s layout — the serve-side restore used at boot and
    by every hot reload. ``epoch`` is the checkpoint's own epoch number
    (the file's ``checkpoint_{e}`` index), not the stored resume epoch."""
    from pytorch_distributed_mnist_tpu.train.checkpoint import load_checkpoint

    state, next_epoch, _best = load_checkpoint(path, template_state)
    return state.params, next_epoch - 1
