"""Request-path economics: response cache, collapse pricing, cost model.

Heavy real traffic is redundant — the same image batch arrives again and
again — and the serve stack (PRs 3–16) priced every request identically
and recomputed every duplicate. This module is the shared economics
layer in front of the batcher (server) and in front of the fleet
(router):

- :class:`ResponseCache` — exact-match response memoization. The key is
  a hash over the RAW request bytes plus the serving identity (model,
  serve mode, precision): two byte-identical requests against the same
  plane are the same answer, and nothing less than byte identity is
  assumed (no canonicalization — a reordered JSON object is a different
  key and merely misses). Every entry is stamped with the serving epoch
  it was computed under and the cache GENERATION current at insert
  time. Invalidation is one integer increment (``bump_generation``,
  registered as a swap hook under the engine/pool/canary params lock):
  a hot reload, precision swap, or canary promote makes every prior
  entry unreachable atomically — no per-entry scan, stale entries are
  lazily dropped on next touch or evicted by LRU pressure.
- :class:`CostModel` — per-bucket measured step cost. Seeded from the
  bucket geometry (the bench's per-bucket timings establish the same
  shape — see DESIGN.md §7n for provenance), refreshed at serve time by
  a cheap online EWMA over the batcher's measured batch walls. Prices
  are normalized so the smallest bucket costs ~1.0; a cache hit prices
  at :data:`HIT_COST` (~0) so duplicate-heavy clients stop starving
  compute-heavy ones under cost-accounted quotas.

Pure stdlib ON PURPOSE (no jax, no numpy): the fleet router — which is
jax-import-free so it can run on a routing box with no accelerator
stack — imports this module for its own keyed cache, sharing one
implementation and one invalidation rule with the backends.

Lock discipline: the cache lock guards dict/counter arithmetic only.
Payloads are built (serialized, device-fetched) OUTSIDE the lock and
handed in; ``put`` re-checks the generation captured at probe time
under the lock and drops the insert if a swap landed in between
(snapshot-then-insert — the engine ``swap_params`` idiom one layer up).
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, Optional, Sequence, Tuple

#: Admission price of a response served from cache: not exactly zero
#: (a flood of even-free requests still spends sockets and handler
#: threads) but ~0 relative to the smallest compute bucket's 1.0.
HIT_COST = 0.01


def request_key(raw: bytes, model: Optional[str], serve_mode: str,
                precision: str) -> str:
    """Exact-match cache key: hash(raw request bytes + model +
    serve-mode + precision). Length-framed so field boundaries cannot
    alias (``"ab"+"c"`` vs ``"a"+"bc"``), and the serving identity is
    part of the key — the same bytes against a different plane or a
    differently-quantized program are a different answer."""
    h = hashlib.sha256()
    for part in (raw, (model or "").encode(), serve_mode.encode(),
                 precision.encode()):
        h.update(len(part).to_bytes(8, "big"))
        h.update(part)
    return h.hexdigest()


class _Entry:
    __slots__ = ("value", "nbytes", "epoch", "generation")

    def __init__(self, value, nbytes: int, epoch: Optional[int],
                 generation: int) -> None:
        self.value = value
        self.nbytes = int(nbytes)
        self.epoch = epoch
        self.generation = generation


class ResponseCache:
    """Bounded LRU response cache with epoch/generation stamping.

    ``max_bytes`` bounds the PAYLOAD bytes held (the caller states each
    value's size — serialized reply bytes; the dict overhead is small
    against logit payloads). One lock, arithmetic only under it.

    ``get(key)`` returns ``(value, epoch, generation)`` — value ``None``
    on miss; the returned generation is the one the caller must hand
    back to ``put`` after computing, so an intervening swap turns the
    insert into a counted drop instead of a stale entry.
    """

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, _Entry]" = \
            collections.OrderedDict()
        self._bytes = 0
        self._generation = 0
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        self.evictions = 0
        self.inserts = 0
        self.stale_drops = 0

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def bump_generation(self, *_args, **_kwargs) -> int:
        """Invalidate EVERYTHING in O(1): one integer increment. Swap
        hooks call this under the engine/pool/canary params lock (with
        whatever epoch arguments the hook carries — ignored), so the
        moment new params are installed no pre-swap entry can hit; the
        entries themselves are dropped lazily on next touch."""
        with self._lock:
            self._generation += 1
            return self._generation

    def get(self, key: str):
        """``(value, epoch, generation)``; value None = miss. A
        generation-mismatched entry is a miss AND is dropped here (the
        lazy half of the O(1) invalidation)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.generation != self._generation:
                del self._entries[key]
                self._bytes -= entry.nbytes
                entry = None
            if entry is None:
                self.misses += 1
                return None, None, self._generation
            self._entries.move_to_end(key)
            self.hits += 1
            self.hit_bytes += entry.nbytes
            return entry.value, entry.epoch, self._generation

    def put(self, key: str, value, nbytes: int, epoch: Optional[int],
            generation: int) -> bool:
        """Insert a computed response, guarded by the generation the
        caller captured at probe time: if a swap bumped it since, the
        value was computed under dead params — drop it (counted), never
        install it."""
        if not self.enabled:
            return False
        nbytes = int(nbytes)
        with self._lock:
            if generation != self._generation:
                self.stale_drops += 1
                return False
            if nbytes > self.max_bytes:
                return False  # one giant reply must not flush the cache
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _Entry(value, nbytes, epoch, generation)
            self._bytes += nbytes
            self.inserts += 1
            while self._bytes > self.max_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self.evictions += 1
        return True

    def snapshot(self) -> Dict:
        """The ``/stats`` ``cache`` block (schema-ADDITIVE)."""
        with self._lock:
            total = self.hits + self.misses
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
                "hit_bytes": self.hit_bytes,
                "evictions": self.evictions,
                "stale_drops": self.stale_drops,
                "generation": self._generation,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacity_bytes": self.max_bytes,
            }


class CostModel:
    """Per-bucket step cost in normalized cost units.

    Seeded from the bucket geometry (cost proportional to bucket rows —
    the shape the bench's per-bucket timings measure on every box this
    repo has run on), then refreshed by an online EWMA over the
    batcher's measured batch walls: ``observe(rows, wall_s)`` per
    completed batch, ``price(rows)`` per admission decision. Prices are
    normalized to the smallest bucket (~1.0), so quota rates configured
    in requests/sec keep their meaning for smallest-bucket traffic and
    an 8x-bucket request costs what it measures — not what it claims.
    """

    def __init__(self, buckets: Sequence[int], alpha: float = 0.2,
                 seed_costs: Optional[Dict[int, float]] = None) -> None:
        if not buckets:
            raise ValueError("CostModel needs at least one bucket")
        self.buckets: Tuple[int, ...] = tuple(sorted(set(
            int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise ValueError(f"buckets must be >= 1, got {self.buckets}")
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        base = float(self.buckets[0])
        # Seed walls in arbitrary units; only RATIOS ever leave price(),
        # and the first real observation rescales every still-seeded
        # bucket onto the measured unit (seconds), so a price never
        # compares a seed unit against a measured one.
        self._wall: Dict[int, float] = {
            b: float(b) / base for b in self.buckets}
        for b, w in (seed_costs or {}).items():
            if int(b) in self._wall and float(w) > 0:
                self._wall[int(b)] = float(w)
        self._observed: Dict[int, int] = {b: 0 for b in self.buckets}
        self._calibrated = False

    def bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    def observe(self, rows: int, wall_s: float) -> None:
        """EWMA-refresh one bucket's measured wall (the batcher's
        completion stage calls this per successful batch)."""
        if wall_s <= 0:
            return
        b = self.bucket_for(int(rows))
        with self._lock:
            prev = self._wall[b]
            if self._observed[b] == 0:
                # First real measurement replaces the geometric seed —
                # an EWMA from a made-up baseline converges too slowly.
                # The very first observation also rescales every
                # still-seeded bucket onto the measured unit, keeping
                # the seed GEOMETRY (cost ~ rows) while making every
                # cross-bucket ratio unit-consistent from then on.
                if not self._calibrated:
                    scale = float(wall_s) / prev
                    for c in self.buckets:
                        if c != b and self._observed[c] == 0:
                            self._wall[c] *= scale
                    self._calibrated = True
                self._wall[b] = float(wall_s)
            else:
                self._wall[b] = ((1.0 - self.alpha) * prev
                                 + self.alpha * float(wall_s))
            self._observed[b] += 1

    def price(self, rows: int) -> float:
        """Cost units for a ``rows``-row request: its bucket's measured
        wall over the smallest bucket's. Floored at HIT_COST (a
        degenerate measurement must never price compute below a cache
        hit)."""
        b = self.bucket_for(int(rows))
        with self._lock:
            base = self._wall[self.buckets[0]]
            wall = self._wall[b]
        if base <= 0:
            return 1.0
        return max(HIT_COST, round(wall / base, 4))

    def snapshot(self) -> Dict:
        with self._lock:
            base = self._wall[self.buckets[0]] or 1.0
            return {
                "buckets": list(self.buckets),
                "alpha": self.alpha,
                "cost_units": {str(b): round(self._wall[b] / base, 4)
                               for b in self.buckets},
                "observed_batches": {str(b): self._observed[b]
                                     for b in self.buckets},
            }
