"""Dynamic micro-batching with a deadline and admission control.

The latency/throughput trade at the heart of serving: a single request
underfills even the smallest useful device batch, but waiting forever to
fill the largest one destroys tail latency. The batcher holds a
thread-safe queue; one worker thread coalesces whatever arrives within
``max_wait`` of the OLDEST waiting request — or until ``max_batch`` rows
are ready, whichever is first — and runs the engine once per formed
batch. Device work is serialized on the worker by construction (the
chips are one shared resource; concurrent forwards would only contend).

Overload is explicit, not emergent: the queue is bounded (``max_queue``
requests), and a submit against a full queue raises :class:`Overloaded`
immediately — the caller (HTTP layer) turns that into a 503. Without the
bound, a stalled or slow engine converts overload into unbounded queue
growth and minutes-long latency for every request already in line, which
is strictly worse than telling new arrivals to back off.

Per-request accounting: enqueue->batch-formed (queue wait) and
enqueue->result (total latency) land in the :class:`ServeLog` the server
exposes at ``/stats``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

import numpy as np


class Overloaded(RuntimeError):
    """Admission control: the request queue is at capacity; back off."""


class _Pending:
    """One submitted request riding the queue."""

    __slots__ = ("images", "rows", "event", "result", "error", "t_submit",
                 "t_batched", "abandoned")

    def __init__(self, images: np.ndarray, rows: int) -> None:
        self.images = images
        self.rows = rows
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_batched = self.t_submit
        # Set by a caller whose result() wait timed out: still-queued
        # abandoned requests are dropped before execution (no device work
        # for an answer nobody will read, no phantom /stats samples, and
        # the queue slot frees for admission control).
        self.abandoned = False

    def finish(self, result: Optional[np.ndarray],
               error: Optional[BaseException], serve_log) -> None:
        self.result = result
        self.error = error
        if serve_log is not None and not self.abandoned:
            now = time.perf_counter()
            serve_log.record_request(
                latency_s=now - self.t_submit,
                queue_wait_s=self.t_batched - self.t_submit,
                images=self.rows,
            )
        self.event.set()


class MicroBatcher:
    """Coalesces concurrent requests into engine-sized batches.

    ``infer_fn(images) -> outputs`` maps a float/uint8 row-stack to a
    per-row output stack (first dims equal); the engine's ``predict`` is
    the production value, but any callable works — the unit tests drive
    the state machine with stubs, no device or socket required.
    """

    def __init__(
        self,
        infer_fn: Callable[[np.ndarray], np.ndarray],
        max_batch: int,
        max_wait_s: float = 0.005,
        max_queue: int = 256,
        serve_log=None,
    ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.infer_fn = infer_fn
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self.serve_log = serve_log
        self._cv = threading.Condition()
        self._queue: List[_Pending] = []
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        if serve_log is not None:
            serve_log.set_queue_depth_probe(self.queue_depth)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serve-batcher")
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop the worker; queued requests are drained first so a clean
        shutdown never strands a caller blocked on ``result``."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "MicroBatcher":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    # -- producer side -----------------------------------------------------

    def submit(self, images) -> _Pending:
        """Enqueue one request. ``images`` must be a row-stack whose first
        dim is the example count (the server preprocesses through
        ``engine.preprocess`` first, so row counting and concatenation
        are unambiguous); any row count is accepted — oversized batches
        ride alone and the engine chunks them. Raises :class:`Overloaded`
        when the queue is at capacity — admission control happens HERE,
        before any work is done for the request."""
        arr = np.asarray(images)
        if arr.ndim < 2 or arr.shape[0] == 0:
            raise ValueError(
                f"submit expects a non-empty (rows, ...) stack of "
                f"examples; got shape {arr.shape}")
        pending = _Pending(arr, int(arr.shape[0]))
        with self._cv:
            if self._stopped:
                raise RuntimeError("batcher is shut down")
            if len(self._queue) >= self.max_queue:
                if self.serve_log is not None:
                    self.serve_log.record_rejection()
                raise Overloaded(
                    f"request queue full ({self.max_queue} pending)")
            self._queue.append(pending)
            self._cv.notify_all()
        return pending

    @staticmethod
    def result(pending: _Pending, timeout: Optional[float] = None):
        if not pending.event.wait(timeout):
            # Nobody will read the answer: if the request is still
            # queued, the worker drops it instead of executing it (an
            # already in-flight batch can't be recalled from the device).
            pending.abandoned = True
            raise TimeoutError("request did not complete in time")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def predict(self, images, timeout: Optional[float] = 30.0):
        """Synchronous submit + wait — the HTTP handler's one call."""
        return self.result(self.submit(images), timeout)

    # -- worker side -------------------------------------------------------

    def _take_batch(self) -> List[_Pending]:
        """Block until work exists, then coalesce under the deadline.

        The deadline is anchored to the OLDEST request's submit time, so
        a trickle of arrivals cannot postpone the flush indefinitely; a
        full ``max_batch`` flushes immediately. Returns ``[]`` only when
        stopped with an empty queue."""
        def takeable_rows() -> int:
            """Rows the take loop below would ACTUALLY co-batch right
            now — same walk, same no-split rule, skipping abandoned
            entries. The flush trigger must use this, not a raw sum: a
            1-row request followed by an oversized one would otherwise
            'fill' the batch on paper and flush the 1-row alone with
            coalescing time still on the clock."""
            rows = 0
            for p in self._queue:
                if p.abandoned:
                    continue
                if rows and rows + p.rows > self.max_batch:
                    break
                rows += p.rows
                if rows >= self.max_batch:
                    break
            return rows

        with self._cv:
            while True:  # until a non-empty take, or stopped + drained
                while not self._queue and not self._stopped:
                    self._cv.wait()
                if not self._queue:
                    return []
                deadline = self._queue[0].t_submit + self.max_wait_s
                while not self._stopped:
                    remaining = deadline - time.perf_counter()
                    if takeable_rows() >= self.max_batch or remaining <= 0:
                        break
                    self._cv.wait(remaining)
                taken, rows = [], 0
                while self._queue and rows < self.max_batch:
                    head = self._queue[0]
                    if head.abandoned:
                        # Its caller timed out and left: drop without
                        # executing (finish() skips stats for abandoned).
                        self._queue.pop(0)
                        head.finish(None, TimeoutError("abandoned"),
                                    self.serve_log)
                        continue
                    # Never split one request across batches: results map
                    # back by whole slices. A request bigger than
                    # max_batch rides alone (the engine chunks it through
                    # the top bucket).
                    if taken and rows + head.rows > self.max_batch:
                        break
                    self._queue.pop(0)
                    taken.append(head)
                    rows += head.rows
                if not taken:
                    continue  # everything seen was abandoned: wait again
                t = time.perf_counter()
                for p in taken:
                    p.t_batched = t
                return taken

    def _run_batch(self, taken: List[_Pending]) -> None:
        images = (taken[0].images if len(taken) == 1
                  else np.concatenate([p.images for p in taken], axis=0))
        try:
            out = np.asarray(self.infer_fn(images))
        except BaseException as exc:  # noqa: BLE001 - delivered per request
            for p in taken:
                p.finish(None, exc, self.serve_log)
            return
        if out.shape[0] != sum(p.rows for p in taken):
            exc = RuntimeError(
                f"infer_fn returned {out.shape[0]} rows for "
                f"{sum(p.rows for p in taken)} inputs")
            for p in taken:
                p.finish(None, exc, self.serve_log)
            return
        off = 0
        for p in taken:
            p.finish(out[off:off + p.rows], None, self.serve_log)
            off += p.rows

    def _loop(self) -> None:
        while True:
            taken = self._take_batch()
            if not taken:
                return  # stopped and drained
            self._run_batch(taken)
