"""Dynamic micro-batching with a deadline and admission control.

The latency/throughput trade at the heart of serving: a single request
underfills even the smallest useful device batch, but waiting forever to
fill the largest one destroys tail latency. The batcher holds a
thread-safe queue; one worker thread coalesces whatever arrives within
``max_wait`` of the OLDEST waiting request — or until ``max_batch`` rows
are ready, whichever is first — and runs the engine once per formed
batch. Device work is serialized on the worker by construction (the
chips are one shared resource; concurrent forwards would only contend).

Overload is explicit, not emergent: the queue is bounded (``max_queue``
requests), and a submit against a full queue raises :class:`Overloaded`
immediately — the caller (HTTP layer) turns that into a 503. Without the
bound, a stalled or slow engine converts overload into unbounded queue
growth and minutes-long latency for every request already in line, which
is strictly worse than telling new arrivals to back off.

With a :class:`~pytorch_distributed_mnist_tpu.serve.control.ShedPolicy`
attached, overload additionally becomes a POLICY instead of a coin
flip: each submit carries a priority class, the queue is priority-
ORDERED (``interactive`` ahead of ``batch`` ahead of ``best_effort``,
FIFO within a class), and each class has an admission watermark — a
fraction of ``max_queue`` past which THAT class is shed while more
urgent classes are still admitted. The raised :class:`Overloaded`
carries ``retry_after_s`` derived from the completion stage's measured
drain rate, so the 503 tells the client when capacity plausibly
exists. Without a policy (the default), every request is the default
class at watermark 1.0 and behavior is byte-identical to the
pre-policy batcher.

The worker is split into two stages. The **form/dispatch** stage
coalesces a batch and hands it to ``dispatch_fn`` — which, against the
engine/pool two-phase API, stages + pads the batch and ENQUEUES the
device execution without waiting (JAX async dispatch) — then
immediately forms the next batch. The **completion** stage pops
dispatched batches FIFO, blocks on ``complete_fn`` (the result fetch),
and delivers results, errors, and accounting exactly as the single
worker did. ``max_inflight`` bounds how many batches may sit between
dispatch and completion: batch N+1's host-side preprocessing/padding
overlaps batch N's device execution instead of serializing behind its
result fetch, and across a replica pool up to ``max_inflight`` batches
execute on different chips concurrently. ``max_inflight=1`` restores
strict dispatch→complete alternation — byte-for-byte the pre-pipelining
behavior — and the classic single-callable ``infer_fn`` form runs the
whole inference inside the dispatch stage, so stub-driven tests and the
single-device server are unchanged.

Per-request accounting: enqueue->batch-formed (queue wait) and
enqueue->result (total latency) land in the :class:`ServeLog` the server
exposes at ``/stats``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from pytorch_distributed_mnist_tpu.serve.control import (
    DrainRate,
    PRIORITY_CLASSES,
    priority_rank,
)


class Overloaded(RuntimeError):
    """Admission control: the request queue is at capacity (or past this
    priority class's shed watermark); back off. ``retry_after_s`` (when
    known) is the drain-rate-derived hint the HTTP 503 forwards as
    ``Retry-After``."""

    def __init__(self, message: str,
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


class _Pending:
    """One submitted request riding the queue."""

    __slots__ = ("images", "rows", "event", "result", "error", "t_submit",
                 "t_batched", "abandoned", "klass", "rank", "seq",
                 "ckey", "cost", "waiters", "guard")

    def __init__(self, images: np.ndarray, rows: int,
                 klass: Optional[str] = None, rank: int = 0,
                 seq: int = 0, ckey: Optional[str] = None,
                 cost: float = 1.0, guard=None) -> None:
        self.images = images
        self.rows = rows
        self.klass = klass
        self.rank = rank
        self.seq = seq
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_batched = self.t_submit
        # In-flight collapsing (ISSUE 19): ``ckey`` is the request's
        # collapse key while it owns a slot in the batcher's inflight-key
        # map; ``waiters`` counts the callers (leader + collapsed
        # followers) whose result() is riding this pending, guarded by
        # ``guard`` (the batcher's _cv — shared, never a new lock).
        self.ckey = ckey
        self.cost = float(cost)
        self.waiters = 1
        self.guard = guard
        # Set when EVERY caller's result() wait timed out: still-queued
        # abandoned requests are dropped before execution (no device work
        # for an answer nobody will read, no phantom /stats samples, and
        # the queue slot frees for admission control).
        self.abandoned = False

    def finish(self, result: Optional[np.ndarray],
               error: Optional[BaseException], serve_log) -> None:
        self.result = result
        self.error = error
        if serve_log is not None and not self.abandoned:
            now = time.perf_counter()
            if self.guard is not None:
                with self.guard:
                    waiters = self.waiters
            else:
                waiters = self.waiters
            # One record per caller still waiting: a collapsed follower
            # is a served request exactly like a cache hit, so it must
            # count in the per-model/class totals even though only one
            # dispatch ran. waiters excludes callers that timed out
            # (result() decrements on timeout), which is the honest
            # count of replies actually delivered.
            for _ in range(max(1, waiters)):
                serve_log.record_request(
                    latency_s=now - self.t_submit,
                    queue_wait_s=self.t_batched - self.t_submit,
                    images=self.rows,
                    klass=self.klass,
                )
        self.event.set()


class MicroBatcher:
    """Coalesces concurrent requests into engine-sized batches.

    Two inference forms:

    - ``infer_fn(images) -> outputs`` maps a float/uint8 row-stack to a
      per-row output stack (first dims equal); the engine's ``predict``
      is the production value, but any callable works — the unit tests
      drive the state machine with stubs, no device or socket required.
      The whole call runs inside the dispatch stage (no pipelining gain,
      full behavioral compatibility).
    - ``dispatch_fn(images) -> handle`` + ``complete_fn(handle) ->
      outputs`` (passed together, ``infer_fn=None``): the two-phase form
      the engine/pool expose. Dispatch enqueues device work and returns
      immediately; completion blocks on the fetch — with
      ``max_inflight > 1`` the stages overlap.

    ``max_inflight`` bounds batches dispatched but not completed
    (default 1: strict alternation, the pre-pipelining behavior).
    """

    def __init__(
        self,
        infer_fn: Optional[Callable[[np.ndarray], np.ndarray]],
        max_batch: int,
        max_wait_s: float = 0.005,
        max_queue: int = 256,
        serve_log=None,
        dispatch_fn: Optional[Callable] = None,
        complete_fn: Optional[Callable] = None,
        max_inflight: int = 1,
        shed_policy=None,
        cost_model=None,
        priced: bool = False,
    ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {max_inflight}")
        if (dispatch_fn is None) != (complete_fn is None):
            raise ValueError(
                "dispatch_fn and complete_fn come as a pair")
        if (infer_fn is None) == (dispatch_fn is None):
            raise ValueError(
                "exactly one of infer_fn or dispatch_fn/complete_fn "
                "is required")
        if infer_fn is not None:
            # Classic form: the full inference runs at dispatch; the
            # "handle" is already the output stack.
            dispatch_fn, complete_fn = infer_fn, lambda out: out
        self.infer_fn = infer_fn
        self.dispatch_fn = dispatch_fn
        self.complete_fn = complete_fn
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self.max_inflight = int(max_inflight)
        self.serve_log = serve_log
        # Priority shedding (serve/control.py): None keeps the classic
        # single-class admission (full queue = 503) and FIFO order.
        self.shed_policy = shed_policy
        # Request-path economics (serve/economics.py): with a CostModel
        # attached the completion stage feeds it measured batch walls
        # (the serve-time EWMA refresh); ``priced`` additionally switches
        # admission depth, drain rate, and Retry-After to COST units —
        # off (the default) is byte-identical to the count-based batcher.
        self.cost_model = cost_model
        self.priced = bool(priced)
        # Collapse map: collapse_key -> the live _Pending duplicates
        # join, guarded by _cv; entries leave before their event fires.
        self._inflight_keys = {}
        self.collapsed = 0
        self._queue_cost = 0.0
        # Completion-side requests/sec over a sliding window — the
        # denominator every Retry-After hint is derived from.
        self._drain = DrainRate()
        self._seq = 0
        self._cv = threading.Condition()
        self._queue: List[_Pending] = []
        self._stopped = False
        # dispatch -> completion conduit: (taken, handle, dispatch_error)
        # triples, FIFO; bounded by the _window semaphore, not the queue.
        self._inflight: "queue.Queue" = queue.Queue()
        self._window = threading.Semaphore(self.max_inflight)
        self._thread: Optional[threading.Thread] = None
        self._completion: Optional[threading.Thread] = None
        if serve_log is not None:
            serve_log.set_queue_depth_probe(self.queue_depth)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name="serve-batcher")
            self._completion = threading.Thread(
                target=self._completion_loop, daemon=True,
                name="serve-completion")
            self._thread.start()
            self._completion.start()
        return self

    def close(self) -> None:
        """Stop the workers; queued requests are drained first (formed,
        dispatched, completed) so a clean shutdown never strands a caller
        blocked on ``result``."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._completion is not None:
            self._completion.join()
            self._completion = None

    def __enter__(self) -> "MicroBatcher":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def drain_rps(self) -> float:
        """Completed requests/sec over the drain window — what
        ``Retry-After`` hints are derived from."""
        return self._drain.rate()

    # -- producer side -----------------------------------------------------

    def submit(self, images, klass: Optional[str] = None,
               collapse_key: Optional[str] = None,
               cost: float = 1.0) -> _Pending:
        """Enqueue one request. ``images`` must be a row-stack whose first
        dim is the example count (the server preprocesses through
        ``engine.preprocess`` first, so row counting and concatenation
        are unambiguous); any row count is accepted — oversized batches
        ride alone and the engine chunks them. Raises :class:`Overloaded`
        when the queue is at capacity — admission control happens HERE,
        before any work is done for the request.

        ``klass`` is the request's priority class. ``None`` (a client
        that never spoke priorities) is TREATED as the most urgent
        class for ordering and admission — identical behavior to the
        pre-policy batcher — but stays ``None`` in the accounting, so
        a server whose clients never send priorities keeps the
        classless ``/stats`` schema (no ``classes`` block). With a
        shed policy attached, admission additionally applies the
        class's queue watermark and the queue is kept priority-ordered
        (FIFO within a class) — an interactive arrival overtakes every
        queued best_effort request.

        ``collapse_key`` opts into in-flight collapsing: a submit whose
        key matches a still-QUEUED (not yet dispatched, not abandoned)
        pending JOINS it — no new queue slot, no re-dispatch; the
        caller's ``result()`` rides the leader's event and sees the
        same result or error (error fan-out reaches every joiner
        exactly once, one raise per ``result()`` call). A follower
        still passes ADMISSION first, at its own price: count-mode
        depth counts every outstanding waiter (a collapsed client is
        still an outstanding client, so a byte-identical flood sheds
        at exactly the classic watermark), and quota accounting for
        the follower's CLIENT is the server's job before this call.
        Once a batch dispatches its key retires — a duplicate arriving
        mid-execution queues normally and is answered by the response
        cache one layer up after the leader completes. ``cost`` is the
        request's admission price in cost units (``priced`` batchers
        account queue depth, drain rate and Retry-After in these
        units; the default 1.0 per request is byte-identical to count
        accounting)."""
        arr = np.asarray(images)
        if arr.ndim < 2 or arr.shape[0] == 0:
            raise ValueError(
                f"submit expects a non-empty (rows, ...) stack of "
                f"examples; got shape {arr.shape}")
        effective = klass or PRIORITY_CLASSES[0]
        rank = priority_rank(effective)
        cost = float(cost)
        with self._cv:
            if self._stopped:
                raise RuntimeError("batcher is shut down")
            if self.priced:
                # Cost-unit depth: the queue's admitted cost plus this
                # request's price beyond the 1.0 a count would charge —
                # at cost 1.0 everywhere this IS the count depth. A
                # would-be follower checks at its own price too; if it
                # then joins, the queue's cost is untouched (it adds
                # no compute).
                depth = self._queue_cost + cost - 1.0
            else:
                # Outstanding-CLIENT depth: every waiter on a queued
                # pending counts — a collapsed follower is still an
                # outstanding request, so a byte-identical flood sheds
                # at exactly the watermark a distinct flood would.
                # Without collapsing this IS len(queue).
                depth = sum(p.waiters for p in self._queue)
            if self.shed_policy is not None:
                admitted = self.shed_policy.admits(
                    effective, depth, self.max_queue)
            else:
                admitted = depth < self.max_queue
            if not admitted:
                if self.serve_log is not None:
                    self.serve_log.record_rejection(klass=klass)
                if self.shed_policy is None:
                    raise Overloaded(
                        f"request queue full ({self.max_queue} pending)")
                limit = self.shed_policy.admit_depth(
                    effective, self.max_queue)
                retry_after = self.shed_policy.retry_after_s(
                    effective, depth, self.max_queue,
                    self._drain.rate(), incoming=cost if self.priced
                    else 1.0)
                raise Overloaded(
                    f"request queue past the {effective!r} admission "
                    f"watermark ({depth:g} pending, class limit {limit} "
                    f"of {self.max_queue})", retry_after_s=retry_after)
            if collapse_key is not None:
                # Admitted — now a duplicate of a still-queued pending
                # joins it instead of consuming a slot and a dispatch.
                leader = self._inflight_keys.get(collapse_key)
                if leader is not None and not leader.abandoned:
                    leader.waiters += 1
                    self.collapsed += 1
                    return leader
            pending = _Pending(arr, int(arr.shape[0]), klass=klass,
                               rank=rank, seq=self._seq,
                               ckey=collapse_key, cost=cost,
                               guard=self._cv)
            self._seq += 1
            if collapse_key is not None:
                self._inflight_keys[collapse_key] = pending
            # Priority insert, stable within a class: scan back from
            # the tail (same-or-more-urgent arrivals append in O(1),
            # the common case; an interactive request overtakes only
            # the less-urgent tail).
            i = len(self._queue)
            while i > 0 and self._queue[i - 1].rank > rank:
                i -= 1
            self._queue.insert(i, pending)
            self._queue_cost += cost
            self._cv.notify_all()
        return pending

    @staticmethod
    def result(pending: _Pending, timeout: Optional[float] = None):
        if not pending.event.wait(timeout):
            # This caller will never read the answer — but a collapsed
            # follower still might: only when the LAST waiter leaves is
            # the pending abandoned (then, if still queued, the worker
            # drops it instead of executing it; an already in-flight
            # batch can't be recalled from the device).
            if pending.guard is not None:
                with pending.guard:
                    pending.waiters -= 1
                    if pending.waiters <= 0:
                        pending.abandoned = True
            else:
                pending.abandoned = True
            raise TimeoutError("request did not complete in time")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def predict(self, images, timeout: Optional[float] = 30.0,
                klass: Optional[str] = None,
                collapse_key: Optional[str] = None, cost: float = 1.0):
        """Synchronous submit + wait — the HTTP handler's one call."""
        return self.result(
            self.submit(images, klass=klass, collapse_key=collapse_key,
                        cost=cost),
            timeout)

    # -- worker side -------------------------------------------------------

    def _take_batch(self) -> List[_Pending]:
        """Block until work exists, then coalesce under the deadline.

        The deadline is anchored to the OLDEST request's submit time, so
        a trickle of arrivals cannot postpone the flush indefinitely; a
        full ``max_batch`` flushes immediately. Returns ``[]`` only when
        stopped with an empty queue."""
        def takeable_rows() -> int:
            """Rows the take loop below would ACTUALLY co-batch right
            now — same walk, same no-split rule, skipping abandoned
            entries. The flush trigger must use this, not a raw sum: a
            1-row request followed by an oversized one would otherwise
            'fill' the batch on paper and flush the 1-row alone with
            coalescing time still on the clock."""
            rows = 0
            dtype = None
            for p in self._queue:
                if p.abandoned:
                    continue
                if rows and (rows + p.rows > self.max_batch
                             or p.images.dtype != dtype):
                    break
                rows += p.rows
                dtype = p.images.dtype
                if rows >= self.max_batch:
                    break
            return rows

        with self._cv:
            while True:  # until a non-empty take, or stopped + drained
                while not self._queue and not self._stopped:
                    self._cv.wait()
                if not self._queue:
                    return []
                # Anchored to the OLDEST waiting request (with priority
                # ordering the head is the most URGENT, not the oldest —
                # an interactive trickle must not reset a queued batch
                # request's clock).
                deadline = min(p.t_submit for p in self._queue) \
                    + self.max_wait_s
                while not self._stopped:
                    remaining = deadline - time.perf_counter()
                    if takeable_rows() >= self.max_batch or remaining <= 0:
                        break
                    self._cv.wait(remaining)
                taken, rows = [], 0
                while self._queue and rows < self.max_batch:
                    head = self._queue[0]
                    if head.abandoned:
                        # Every caller timed out and left: drop without
                        # executing (finish() skips stats for abandoned).
                        self._queue.pop(0)
                        self._queue_cost -= head.cost
                        if head.ckey is not None and \
                                self._inflight_keys.get(head.ckey) is head:
                            del self._inflight_keys[head.ckey]
                        head.finish(None, TimeoutError("abandoned"),
                                    self.serve_log)
                        continue
                    # Never split one request across batches: results map
                    # back by whole slices. A request bigger than
                    # max_batch rides alone (the engine chunks it through
                    # the top bucket). Never MIX dtypes either: with the
                    # fused serve plane, raw uint8 requests ride the
                    # preprocess passthrough next to already-normalized
                    # float ones, and np.concatenate's promotion would
                    # silently reinterpret 0-255 bytes as normalized
                    # pixels — a dtype change flushes the batch instead.
                    if taken and (rows + head.rows > self.max_batch
                                  or head.images.dtype
                                  != taken[0].images.dtype):
                        break
                    self._queue.pop(0)
                    self._queue_cost -= head.cost
                    if head.ckey is not None and \
                            self._inflight_keys.get(head.ckey) is head:
                        # Collapse window closes AT DISPATCH: a
                        # duplicate arriving mid-execution queues
                        # normally (and the response cache answers it
                        # after this batch completes) — it must never
                        # ride a result that predates a param swap.
                        del self._inflight_keys[head.ckey]
                    taken.append(head)
                    rows += head.rows
                if not self._queue:
                    self._queue_cost = 0.0  # re-zero any float drift
                if not taken:
                    continue  # everything seen was abandoned: wait again
                t = time.perf_counter()
                for p in taken:
                    p.t_batched = t
                return taken

    def _dispatch_loop(self) -> None:
        """Form/dispatch stage: coalesce a batch, hand it to
        ``dispatch_fn`` (which enqueues device work and returns — or, in
        the classic ``infer_fn`` form, runs the whole inference), and
        immediately form the next one. The ``_window`` semaphore holds
        dispatch ``max_inflight`` batches ahead of completion at most;
        with a window of 1 this loop alternates with completion exactly
        like the original single worker."""
        try:
            while True:
                self._window.acquire()
                taken = self._take_batch()
                if not taken:
                    self._window.release()
                    return  # stopped and drained
                handle, error = None, None
                try:
                    # Concatenation inside the try: co-batched requests
                    # with mismatched trailing shapes (submit validates
                    # only ndim) must become per-request errors, not a
                    # dead worker.
                    images = (taken[0].images if len(taken) == 1
                              else np.concatenate(
                                  [p.images for p in taken], axis=0))
                    handle = self.dispatch_fn(images)
                except BaseException as exc:  # noqa: BLE001 - per-request
                    error = exc
                self._inflight.put((taken, handle, error))
        finally:
            # ALWAYS hand completion its shutdown sentinel — a dispatch
            # thread dying any other way would otherwise leave close()
            # blocked forever on the completion join.
            self._inflight.put(None)

    def _completion_loop(self) -> None:
        """Completion stage: pop dispatched batches FIFO, block on the
        result fetch, deliver results/errors/accounting per request —
        exactly what the tail of the original worker loop did."""
        while True:
            item = self._inflight.get()
            if item is None:
                return
            taken, handle, error = item
            try:
                self._complete_batch(taken, handle, error)
            finally:
                self._window.release()

    def _complete_batch(self, taken: List[_Pending], handle,
                        error) -> None:
        out = None
        if error is None:
            # Validation INSIDE the try: a malformed return (0-d array,
            # wrong row count) must become a per-request error — an
            # exception escaping here would kill the completion thread
            # and wedge close() behind the window semaphore.
            try:
                out = np.asarray(self.complete_fn(handle))
                rows = sum(p.rows for p in taken)
                if out.ndim == 0 or out.shape[0] != rows:
                    which = ("infer_fn" if self.infer_fn is not None
                             else "complete_fn")
                    raise RuntimeError(
                        f"{which} returned "
                        f"{'a scalar' if out.ndim == 0 else out.shape[0]}"
                        f" row(s) for {rows} inputs")
            except BaseException as exc:  # noqa: BLE001 - per-request delivery
                error = exc
        if error is not None:
            for p in taken:
                p.finish(None, error, self.serve_log)
            return
        if self.cost_model is not None:
            # Serve-time EWMA refresh of the per-bucket cost table: the
            # measured wall from batch formation to delivered results.
            self.cost_model.observe(
                sum(p.rows for p in taken),
                time.perf_counter() - taken[0].t_batched)
        off = 0
        for p in taken:
            p.finish(out[off:off + p.rows], None, self.serve_log)
            off += p.rows
        # Completed requests feed the drain-rate estimate Retry-After
        # hints divide by (errors excluded: a failing plane is not
        # drain capacity). Priced batchers drain COST units, so the
        # hint says when the drained cost plausibly re-admits, not the
        # drained request count.
        self._drain.note(sum(p.cost for p in taken) if self.priced
                         else len(taken))
