"""Serving subsystem: request-level inference decoupled from training.

The training side of this framework runs epochs; this package runs
REQUESTS — the north-star's "serves heavy traffic" capability. Pieces:

- ``engine.py``: :class:`InferenceEngine` — params + a fixed set of
  AOT-compiled forward programs at batch buckets (pad up, never
  recompile), built on the same forward-program builder ``--evaluate``
  uses (``train/steps.py make_forward_program``);
- ``batcher.py``: :class:`MicroBatcher` — dynamic micro-batching with a
  max-wait deadline, max-batch coalescing, and bounded-queue admission
  control (:class:`Overloaded` instead of unbounded latency);
- ``pool.py``: :class:`EnginePool` — the multi-chip data plane: one
  engine replica per local device (per-device params + AOT programs)
  behind a least-loaded dispatcher, driven through the batcher's
  pipelined dispatch/complete stages (``--serve-devices`` /
  ``--max-inflight``); with a sharded ``--serve-mode`` the chips
  partition into ``--serve-mesh``-sized mesh groups instead;
- ``programs.py``: the forward-program registry — given a model name
  and a ``--serve-mode`` (replicated / tensor / expert / pipeline,
  extensible), builds the serving mesh, derives param/input/output
  shardings from the training rule tables, and hands the engine a
  :class:`MeshPlacement` its bucket programs AOT-lower against, plus
  the checkpoint parallel-layout gate (``check_checkpoint_layout``)
  and the PRECISION plane (``--serve-precision``: f32 / bf16 / int8w /
  int8, extensible — install-time quantization with per-leaf scales as
  program arguments, so hot reload stays an atomic swap);
- ``canary.py``: :class:`ShadowCanary` — the shadow-traffic accuracy
  canary gating a quantized precision: the f32 baseline answers while
  a fraction of live batches shadows the quantized plane; promote
  after clean rows, auto-rollback past the disagreement budget,
  per-publish reset through the reload watcher;
- ``pipeline.py``: :class:`PipelineEngine` — the MPMD plane for
  pipeline-trained checkpoints: one INDEPENDENT program per stage chip
  (stage params split at the training stage boundaries), micro-batches
  streamed between stages with async device-to-device hops so stage k
  runs batch N while stage k+1 runs batch N-1;
- ``reload.py``: :class:`CheckpointWatcher` — polls a published
  checkpoint directory (``train/checkpoint.py`` conventions) and swaps
  params atomically between batches (fanned out per replica on a pool);
- ``control.py``: the CONTROL PLANE above the data plane — priority
  classes with per-class shed watermarks (:class:`ShedPolicy`),
  per-client token-bucket quotas (:class:`ClientQuotas`, 429 before a
  queue slot is spent), the SLO-driven :class:`AutoScaler` actuating
  the pool's resize path with hysteresis + cooldown, and the
  :class:`WeightedFairGate` sharing one chip budget across a
  ``--model-set`` of models;
- ``server.py``: the ``serve`` CLI subcommand — a stdlib HTTP JSON
  endpoint with ``/predict``, ``/healthz``, ``/stats``, ``/resize``
  (one model plane per ``--model-set`` entry, requests routed on their
  ``model`` field).

Drive it with ``tools/loadgen.py``; measure it with
``python bench.py --mode serve``.
"""

from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher, Overloaded
from pytorch_distributed_mnist_tpu.serve.canary import ShadowCanary
from pytorch_distributed_mnist_tpu.serve.control import (
    PRIORITY_CLASSES,
    AutoScaler,
    ClientQuotas,
    ShedPolicy,
    TokenBucket,
    WeightedFairGate,
)
from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
from pytorch_distributed_mnist_tpu.serve.pipeline import PipelineEngine
from pytorch_distributed_mnist_tpu.serve.pool import EnginePool, EngineReplica
from pytorch_distributed_mnist_tpu.serve.programs import (
    SERVE_MODES,
    SERVE_PRECISIONS,
    MeshPlacement,
    ServePrecision,
    build_group_placements,
    build_placement,
    check_checkpoint_layout,
    servable_modes,
    serve_precisions,
)
from pytorch_distributed_mnist_tpu.serve.reload import CheckpointWatcher

__all__ = [
    "PRIORITY_CLASSES",
    "SERVE_MODES",
    "SERVE_PRECISIONS",
    "AutoScaler",
    "CheckpointWatcher",
    "ClientQuotas",
    "ShedPolicy",
    "TokenBucket",
    "WeightedFairGate",
    "EnginePool",
    "EngineReplica",
    "InferenceEngine",
    "MeshPlacement",
    "MicroBatcher",
    "Overloaded",
    "PipelineEngine",
    "ServePrecision",
    "ShadowCanary",
    "build_group_placements",
    "build_placement",
    "check_checkpoint_layout",
    "servable_modes",
    "serve_precisions",
]
