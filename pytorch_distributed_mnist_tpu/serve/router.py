"""The ``route`` CLI subcommand: a fleet-federation front-end.

Everything through PR 14/15 ends at one serve process — one chip
budget, one ``/stats``, one autoscaler — so one SIGKILL takes the whole
serving surface down. ``tpu-mnist route --backends host:port,...`` puts
a pure-stdlib routing tier above N backend serve processes and makes a
BACKEND the failure domain, not the system:

- **Discovery + health.** A static ``--backends`` list and/or dynamic
  ``--backends-dir`` discovery (serve processes started with
  ``--register-dir`` drop/remove ``backend_*.json`` records; the sweep
  reconciles joins on probation and reaps only what it discovered),
  plus a background ``/healthz`` poller running the pool-heal state
  machine one level up (serve/pool.py, PR 10): ``--quarantine-after``
  consecutive failures quarantine a backend (not routable, still
  probed), a successful probe re-admits it on PROBATION (routable, one
  strike re-quarantines), ``--probation-successes`` clean results make
  it HEALTHY again.
- **Routing.** Each ``/predict`` routes on model x priority:
  least-loaded over the routable backends serving that model (fewest
  in-flight of the request's class, then fewest total, then name — a
  deterministic tie-break), with consistent-hash ``client_id`` affinity
  on top (a client sticks to one backend while the backend set is
  stable; when it changes, only ~1/N of clients move — the hash-ring
  property).
- **Defensive dispatch.** Per-request connect/read timeouts; ONE retry
  on a DIFFERENT backend only for failures that PROVE the backend never
  executed the request (connection refused, reset before any response
  bytes, or the backend's own drain-503 refusal) — a timeout or a
  mid-body reset may have executed, so it is never double-dispatched;
  backend 503/429 ``Retry-After`` passes through untouched (fleet-wide
  backpressure must reach clients); a loud fleet 503 only when ZERO
  routable backends remain.
- **Deploys as fleet operations.** ``POST /rollout`` runs a rolling
  reload — drain one backend (its own admission control, PR's /drain),
  wait for in-flight to hit zero via ``/stats``, publish the checkpoint
  into that backend's directory, verify ``/healthz`` epoch, rejoin,
  next — and fleet canaries: publish to one backend first, route a
  deterministic fraction of *clients* there, and reuse the PR 13 canary
  verdict shape (shadow -> primary / rolled_back on an error budget)
  for fleet-wide auto-promote/auto-rollback.
- **Two-tier autoscaling.** ``--fleet-min/--fleet-max`` scale the
  NUMBER of backend processes (spawn via ``--spawn-backend``); PR 14's
  per-pool autoscaler stays the intra-process actuator.
- **Aggregated /stats.** Per-backend rows plus fleet quantiles merged
  from the PR 14 rolling-window blocks (count-weighted CDF merge —
  ``merge_windows``).

Lock discipline (pinned by the tpumnist-lint lock-discipline checker):
the routing table has ONE lock and no network IO ever runs under it —
every dispatch snapshots the decision under the lock, then talks HTTP
outside it. The health poller keeps its own lock for sweep bookkeeping
with the same rule.

Deliberately pure stdlib: this module imports no jax/numpy and calls
nothing in the data plane it fronts — the router keeps routing and
failing over even when every backend is down. (The package import
chain may load the framework; nothing HERE uses it, which is what the
unit suite exercises: every class above the HTTP layer is pure.)
"""

from __future__ import annotations

import argparse
import bisect
import hashlib
import http.client
import json
import os
import re
import shlex
import shutil
import struct
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

# The shared request-economics layer (serve/economics.py) is pure
# stdlib BY CONTRACT — the router reuses the backend's exact-match
# keyed cache and generation-invalidation rule without growing a
# jax/numpy dependency.
from pytorch_distributed_mnist_tpu.serve.economics import (
    ResponseCache,
    request_key,
)

# Mirrors serve/control.py::PRIORITY_CLASSES without importing it (that
# module imports numpy; the router is stdlib-only). The backend remains
# the authority — an unknown class forwarded anyway comes back 400.
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")

# Fault injection for the fleet chaos twins (tools/chaos.py
# --fleet-canary-rollback): "canary_disagree" makes every canary-cohort
# row count as a disagreement, driving the budget rollback path
# deterministically — the same pattern as serve/canary.py's
# TPUMNIST_CANARY_FAULT one level down.
FLEET_FAULT_ENV = "TPUMNIST_FLEET_FAULT"

MAX_BODY_BYTES = 16 << 20

# Backend health states — the pool-heal vocabulary one level up.
HEALTHY = "healthy"
QUARANTINED = "quarantined"
PROBATION = "probation"

_EPOCH_RE = re.compile(r"checkpoint_(\d+)")


# ---------------------------------------------------------------------------
# Pure parts: every class below is deterministic and IO-free, unit-tested
# in tests/test_serve_router.py without a socket in sight.
# ---------------------------------------------------------------------------


class BackendHealth:
    """The quarantine/probation state machine, as pure transitions.

    HEALTHY --(quarantine_after consecutive failures)--> QUARANTINED
    QUARANTINED --(one successful probe)--> PROBATION
    PROBATION --(one failure)--> QUARANTINED  (one strike on probation)
    PROBATION --(probation_successes consecutive successes)--> HEALTHY

    Any success resets the failure count (exactly the pool's rule).
    Callers hold whatever lock guards the backend table; this class
    holds none.
    """

    def __init__(self, quarantine_after: int = 3,
                 probation_successes: int = 3) -> None:
        if quarantine_after < 1:
            raise ValueError(
                f"quarantine_after must be >= 1, got {quarantine_after}")
        if probation_successes < 1:
            raise ValueError(f"probation_successes must be >= 1, "
                             f"got {probation_successes}")
        self.quarantine_after = quarantine_after
        self.probation_successes = probation_successes
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.probation_streak = 0
        self.quarantines = 0
        self.readmissions = 0

    @property
    def routable(self) -> bool:
        return self.state != QUARANTINED

    def note_success(self) -> Optional[str]:
        """Record one successful probe/dispatch; returns the new state
        when a transition happened, else None."""
        self.consecutive_failures = 0
        if self.state == QUARANTINED:
            self.state = PROBATION
            self.probation_streak = 0
            return PROBATION
        if self.state == PROBATION:
            self.probation_streak += 1
            if self.probation_streak >= self.probation_successes:
                self.state = HEALTHY
                self.readmissions += 1
                return HEALTHY
        return None

    def note_failure(self) -> Optional[str]:
        """Record one failed probe/dispatch; returns QUARANTINED when
        this failure crossed the threshold, else None."""
        if self.state == PROBATION:
            # One strike: probation earns trust slowly, loses it fast.
            self.state = QUARANTINED
            self.consecutive_failures = 0
            self.probation_streak = 0
            self.quarantines += 1
            return QUARANTINED
        if self.state == QUARANTINED:
            return None
        self.consecutive_failures += 1
        if self.consecutive_failures >= self.quarantine_after:
            self.state = QUARANTINED
            self.consecutive_failures = 0
            self.quarantines += 1
            return QUARANTINED
        return None


class HashRing:
    """Consistent hashing for client affinity: each node owns
    ``replicas`` points on a 64-bit ring; a key routes to the first
    point clockwise. Adding/removing one of N nodes moves only ~1/N of
    the keys — every other client keeps its backend (and that backend's
    warm batcher) across a fleet topology change."""

    def __init__(self, nodes: Sequence[str] = (), replicas: int = 64) -> None:
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.replicas = replicas
        self._points: List[Tuple[int, str]] = []  # sorted (hash, node)
        for node in nodes:
            self.add(node)

    @staticmethod
    def _hash(key: str) -> int:
        digest = hashlib.sha256(key.encode("utf-8", "replace")).digest()
        return int.from_bytes(digest[:8], "big")

    def __contains__(self, node: str) -> bool:
        return any(n == node for _, n in self._points)

    def __len__(self) -> int:
        return len({n for _, n in self._points})

    def add(self, node: str) -> None:
        if node in self:
            return
        for i in range(self.replicas):
            bisect.insort(self._points, (self._hash(f"{node}#{i}"), node))

    def remove(self, node: str) -> None:
        self._points = [(h, n) for h, n in self._points if n != node]

    def node_for(self, key: str) -> Optional[str]:
        if not self._points:
            return None
        h = self._hash(key)
        idx = bisect.bisect_right(self._points, (h, "￿"))
        if idx == len(self._points):
            idx = 0
        return self._points[idx][1]


class TransportError(Exception):
    """One failed backend HTTP exchange, annotated with whether any
    response bytes had arrived — the fact the retry-safety classifier
    needs (a reset AFTER the status line may have executed)."""

    def __init__(self, exc: BaseException, body_started: bool) -> None:
        super().__init__(repr(exc))
        self.exc = exc
        self.body_started = body_started


def classify_failure(exc: BaseException) -> str:
    """Bucket one transport exception: ``refused`` / ``reset`` /
    ``timeout`` / ``http`` / ``transport`` / ``other``. URLError is
    unwrapped to its reason first."""
    if isinstance(exc, TransportError):
        exc = exc.exc
    reason = exc
    if isinstance(exc, urllib.error.HTTPError):
        return "http"
    if isinstance(exc, urllib.error.URLError) and isinstance(
            exc.reason, BaseException):
        reason = exc.reason
    if isinstance(reason, ConnectionRefusedError):
        return "refused"
    # RemoteDisconnected subclasses ConnectionResetError: "closed the
    # connection without response" is precisely reset-before-body.
    if isinstance(reason, (ConnectionResetError, BrokenPipeError)):
        return "reset"
    # socket.timeout is TimeoutError on 3.10+; check BEFORE the OSError
    # catch-all (TimeoutError subclasses OSError).
    if isinstance(reason, TimeoutError):
        return "timeout"
    if isinstance(reason, OSError):
        return "transport"
    return "other"


def retry_safe(exc: BaseException, body_started: bool = False) -> bool:
    """True only when the failure PROVES the backend never executed the
    request, so dispatching it to a different backend cannot double-run
    it: connection refused (never accepted) or reset before any
    response bytes (never answered — stdlib http.client raises
    RemoteDisconnected for exactly this). A timeout is ambiguous (the
    backend may be executing right now) and anything after the first
    response byte certainly reached application code: neither retries.
    HTTP status replies are not transport failures at all — 5xx passes
    through (the backend DID run something)."""
    if isinstance(exc, TransportError):
        body_started = body_started or exc.body_started
        exc = exc.exc
    if body_started:
        return False
    return classify_failure(exc) in ("refused", "reset")


def pick_backend(candidates: Sequence["Backend"], klass: Optional[str] = None,
                 client_id: Optional[str] = None,
                 ring: Optional[HashRing] = None) -> Optional["Backend"]:
    """The pure dispatch decision over a snapshot of routable backends:
    consistent-hash affinity when the client's ring choice is among the
    candidates, else least-loaded — fewest in-flight of the request's
    priority class, then fewest total, then fewest requests served so
    far (fast backends finish between arrivals, so the in-flight keys
    tie at zero constantly — without this the winner would be STICKY
    and one backend would absorb the whole open-loop stream), then
    lexicographic name (the deterministic last tie-break the unit
    suite pins)."""
    if not candidates:
        return None
    if client_id and ring is not None:
        preferred = ring.node_for(client_id)
        for backend in candidates:
            if backend.name == preferred:
                return backend
    k = klass or PRIORITY_CLASSES[0]
    return min(candidates,
               key=lambda b: (b.inflight.get(k, 0), b.total_inflight,
                              b.requests, b.name))


def _interp_cdf(knots: Sequence[Tuple[float, float]], x: float) -> float:
    """Piecewise-linear CDF through (value, cumulative-fraction) knots."""
    if x >= knots[-1][0]:
        return 1.0
    prev_x, prev_y = knots[0]
    if x <= prev_x:
        return prev_y if x == prev_x else 0.0
    for kx, ky in knots[1:]:
        if x <= kx:
            if kx == prev_x:
                return ky
            frac = (x - prev_x) / (kx - prev_x)
            return prev_y + frac * (ky - prev_y)
        prev_x, prev_y = kx, ky
    return 1.0


def merge_windows(blocks: Sequence[Optional[dict]]) -> dict:
    """Merge per-backend rolling-window blocks (serve/profiling.py
    ``ServeLog.window_stats``: seconds/rps/queue_depth/p50_ms/p95_ms/
    p99_ms/count) into fleet quantiles.

    Backends export quantiles, not raw samples, so the exact fleet
    quantile is unrecoverable; this is the standard deterministic
    approximation: model each backend's latency CDF as piecewise-linear
    through its known quantile knots ((p50, .5), (p95, .95), (p99, 1.0)
    — p99 treated as the effective max), sum the CDFs weighted by
    request count, and invert by bisection. Exact when backends share a
    distribution; always within [min, max] of the per-backend quantiles
    otherwise (pinned against a flat recompute in the unit suite).
    Throughput merges exactly: rps/count/queue_depth are sums."""
    rows = [b for b in blocks if b and b.get("count", 0) > 0]
    merged = {
        "backends": len(rows),
        "seconds": max((float(b.get("seconds", 0.0)) for b in rows),
                       default=0.0),
        "rps": round(sum(float(b.get("rps", 0.0)) for b in rows), 3),
        "queue_depth": sum(int(b.get("queue_depth", 0)) for b in rows),
        "count": sum(int(b["count"]) for b in rows),
    }
    if not rows:
        merged.update({"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0})
        return merged
    total = merged["count"]
    knotted = [
        ([(0.0, 0.0), (float(b["p50_ms"]), 0.50),
          (max(float(b["p95_ms"]), float(b["p50_ms"])), 0.95),
          (max(float(b["p99_ms"]), float(b["p95_ms"]),
               float(b["p50_ms"])), 1.0)], int(b["count"]))
        for b in rows
    ]
    hi_bound = max(knots[-1][0] for knots, _ in knotted)

    def cdf(x: float) -> float:
        return sum(c * _interp_cdf(knots, x)
                   for knots, c in knotted) / total

    def quantile(q: float) -> float:
        lo, hi = 0.0, max(hi_bound, 1e-9)
        for _ in range(64):
            mid = (lo + hi) / 2.0
            if cdf(mid) < q:
                lo = mid
            else:
                hi = mid
        return round(hi, 3)

    merged.update({"p50_ms": quantile(0.50), "p95_ms": quantile(0.95),
                   "p99_ms": quantile(0.99)})
    return merged


class RollingReload:
    """The rolling-deploy sequencer: strictly one backend at a time,
    each through drain -> wait in-flight zero -> publish -> verify
    epoch -> rejoin. ``ops`` is injected (the router's real ops do HTTP
    + an atomic file copy) so the ordering contract is unit-testable
    with a scripted fake; a failure undrains the victim and STOPS — the
    backends not yet touched keep serving the old epoch, which is the
    whole point of rolling."""

    def __init__(self, ops, *, drain_timeout_s: float = 30.0,
                 verify_timeout_s: float = 60.0, poll_s: float = 0.05,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.ops = ops
        self.drain_timeout_s = float(drain_timeout_s)
        self.verify_timeout_s = float(verify_timeout_s)
        self.poll_s = float(poll_s)
        self._sleep = sleep
        self._clock = clock

    def _wait(self, check: Callable[[], bool], timeout_s: float,
              what: str) -> None:
        deadline = self._clock() + timeout_s
        while True:
            if check():
                return
            if self._clock() >= deadline:
                raise TimeoutError(f"timed out after {timeout_s}s "
                                   f"waiting for {what}")
            self._sleep(self.poll_s)

    def run(self, backends: Sequence[str], target_epoch: int) -> dict:
        updated: List[str] = []
        for name in backends:
            try:
                self.ops.drain(name)
                self._wait(lambda: self.ops.active_requests(name) == 0,
                           self.drain_timeout_s,
                           f"{name} in-flight to reach zero")
                self.ops.publish(name)
                self._wait(lambda: self.ops.epoch(name) == target_epoch,
                           self.verify_timeout_s,
                           f"{name} to serve epoch {target_epoch}")
                self.ops.undrain(name)
            except Exception as exc:  # noqa: BLE001 - report, never raise
                try:
                    self.ops.undrain(name)
                except Exception:  # noqa: BLE001 - best-effort rejoin
                    pass
                return {"ok": False, "updated": updated, "failed": name,
                        "error": repr(exc), "target_epoch": target_epoch}
            updated.append(name)
        return {"ok": True, "updated": updated,
                "target_epoch": target_epoch}


SHADOW = "shadow"
PRIMARY = "primary"
ROLLED_BACK = "rolled_back"


class FleetCanary:
    """PR 13's canary verdict shape, one level up. At fleet scope there
    are no logits to diff, so a "row" is one reply served by the canary
    cohort and a "disagreement" is a failed one (5xx or transport) —
    the contract under test is availability of the new epoch, not
    numerics (the backend's own shadow canary still guards those).
    Verdict rule is verbatim PR 13: rollback when disagreed_rows exceed
    ``budget * promote_after`` (rollback outranks promotion), promote
    when ``promote_after`` rows compared inside the budget. Counter
    mutation runs under one lock; the caller acts on the returned
    verdict OUTSIDE it (lock discipline: the follow-up is HTTP)."""

    def __init__(self, fraction: float, backends: Sequence[str],
                 target_epoch: int, baseline_epoch: Optional[int],
                 promote_after: int = 200, budget: float = 0.02) -> None:
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1], "
                             f"got {fraction}")
        if promote_after < 1:
            raise ValueError(f"promote_after must be >= 1, "
                             f"got {promote_after}")
        if budget < 0.0:
            raise ValueError(f"budget must be >= 0, got {budget}")
        self.fraction = float(fraction)
        self.backends = tuple(backends)
        self.target_epoch = int(target_epoch)
        self.baseline_epoch = baseline_epoch
        self.promote_after = int(promote_after)
        self.budget = float(budget)
        self._lock = threading.Lock()
        self._state = SHADOW
        self.compared_rows = 0
        self.disagreed_rows = 0
        self.promotions = 0
        self.rollbacks = 0
        self._fault = os.environ.get(FLEET_FAULT_ENV, "")

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def wants(self, client_id: Optional[str]) -> bool:
        """Deterministic cohort membership: the same client hashes to
        the same side for the whole canary (no coin flips — a client
        never flaps between epochs mid-experiment). Anonymous requests
        stay on the baseline."""
        if not client_id or self.state != SHADOW:
            return False
        digest = hashlib.sha256(
            f"fleet-canary:{client_id}".encode()).digest()
        return (int.from_bytes(digest[:8], "big") % 10_000
                < self.fraction * 10_000)

    def note_result(self, ok: bool) -> Optional[str]:
        """Record one canary-cohort reply; returns "promote" or
        "rollback" exactly once, on the row that decides."""
        if self._fault == "canary_disagree":
            ok = False
        with self._lock:
            if self._state != SHADOW:
                return None
            self.compared_rows += 1
            if not ok:
                self.disagreed_rows += 1
            if self.disagreed_rows > self.budget * self.promote_after:
                self._state = ROLLED_BACK
                self.rollbacks += 1
                return "rollback"
            if self.compared_rows >= self.promote_after:
                self._state = PRIMARY
                self.promotions += 1
                return "promote"
        return None

    def fail(self) -> Optional[str]:
        """The install-verify failure path: the canary backend never
        reached the target epoch (corrupt/mislayouted publish — the
        watcher refused it), so there is nothing to measure: straight
        to rolled_back."""
        with self._lock:
            if self._state != SHADOW:
                return None
            self._state = ROLLED_BACK
            self.rollbacks += 1
            return "rollback"

    def snapshot(self) -> dict:
        with self._lock:
            compared = self.compared_rows
            return {
                "state": self._state,
                "fraction": self.fraction,
                "backends": list(self.backends),
                "target_epoch": self.target_epoch,
                "baseline_epoch": self.baseline_epoch,
                "promote_after": self.promote_after,
                "budget": self.budget,
                "compared_rows": compared,
                "disagreed_rows": self.disagreed_rows,
                "disagree_rate": round(self.disagreed_rows / compared, 4)
                                 if compared else 0.0,
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
            }


class FleetAutoscaler:
    """Two-tier control, the fleet half: decide when to START or STOP a
    whole backend process. PR 14's AutoScaler (serve/control.py) keeps
    re-shaping the pool INSIDE each process; this tier only changes how
    many processes exist — the same DCN-over-ICI split the data plane
    uses. Same control shape as the per-pool scaler: scale up
    immediately on SLO breach (merged fleet p95 over ``slo_p95_ms``, or
    fewer routable backends than the floor), scale down only after
    ``down_after`` consecutive calm ticks, both behind a shared
    cooldown. ``decide`` is pure (explicit ``now``) for the unit suite;
    ``start_fn``/``stop_fn`` are injected actuators."""

    def __init__(self, min_backends: int, max_backends: int, *,
                 slo_p95_ms: float = 100.0, calm_frac: float = 0.3,
                 cooldown_s: float = 10.0, down_after: int = 3,
                 start_fn: Optional[Callable[[], bool]] = None,
                 stop_fn: Optional[Callable[[], bool]] = None) -> None:
        if min_backends < 1:
            raise ValueError(f"--fleet-min must be >= 1, "
                             f"got {min_backends}")
        if max_backends < min_backends:
            raise ValueError(f"--fleet-max {max_backends} is below "
                             f"--fleet-min {min_backends}")
        self.min_backends = min_backends
        self.max_backends = max_backends
        self.slo_p95_ms = float(slo_p95_ms)
        self.calm_frac = float(calm_frac)
        self.cooldown_s = float(cooldown_s)
        self.down_after = int(down_after)
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self.dry_run = start_fn is None
        self._last_action_t = float("-inf")
        self._calm_streak = 0
        self.ups = 0
        self.downs = 0
        self.decisions: List[dict] = []

    def decide(self, n_routable: int, merged: dict,
               now: float) -> Optional[str]:
        """One control tick over the merged fleet window; returns "up",
        "down", or None. Pure: no clock, no IO."""
        if n_routable < self.min_backends:
            # Below the floor is an availability hole, not a load
            # question: no cooldown, no hysteresis.
            self._calm_streak = 0
            self._note(now, "up", n_routable, merged, reason="below_min")
            return "up"
        if now - self._last_action_t < self.cooldown_s:
            return None
        p95 = float(merged.get("p95_ms", 0.0) or 0.0)
        busy = p95 > self.slo_p95_ms and merged.get("count", 0) > 0
        calm = merged.get("count", 0) == 0 or p95 < self.slo_p95_ms \
            * self.calm_frac
        if busy and n_routable < self.max_backends:
            self._calm_streak = 0
            self._note(now, "up", n_routable, merged, reason="p95_over_slo")
            return "up"
        if calm and n_routable > self.min_backends:
            self._calm_streak += 1
            if self._calm_streak >= self.down_after:
                self._calm_streak = 0
                self._note(now, "down", n_routable, merged, reason="calm")
                return "down"
        else:
            self._calm_streak = 0
        return None

    def _note(self, now: float, action: str, n: int, merged: dict,
              reason: str) -> None:
        self._last_action_t = now
        if action == "up":
            self.ups += 1
        else:
            self.downs += 1
        self.decisions.append({
            "t": round(now, 3), "action": action, "reason": reason,
            "routable": n, "p95_ms": merged.get("p95_ms"),
            "rps": merged.get("rps")})
        del self.decisions[:-20]

    def snapshot(self) -> dict:
        return {
            "min_backends": self.min_backends,
            "max_backends": self.max_backends,
            "slo_p95_ms": self.slo_p95_ms,
            "cooldown_s": self.cooldown_s,
            "down_after": self.down_after,
            "dry_run": self.dry_run,
            "scale_ups": self.ups,
            "scale_downs": self.downs,
            "decisions": list(self.decisions),
        }


# ---------------------------------------------------------------------------
# The routing table: the one lock, and everything it guards.
# ---------------------------------------------------------------------------


class Backend:
    """One fleet member as the router sees it: health state machine,
    in-flight counters (per priority class + total), and the last
    /healthz view (epoch, models, draining). Mutated only under the
    Fleet lock."""

    __slots__ = ("name", "url", "health", "inflight", "total_inflight",
                 "epoch", "models", "draining", "spawned", "proc",
                 "last_error", "requests", "failures")

    def __init__(self, url: str, quarantine_after: int = 3,
                 probation_successes: int = 3, spawned: bool = False,
                 proc=None) -> None:
        parsed = urllib.parse.urlsplit(
            url if "//" in url else f"http://{url}")
        if not parsed.hostname or not parsed.port:
            raise ValueError(f"backend must be host:port, got {url!r}")
        self.name = f"{parsed.hostname}:{parsed.port}"
        self.url = f"http://{self.name}"
        self.health = BackendHealth(quarantine_after, probation_successes)
        self.inflight: Dict[str, int] = {}
        self.total_inflight = 0
        self.epoch: Optional[int] = None
        self.models: Set[str] = set()
        self.draining = False
        self.spawned = spawned
        self.proc = proc
        self.last_error: Optional[str] = None
        self.requests = 0
        self.failures = 0

    @property
    def routable(self) -> bool:
        return self.health.routable and not self.draining

    def serves(self, model: Optional[str]) -> bool:
        return model is None or not self.models or model in self.models

    def row(self) -> dict:
        """The /stats per-backend row (cheap, no IO)."""
        return {
            "name": self.name,
            "state": self.health.state,
            "draining": self.draining,
            "routable": self.routable,
            "inflight": self.total_inflight,
            "epoch": self.epoch,
            "models": sorted(self.models),
            "requests": self.requests,
            "failures": self.failures,
            "quarantines": self.health.quarantines,
            "readmissions": self.health.readmissions,
            "spawned": self.spawned,
            "last_error": self.last_error,
        }


class Fleet:
    """The routing table. ONE lock guards the backend map, the hash
    ring, and every in-flight counter; the rule (enforced by the
    lock-discipline checker on this module) is snapshot-then-dispatch —
    ``acquire`` makes the whole routing decision and reserves the
    in-flight slot under the lock, and the HTTP exchange happens
    outside it."""

    def __init__(self, quarantine_after: int = 3,
                 probation_successes: int = 3, hash_replicas: int = 64,
                 on_event: Optional[Callable[..., None]] = None,
                 cache: Optional[ResponseCache] = None) -> None:
        self._lock = threading.Lock()
        self._backends: Dict[str, Backend] = {}
        self._ring = HashRing(replicas=hash_replicas)
        self.quarantine_after = quarantine_after
        self.probation_successes = probation_successes
        self._on_event = on_event
        # The router's response cache: invalidated (generation bump)
        # whenever the health poller observes ANY backend's serving
        # epoch change — a rollout/reload on one backend means a cached
        # reply anywhere in the fleet may now be stale.
        self._cache = cache
        self.failovers = 0
        self.retries = 0
        self.fleet_503s = 0

    def _emit(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            self._on_event(kind, **fields)

    def add(self, url: str, spawned: bool = False, proc=None) -> Backend:
        backend = Backend(url, self.quarantine_after,
                          self.probation_successes, spawned=spawned,
                          proc=proc)
        with self._lock:
            if backend.name in self._backends:
                return self._backends[backend.name]
            self._backends[backend.name] = backend
            self._ring.add(backend.name)
        self._emit("fleet_backend_added", backend=backend.name,
                   spawned=spawned)
        return backend

    def remove(self, name: str) -> Optional[Backend]:
        with self._lock:
            backend = self._backends.pop(name, None)
            self._ring.remove(name)
        if backend is not None:
            self._emit("fleet_backend_removed", backend=name)
        return backend

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._backends)

    def get(self, name: str) -> Optional[Backend]:
        with self._lock:
            return self._backends.get(name)

    def backends(self) -> List[Backend]:
        with self._lock:
            return [self._backends[n] for n in sorted(self._backends)]

    def n_routable(self) -> int:
        with self._lock:
            return sum(1 for b in self._backends.values() if b.routable)

    def acquire(self, model: Optional[str] = None,
                klass: Optional[str] = None,
                client_id: Optional[str] = None,
                exclude: Sequence[str] = (),
                within: Optional[Set[str]] = None) -> Optional[Backend]:
        """The routing decision + in-flight reservation, atomically
        under the table lock (so two concurrent acquires see each
        other's load). ``exclude`` removes the backend a retry already
        failed on; ``within`` restricts to a canary cohort. Returns
        None only when no routable backend fits — the caller's loud
        fleet 503."""
        with self._lock:
            candidates = [
                b for b in self._backends.values()
                if b.routable and b.serves(model) and b.name not in exclude
                and (within is None or b.name in within)]
            chosen = pick_backend(candidates, klass=klass,
                                  client_id=client_id, ring=self._ring)
            if chosen is None:
                return None
            k = klass or PRIORITY_CLASSES[0]
            chosen.inflight[k] = chosen.inflight.get(k, 0) + 1
            chosen.total_inflight += 1
            chosen.requests += 1
            return chosen

    def release(self, backend: Backend, klass: Optional[str] = None) -> None:
        k = klass or PRIORITY_CLASSES[0]
        with self._lock:
            backend.inflight[k] = max(0, backend.inflight.get(k, 0) - 1)
            backend.total_inflight = max(0, backend.total_inflight - 1)

    def note_success(self, name: str, info: Optional[dict] = None) -> None:
        """A successful probe or dispatch: health transition + cached
        /healthz view, all under the lock; the transition event is
        emitted after it drops."""
        transition = None
        epoch_changed = False
        with self._lock:
            backend = self._backends.get(name)
            if backend is None:
                return
            transition = backend.health.note_success()
            if transition == PROBATION:
                self._ring.add(name)
            if info is not None:
                epoch = info.get("model_epoch")
                new_epoch = int(epoch) if epoch is not None else None
                epoch_changed = new_epoch != backend.epoch
                backend.epoch = new_epoch
                backend.draining = bool(info.get("draining", False))
                models = info.get("models")
                if isinstance(models, dict):
                    backend.models = set(models)
                elif info.get("model"):
                    backend.models = {info["model"]}
                backend.last_error = None
        if epoch_changed and self._cache is not None:
            # Invalidation rides the poller's observation (same idiom
            # as _emit: the cache's own lock, taken OUTSIDE the table
            # lock): any backend epoch change makes every router entry
            # unreachable in O(1).
            self._cache.bump_generation()
        if transition == PROBATION:
            self._emit("fleet_probation", backend=name)
        elif transition == HEALTHY:
            self._emit("fleet_readmitted", backend=name)

    def note_failure(self, name: str, reason: str) -> None:
        transition = None
        with self._lock:
            backend = self._backends.get(name)
            if backend is None:
                return
            backend.failures += 1
            backend.last_error = reason
            transition = backend.health.note_failure()
            if transition == QUARANTINED:
                # Quarantined backends leave the affinity ring so their
                # clients re-home NOW (and, by consistency, only them).
                self._ring.remove(name)
        if transition == QUARANTINED:
            self._emit("fleet_quarantine", backend=name, reason=reason)

    def admit_probation(self, name: str) -> None:
        """Admit a just-spawned backend on PROBATION: a fresh process
        earns HEALTHY through the same streak a healed one does."""
        with self._lock:
            backend = self._backends.get(name)
            if backend is None:
                return
            backend.health.state = PROBATION
            backend.health.probation_streak = 0
            self._ring.add(name)

    def set_draining(self, name: str, draining: bool) -> None:
        with self._lock:
            backend = self._backends.get(name)
            if backend is None:
                return
            backend.draining = draining
            if draining:
                self._ring.remove(name)
            elif backend.health.routable:
                self._ring.add(name)

    def snapshot_rows(self) -> List[dict]:
        with self._lock:
            return [self._backends[n].row()
                    for n in sorted(self._backends)]

    def spawned_backends(self) -> List[Backend]:
        with self._lock:
            return [b for n, b in sorted(self._backends.items())
                    if b.spawned]


# ---------------------------------------------------------------------------
# HTTP plumbing (all of it OUTSIDE any lock).
# ---------------------------------------------------------------------------


def http_exchange(url: str, *, method: str = "GET",
                  body: Optional[bytes] = None,
                  connect_timeout: float = 1.0,
                  read_timeout: float = 30.0) -> Tuple[int, dict, bytes]:
    """One backend HTTP exchange with SPLIT connect/read timeouts
    (urllib's single knob can't tell "backend is gone" from "backend is
    slow"). Returns (status, headers, body). Raises TransportError with
    ``body_started`` set precisely: failures up to and including the
    status line are pre-response (the retry-safe window); failures
    while reading the body are not."""
    parsed = urllib.parse.urlsplit(url)
    path = parsed.path or "/"
    if parsed.query:
        path += "?" + parsed.query
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=connect_timeout)
    try:
        try:
            conn.connect()
            if conn.sock is not None:
                conn.sock.settimeout(read_timeout)
            headers = {"Content-Type": "application/json"} if body else {}
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
        except Exception as exc:  # noqa: BLE001 - classified by caller
            raise TransportError(exc, body_started=False) from exc
        try:
            data = resp.read()
        except Exception as exc:  # noqa: BLE001 - classified by caller
            raise TransportError(exc, body_started=True) from exc
        # http.client only raises IncompleteRead for CHUNK-framed short
        # bodies; a Content-Length body torn mid-stream comes back as
        # plain short bytes. Verify the count or truncated JSON reaches
        # json.loads as a decode error the retry logic misclassifies
        # (the distrib/fetch.py torn-chunk incident, one layer over).
        expected = resp.getheader("Content-Length")
        if expected and expected.isdigit() and len(data) != int(expected):
            raise TransportError(
                OSError(f"short body from {url}: got {len(data)} of "
                        f"{expected} bytes"),
                body_started=True)
        return resp.status, dict(resp.headers.items()), data
    finally:
        conn.close()


def get_json(url: str, *, connect_timeout: float = 1.0,
             read_timeout: float = 10.0) -> dict:
    status, _, body = http_exchange(url, connect_timeout=connect_timeout,
                                    read_timeout=read_timeout)
    if status != 200:
        raise TransportError(
            RuntimeError(f"GET {url} -> {status}"), body_started=True)
    return json.loads(body)


def post_json(url: str, payload: dict, *, connect_timeout: float = 1.0,
              read_timeout: float = 30.0) -> dict:
    status, _, body = http_exchange(
        url, method="POST", body=json.dumps(payload).encode(),
        connect_timeout=connect_timeout, read_timeout=read_timeout)
    if status != 200:
        raise TransportError(
            RuntimeError(f"POST {url} -> {status}: {body[:200]!r}"),
            body_started=True)
    return json.loads(body)


class RouterLog:
    """The router's own stdlib observability (it cannot import
    ServeLog: that path pulls jax). Counters plus a bounded latency
    reservoir; quantiles computed on snapshot."""

    def __init__(self, window: int = 4096) -> None:
        self._lock = threading.Lock()
        self._window = window
        self._latencies: List[float] = []
        self._t0 = time.time()
        self.requests = 0
        self.by_code: Dict[str, int] = {}
        self.by_class: Dict[str, int] = {}

    def record(self, latency_s: float, code: int,
               klass: Optional[str] = None) -> None:
        with self._lock:
            self.requests += 1
            self.by_code[str(code)] = self.by_code.get(str(code), 0) + 1
            if klass:
                self.by_class[klass] = self.by_class.get(klass, 0) + 1
            self._latencies.append(latency_s)
            del self._latencies[:-self._window]

    @staticmethod
    def _percentile(sorted_vals: List[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = q * (len(sorted_vals) - 1)
        lo = int(idx)
        hi = min(lo + 1, len(sorted_vals) - 1)
        frac = idx - lo
        return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac

    def snapshot(self) -> dict:
        with self._lock:
            vals = sorted(self._latencies)
            ms = lambda s: round(s * 1e3, 3)  # noqa: E731
            return {
                "requests": self.requests,
                "by_code": dict(self.by_code),
                "by_class": dict(self.by_class),
                "uptime_s": round(time.time() - self._t0, 3),
                "latency_ms": {
                    "p50": ms(self._percentile(vals, 0.50)),
                    "p95": ms(self._percentile(vals, 0.95)),
                    "p99": ms(self._percentile(vals, 0.99)),
                    "count": len(vals),
                },
            }


class HealthPoller:
    """The background /healthz sweep that drives the quarantine/
    probation machine. Lock discipline, same as dispatch: snapshot the
    backend list under the table lock (Fleet.backends), probe each one
    OUTSIDE any lock, then write results back through Fleet.note_*.
    The poller's own ``_lock`` guards only its sweep bookkeeping
    (last-sweep clock + per-backend probe ages for /stats).

    ``backends_dir`` adds dynamic discovery: backends started with
    ``--register-dir DIR`` drop a ``backend_*.json`` record there
    (tmp+rename; removed on drain and on shutdown), and every sweep
    reconciles first — a new record joins the fleet on PROBATION (a
    discovered process earns HEALTHY exactly like a spawned or healed
    one), a vanished record removes the backend IF this poller
    discovered it (static ``--backends`` members and scaler-spawned
    processes are never reaped by discovery)."""

    def __init__(self, fleet: Fleet, interval_s: float = 0.5,
                 connect_timeout: float = 0.5,
                 read_timeout: float = 2.0,
                 backends_dir: Optional[str] = None) -> None:
        self.fleet = fleet
        self.interval_s = float(interval_s)
        self.connect_timeout = float(connect_timeout)
        self.read_timeout = float(read_timeout)
        self.backends_dir = backends_dir
        # Backend names THIS poller added from records — the only ones
        # a vanished record may remove.
        self._discovered: Set[str] = set()
        self._lock = threading.Lock()
        self._last_sweep_t: Optional[float] = None
        self._probes: Dict[str, float] = {}
        self.sweeps = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sync_backends_dir(self) -> None:
        """Reconcile the fleet against the registration records. All
        file IO outside any lock; Fleet.add/remove take the table lock
        briefly per mutation, the sweep-then-dispatch rule intact."""
        if not self.backends_dir:
            return
        urls: List[str] = []
        try:
            entries = sorted(os.listdir(self.backends_dir))
        except OSError:
            entries = []
        for entry in entries:
            if not (entry.startswith("backend_")
                    and entry.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.backends_dir, entry)) as f:
                    url = json.load(f).get("url")
            except Exception:  # noqa: BLE001 - torn record: next sweep
                continue
            if url:
                urls.append(url)
        present: Set[str] = set()
        for url in urls:
            try:
                parsed = urllib.parse.urlsplit(
                    url if "//" in url else f"http://{url}")
                name = f"{parsed.hostname}:{parsed.port}"
            except ValueError:
                continue
            present.add(name)
            if name in self._discovered or self.fleet.get(name) is not None:
                continue
            self.fleet.add(url)
            self._discovered.add(name)
            self.fleet.admit_probation(name)
        for name in sorted(self._discovered - present):
            self._discovered.discard(name)
            self.fleet.remove(name)

    def sweep_once(self) -> None:
        """One full probe pass — public and thread-free so tests drive
        re-admission deterministically. Discovery reconciles FIRST, so
        a just-registered backend is probed in the same sweep that
        admits it."""
        self.sync_backends_dir()
        for backend in self.fleet.backends():
            name, url = backend.name, backend.url
            try:
                info = get_json(f"{url}/healthz",
                                connect_timeout=self.connect_timeout,
                                read_timeout=self.read_timeout)
            except Exception as exc:  # noqa: BLE001 - a probe never kills the poller
                self.fleet.note_failure(name, classify_failure(exc))
            else:
                self.fleet.note_success(name, info=info)
            with self._lock:
                self._probes[name] = time.time()
        with self._lock:
            self._last_sweep_t = time.time()
            self.sweeps += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "sweeps": self.sweeps,
                "last_sweep_t": self._last_sweep_t,
            }

    def start(self) -> "HealthPoller":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="router-health")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sweep_once()
            except Exception as exc:  # noqa: BLE001 - poller never dies
                print(f"router health: sweep failed: {exc!r}", flush=True)


def epoch_of_checkpoint(path: str) -> int:
    """Epoch from a publish filename (``checkpoint_{e}.npz``/``.ckpt``/
    ``.manifest`` — train/checkpoint.py's naming contract; a delta
    manifest rides the same pattern, so rollouts ship manifests with no
    router-side special case)."""
    match = _EPOCH_RE.search(os.path.basename(path))
    if not match:
        raise ValueError(
            f"cannot parse an epoch from {path!r}; publishes are named "
            f"checkpoint_EPOCH.npz/.ckpt/.manifest (train/checkpoint.py)")
    return int(match.group(1))


def atomic_copy(source: str, dest_dir: str) -> str:
    """Publish one checkpoint file into a backend's directory the way
    the trainer does: full write to a dot-tmp name, then one
    os.replace — the backend's watcher can never see a torn file."""
    base = os.path.basename(source)
    tmp = os.path.join(dest_dir, f".tmp-router-{base}")
    dest = os.path.join(dest_dir, base)
    shutil.copyfile(source, tmp)
    os.replace(tmp, dest)
    return dest


def _rewrite_meta_npy(npy: bytes, stored_epoch: int) -> bytes:
    """Rebuild a checkpoint's ``__meta__`` npy member (a 1-D uint8 array
    of JSON bytes — train/checkpoint.py's container) with ``epoch``
    replaced by ``stored_epoch``. Stdlib-only npy surgery: parse the
    header to find the payload, edit the JSON, emit a fresh v1.0 header."""
    if npy[:6] != b"\x93NUMPY":
        raise ValueError("checkpoint __meta__ member is not an npy array")
    if npy[6] == 1:
        (hlen,) = struct.unpack_from("<H", npy, 8)
        payload = npy[10 + hlen:]
    else:
        (hlen,) = struct.unpack_from("<I", npy, 8)
        payload = npy[12 + hlen:]
    meta = json.loads(payload.decode())
    meta["epoch"] = stored_epoch
    data = json.dumps(meta).encode()
    header = ("{'descr': '|u1', 'fortran_order': False, "
              f"'shape': ({len(data)},), }}")
    pad = (64 - (10 + len(header) + 1) % 64) % 64
    header_bytes = (header + " " * pad + "\n").encode("latin1")
    return (b"\x93NUMPY\x01\x00" + struct.pack("<H", len(header_bytes))
            + header_bytes + data)


def republish_with_epoch(source: str, dest: str, epoch: int) -> None:
    """Copy checkpoint ``source`` to ``dest`` with its EMBEDDED epoch
    rebased to ``epoch`` (stored as ``epoch + 1``, save_checkpoint's
    resume-at-next convention). The engines' swap-ordering rule trusts
    the meta epoch, not the filename — so rolling BASELINE weights
    forward under a new epoch number requires rewriting the meta, or the
    backend refuses the "older" params and keeps serving the bad ones.
    An npz is a zip of npy members; only ``__meta__.npy`` changes, every
    array member is copied byte-for-byte. Sharded ``.ckpt`` directories
    get the same edit on ``meta.json``; a delta ``.manifest`` is plain
    JSON — same edit, chunk references untouched (the fetchers pull the
    SAME bytes, only the swap-ordering epoch moves). Write-then-replace,
    atomic either way."""
    tmp = dest + ".tmp"
    if source.endswith(".manifest"):
        with open(source) as f:
            meta = json.load(f)
        meta["epoch"] = epoch + 1
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, dest)
        return
    if os.path.isdir(source):
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        shutil.copytree(source, tmp)
        meta_path = os.path.join(tmp, "meta.json")
        with open(meta_path) as f:
            meta = json.load(f)
        meta["epoch"] = epoch + 1
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        if os.path.exists(dest):
            shutil.rmtree(dest)
        os.replace(tmp, dest)
        return
    with zipfile.ZipFile(source) as zin, \
            zipfile.ZipFile(tmp, "w", zipfile.ZIP_STORED) as zout:
        for item in zin.infolist():
            data = zin.read(item.filename)
            if item.filename == "__meta__.npy":
                data = _rewrite_meta_npy(data, epoch + 1)
            zout.writestr(item.filename, data)
    os.replace(tmp, dest)


class HttpRolloutOps:
    """RollingReload's real actuator: router-side drain marking first
    (dispatch stops choosing the backend), then the backend's own
    admission gate, /stats for quiescence, an atomic file copy for
    publish, /healthz for epoch verification."""

    def __init__(self, ctx: "RouterContext", dirs: Dict[str, str],
                 source: str) -> None:
        self.ctx = ctx
        self.dirs = dirs
        self.source = source
        self.published: Dict[str, str] = {}

    def _url(self, name: str) -> str:
        backend = self.ctx.fleet.get(name)
        if backend is None:
            raise RuntimeError(f"backend {name} left the fleet mid-rollout")
        return backend.url

    def drain(self, name: str) -> None:
        # Router first (no NEW dispatches), backend second (stragglers
        # already on the wire get the drain-503 the dispatch loop
        # treats as retry-safe refusal).
        self.ctx.fleet.set_draining(name, True)
        post_json(f"{self._url(name)}/drain", {"drain": True},
                  connect_timeout=self.ctx.connect_timeout,
                  read_timeout=self.ctx.read_timeout)
        self.ctx.event("fleet_rollout_drain", backend=name)

    def active_requests(self, name: str) -> int:
        stats = get_json(f"{self._url(name)}/stats",
                         connect_timeout=self.ctx.connect_timeout,
                         read_timeout=self.ctx.read_timeout)
        return int(stats.get("active_requests", 0)) \
            + int(stats.get("queue_depth", 0))

    def publish(self, name: str) -> None:
        dest_dir = self.dirs[name]
        self.published[name] = atomic_copy(self.source, dest_dir)
        self.ctx.event("fleet_rollout_publish", backend=name,
                       path=self.published[name])

    def epoch(self, name: str) -> Optional[int]:
        info = get_json(f"{self._url(name)}/healthz",
                        connect_timeout=self.ctx.connect_timeout,
                        read_timeout=self.ctx.read_timeout)
        epoch = info.get("model_epoch")
        return None if epoch is None else int(epoch)

    def undrain(self, name: str) -> None:
        try:
            post_json(f"{self._url(name)}/drain", {"drain": False},
                      connect_timeout=self.ctx.connect_timeout,
                      read_timeout=self.ctx.read_timeout)
        finally:
            self.ctx.fleet.set_draining(name, False)
        self.ctx.event("fleet_rollout_rejoin", backend=name)

    def unpublish(self, name: str) -> None:
        """Rollback for a publish that never installed: remove the bad
        file so the watcher's latest resolves back to the baseline."""
        path = self.published.pop(name, None)
        if path is not None and os.path.exists(path):
            os.remove(path)


class RouterContext:
    """Everything one router process owns; built by
    :func:`create_router` and shared with the handlers via the server
    object (the serve/server.py pattern, so tests boot in-process on
    port 0)."""

    def __init__(self, fleet: Fleet, poller: HealthPoller, *,
                 sink=None, connect_timeout: float = 1.0,
                 read_timeout: float = 30.0,
                 drain_timeout_s: float = 30.0,
                 verify_timeout_s: float = 60.0,
                 fleet_autoscaler: Optional[FleetAutoscaler] = None,
                 spawn_template: Optional[str] = None,
                 cache: Optional[ResponseCache] = None) -> None:
        self.fleet = fleet
        self.poller = poller
        self.sink = sink
        self.cache = cache
        self.log = RouterLog()
        self.connect_timeout = float(connect_timeout)
        self.read_timeout = float(read_timeout)
        self.drain_timeout_s = float(drain_timeout_s)
        self.verify_timeout_s = float(verify_timeout_s)
        self.fleet_autoscaler = fleet_autoscaler
        self.spawn_template = spawn_template
        self.t_start = time.time()
        self.canary: Optional[FleetCanary] = None
        self.canary_ops: Optional[HttpRolloutOps] = None
        self.canary_pending: List[str] = []
        self._rollout_lock = threading.Lock()
        self.last_rollout: Optional[dict] = None
        self._scaler_stop = threading.Event()
        self._scaler_thread: Optional[threading.Thread] = None

    # -- events -----------------------------------------------------------

    def event(self, kind: str, **fields) -> None:
        """Router lifecycle line into the PR 3 JSONL sink (quarantines,
        failovers, rollout steps, canary verdicts, fleet scaling). Only
        touched when a sink exists, so the stdlib-only router never
        imports the profiling module (and its jax dependency) without
        --metrics-file."""
        if self.sink is None:
            return
        from pytorch_distributed_mnist_tpu.utils.profiling import (
            record_fleet_event,
        )

        record_fleet_event(self.sink, kind, **fields)

    # -- rollouts ---------------------------------------------------------

    def resolve_dirs(self, names: Sequence[str],
                     overrides: Optional[dict]) -> Dict[str, str]:
        """Each backend's publish directory: an explicit ``dirs`` map in
        the /rollout body wins; otherwise the dirname of the checkpoint
        the backend reported on /healthz. Unresolvable is a loud error
        — publishing into a guessed directory is how fleets eat bad
        deploys."""
        overrides = overrides or {}
        dirs: Dict[str, str] = {}
        missing = []
        for name in names:
            if name in overrides:
                dirs[name] = overrides[name]
                continue
            backend = self.fleet.get(name)
            info = None
            if backend is not None:
                try:
                    info = get_json(f"{backend.url}/healthz",
                                    connect_timeout=self.connect_timeout,
                                    read_timeout=self.read_timeout)
                except Exception:  # noqa: BLE001 - reported below
                    info = None
            checkpoint = (info or {}).get("checkpoint")
            if checkpoint:
                dirs[name] = os.path.dirname(checkpoint)
            else:
                missing.append(name)
        if missing:
            raise ValueError(
                f"cannot resolve a checkpoint directory for {missing} "
                f"(fresh-init backends report none on /healthz); pass "
                f"'dirs': {{\"host:port\": \"/path\"}} in the /rollout "
                f"body")
        return dirs

    def rollout(self, source: str, dir_overrides: Optional[dict] = None,
                backends: Optional[Sequence[str]] = None,
                drain_timeout_s: Optional[float] = None,
                verify_timeout_s: Optional[float] = None) -> dict:
        """The full rolling reload, one backend at a time."""
        if not self._rollout_lock.acquire(blocking=False):
            raise RuntimeError("a rollout is already in progress")
        try:
            target = epoch_of_checkpoint(source)
            if not os.path.exists(source):
                raise ValueError(f"no such checkpoint: {source!r}")
            names = list(backends) if backends else \
                [b.name for b in self.fleet.backends() if b.routable]
            if not names:
                raise ValueError("no routable backends to roll out to")
            ops = HttpRolloutOps(self, self.resolve_dirs(
                names, dir_overrides), source)
            self.event("fleet_rollout_start", target_epoch=target,
                       backends=names)
            result = RollingReload(
                ops,
                drain_timeout_s=drain_timeout_s or self.drain_timeout_s,
                verify_timeout_s=verify_timeout_s or self.verify_timeout_s,
            ).run(names, target)
            self.event("fleet_rollout_done", **{
                k: v for k, v in result.items() if k != "error"})
            self.last_rollout = result
            return result
        finally:
            self._rollout_lock.release()

    def canary_rollout(self, source: str, canary_spec: dict,
                       dir_overrides: Optional[dict] = None,
                       drain_timeout_s: Optional[float] = None,
                       verify_timeout_s: Optional[float] = None) -> dict:
        """Publish to the canary cohort's backends only, then hand
        routing the deterministic client split. The verdict (note_result
        / fail) later promotes to the rest of the fleet or rolls the
        canary backends back."""
        if not self._rollout_lock.acquire(blocking=False):
            raise RuntimeError("a rollout is already in progress")
        try:
            if self.canary is not None and self.canary.state == SHADOW:
                raise RuntimeError("a fleet canary is already active")
            target = epoch_of_checkpoint(source)
            if not os.path.exists(source):
                raise ValueError(f"no such checkpoint: {source!r}")
            fraction = float(canary_spec.get("fraction", 0.25))
            promote_after = int(canary_spec.get("promote_after", 200))
            budget = float(canary_spec.get("budget", 0.02))
            all_names = [b.name for b in self.fleet.backends()
                         if b.routable]
            if len(all_names) < 2:
                raise ValueError(
                    "a fleet canary needs >= 2 routable backends (one "
                    "cohort on each epoch)")
            canary_names = list(canary_spec.get("backends") or
                                all_names[:1])
            rest = [n for n in all_names if n not in canary_names]
            if not rest:
                raise ValueError("the canary cohort covers every "
                                 "backend; nothing left on the baseline")
            dirs = self.resolve_dirs(all_names, dir_overrides)
            ops = HttpRolloutOps(self, dirs, source)
            # The baseline epoch anchors the rollback (which weights to
            # republish), so read it LIVE from the backend — the
            # poller's cached view can lag a just-finished rollout by
            # one sweep, and a stale/None baseline would turn a budget
            # rollback into a bare unpublish.
            try:
                baseline_epoch = ops.epoch(canary_names[0])
            except Exception:  # noqa: BLE001 - cache fallback
                backend = self.fleet.get(canary_names[0])
                baseline_epoch = backend.epoch if backend else None
            canary = FleetCanary(fraction, canary_names, target,
                                 baseline_epoch,
                                 promote_after=promote_after,
                                 budget=budget)
            self.event("fleet_canary_start", target_epoch=target,
                       backends=canary_names, fraction=fraction)
            result = RollingReload(
                ops,
                drain_timeout_s=drain_timeout_s or self.drain_timeout_s,
                verify_timeout_s=verify_timeout_s or self.verify_timeout_s,
            ).run(canary_names, target)
            if not result["ok"]:
                # The publish never installed (corrupt file, wrong
                # layout — the watcher refused it): auto-rollback is
                # just removing the bad file; the baseline epoch was
                # serving the whole time.
                canary.fail()
                for name in canary_names:
                    ops.unpublish(name)
                self.canary = canary
                self.event("fleet_canary_rollback",
                           target_epoch=target, install_failed=True,
                           **{k: v for k, v in result.items()
                              if k in ("failed", "error")})
                return {"ok": False, "canary": canary.snapshot(),
                        "rollout": result}
            self.canary = canary
            self.canary_ops = ops
            self.canary_pending = rest
            return {"ok": True, "canary": canary.snapshot(),
                    "rollout": result}
        finally:
            self._rollout_lock.release()

    def canary_verdict(self, verdict: str) -> None:
        """Act on a flipped canary verdict on a worker thread (the
        deciding row's handler must not pay the follow-up rollout)."""
        threading.Thread(target=self._apply_verdict, args=(verdict,),
                         daemon=True, name="router-canary").start()

    def _apply_verdict(self, verdict: str) -> None:
        canary, ops = self.canary, self.canary_ops
        if canary is None or ops is None:
            return
        try:
            if verdict == "promote":
                self.event("fleet_canary_promote",
                           target_epoch=canary.target_epoch)
                pending = list(self.canary_pending)
                result = RollingReload(
                    ops, drain_timeout_s=self.drain_timeout_s,
                    verify_timeout_s=self.verify_timeout_s,
                ).run(pending, canary.target_epoch)
                self.last_rollout = result
            else:
                # Budget rollback after a successful install: epochs
                # only move forward (the engines' swap-ordering rule
                # refuses older params), so restoring the baseline is a
                # roll-forward republish of the BASELINE WEIGHTS as
                # target_epoch + 1, plus removing the bad file. Epochs
                # are publish sequence numbers, not identities — the
                # canary block records which weights each one carries.
                self.event("fleet_canary_rollback",
                           target_epoch=canary.target_epoch,
                           install_failed=False)
                for name in canary.backends:
                    backend = self.fleet.get(name)
                    if backend is None:
                        continue
                    try:
                        self._restore_baseline(name, ops, canary)
                    except Exception as exc:  # noqa: BLE001 - keep restoring the rest
                        self.event("fleet_canary_restore_failed",
                                   backend=name, error=repr(exc))
        finally:
            self.canary_ops = None
            self.canary_pending = []

    def _restore_baseline(self, name: str, ops: HttpRolloutOps,
                          canary: FleetCanary) -> None:
        dest_dir = ops.dirs[name]
        if canary.baseline_epoch is None:
            ops.unpublish(name)
            return
        baseline = None
        for fname in os.listdir(dest_dir):
            match = _EPOCH_RE.search(fname)
            if match and int(match.group(1)) == canary.baseline_epoch:
                baseline = os.path.join(dest_dir, fname)
                break
        ops.unpublish(name)
        if baseline is None:
            return
        ext = os.path.splitext(baseline)[1]
        restored = os.path.join(
            dest_dir, f"checkpoint_{canary.target_epoch + 1}{ext}")
        republish_with_epoch(baseline, restored, canary.target_epoch + 1)
        self.event("fleet_canary_restored", backend=name,
                   weights_epoch=canary.baseline_epoch,
                   published_as=canary.target_epoch + 1)

    # -- fleet autoscaling ------------------------------------------------

    def spawn_backend(self) -> bool:
        """Start one backend process from --spawn-backend's argv
        template (port forced to 0), parse its "serving on" line, and
        admit it on PROBATION — a fresh process earns HEALTHY the same
        way a healed one does."""
        if not self.spawn_template:
            return False
        argv = [sys.executable, "-m", "pytorch_distributed_mnist_tpu",
                *shlex.split(self.spawn_template), "--port", "0"]
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        url = None
        try:
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                line = proc.stdout.readline() if proc.stdout else ""
                if not line:
                    if proc.poll() is not None:
                        break
                    continue
                match = re.search(r"serving on (http://\S+)", line)
                if match:
                    url = match.group(1)
                    break
        finally:
            # No URL — deadline, early exit, or an exception while
            # parsing — means nobody will ever own this process: kill
            # AND wait here, or the half-booted backend leaks (kill
            # without wait still leaves a zombie holding its chips).
            if url is None:
                proc.kill()
                proc.wait()
        if url is None:
            self.event("fleet_scale_up_failed")
            return False
        # The spawned process keeps writing to stdout; drain it on a
        # reaper thread so the pipe never fills and blocks serving.
        threading.Thread(target=lambda: [None for _ in proc.stdout],
                         daemon=True, name="router-spawn-drain").start()
        backend = self.fleet.add(url, spawned=True, proc=proc)
        self.fleet.admit_probation(backend.name)
        self.event("fleet_scale_up", backend=backend.name)
        return True

    def stop_backend(self) -> bool:
        """Scale down: drain the least-loaded SPAWNED backend (static
        --backends members are the operator's; the scaler only reaps
        what it sowed), wait for quiescence, terminate, remove."""
        spawned = [b for b in self.fleet.spawned_backends() if b.routable]
        if not spawned:
            return False
        victim = min(spawned, key=lambda b: (b.total_inflight, b.name))
        self.fleet.set_draining(victim.name, True)
        try:
            post_json(f"{victim.url}/drain", {"drain": True},
                      connect_timeout=self.connect_timeout,
                      read_timeout=self.read_timeout)
            deadline = time.monotonic() + self.drain_timeout_s
            while time.monotonic() < deadline:
                stats = get_json(f"{victim.url}/stats",
                                 connect_timeout=self.connect_timeout,
                                 read_timeout=self.read_timeout)
                if not stats.get("active_requests", 0) and \
                        not stats.get("queue_depth", 0):
                    break
                time.sleep(0.05)
        except Exception:  # noqa: BLE001 - a dead victim still gets reaped
            pass
        if victim.proc is not None:
            victim.proc.terminate()
            try:
                victim.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                victim.proc.kill()
        self.fleet.remove(victim.name)
        self.event("fleet_scale_down", backend=victim.name)
        return True

    def scaler_tick(self) -> Optional[str]:
        """One fleet-autoscaler control step: merged window from the
        routable backends' /stats, a pure decide(), then the actuator."""
        scaler = self.fleet_autoscaler
        if scaler is None:
            return None
        windows = []
        for backend in self.fleet.backends():
            if not backend.routable:
                continue
            try:
                stats = get_json(f"{backend.url}/stats",
                                 connect_timeout=self.connect_timeout,
                                 read_timeout=self.read_timeout)
                windows.append(stats.get("window"))
            except Exception:  # noqa: BLE001 - the poller owns health accounting
                continue
        action = scaler.decide(self.fleet.n_routable(),
                               merge_windows(windows), time.monotonic())
        if action == "up" and not scaler.dry_run:
            scaler.start_fn()
        elif action == "down" and not scaler.dry_run:
            scaler.stop_fn()
        return action

    def start_scaler(self, interval_s: float) -> None:
        if self.fleet_autoscaler is None or self._scaler_thread:
            return

        def _loop():
            while not self._scaler_stop.wait(interval_s):
                try:
                    self.scaler_tick()
                except Exception as exc:  # noqa: BLE001 - scaler never dies
                    print(f"fleet autoscaler: tick failed: {exc!r}",
                          flush=True)

        self._scaler_thread = threading.Thread(
            target=_loop, daemon=True, name="router-fleet-scaler")
        self._scaler_thread.start()

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        self._scaler_stop.set()
        if self._scaler_thread is not None:
            self._scaler_thread.join()
            self._scaler_thread = None
        self.poller.stop()
        for backend in self.fleet.spawned_backends():
            if backend.proc is not None:
                backend.proc.terminate()


class _RouterHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
        pass

    @property
    def ctx(self) -> RouterContext:
        return self.server.ctx  # type: ignore[attr-defined]

    def _reply(self, code: int, payload: dict,
               headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                self.send_header(name, str(value))
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            pass  # client gave up; same contract as the backend server

    def _reply_raw(self, code: int, body: bytes,
                   headers: Optional[dict] = None) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                self.send_header(name, str(value))
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            pass

    # -- GET --------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 - stdlib name
        ctx = self.ctx
        if self.path == "/healthz":
            rows = ctx.fleet.snapshot_rows()
            routable = sum(1 for r in rows if r["routable"])
            self._reply(200 if routable else 503, {
                "ok": routable > 0,
                "role": "router",
                "backends": {r["name"]: r["state"] for r in rows},
                "routable": routable,
                "total": len(rows),
                "uptime_s": round(time.time() - ctx.t_start, 3),
            })
        elif self.path == "/stats":
            self._reply(200, self._stats())
        else:
            self._reply(404, {"error": f"no route {self.path!r}"})

    def _stats(self) -> dict:
        """The aggregated fleet view: per-backend rows (the router's
        cached health/load state joined with a live /stats fetch from
        each routable backend) plus fleet quantiles merged from the
        rolling-window blocks."""
        ctx = self.ctx
        rows = ctx.fleet.snapshot_rows()
        windows = []
        for row in rows:
            if not row["routable"]:
                continue
            backend = ctx.fleet.get(row["name"])
            if backend is None:
                continue
            try:
                stats = get_json(f"{backend.url}/stats",
                                 connect_timeout=ctx.connect_timeout,
                                 read_timeout=ctx.read_timeout)
            except Exception as exc:  # noqa: BLE001 - a row, not a failure
                row["stats_error"] = classify_failure(exc)
                continue
            row["window"] = stats.get("window")
            row["active_requests"] = stats.get("active_requests")
            row["queue_depth"] = stats.get("queue_depth")
            row["counts"] = stats.get("counts")
            windows.append(stats.get("window"))
        out = {
            "role": "router",
            "router": ctx.log.snapshot(),
            "backends": rows,
            "fleet": {
                "routable": sum(1 for r in rows if r["routable"]),
                "total": len(rows),
                "failovers": ctx.fleet.failovers,
                "retries": ctx.fleet.retries,
                "fleet_503s": ctx.fleet.fleet_503s,
                "window": merge_windows(windows),
            },
            "health_poller": ctx.poller.snapshot(),
        }
        if ctx.cache is not None and ctx.cache.enabled:
            out["cache"] = ctx.cache.snapshot()
        if ctx.canary is not None:
            out["fleet_canary"] = ctx.canary.snapshot()
        if ctx.last_rollout is not None:
            out["last_rollout"] = ctx.last_rollout
        if ctx.fleet_autoscaler is not None:
            out["fleet_autoscaler"] = ctx.fleet_autoscaler.snapshot()
        return out

    # -- POST -------------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802 - stdlib name
        if self.path == "/rollout":
            self._do_rollout()
            return
        if self.path != "/predict":
            self._reply(404, {"error": f"no route {self.path!r}"})
            return
        self._do_predict()

    def _do_predict(self) -> None:
        ctx = self.ctx
        t0 = time.perf_counter()
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_BODY_BYTES:
            self._reply(413, {"error": f"body over {MAX_BODY_BYTES} "
                                       f"bytes; batch client-side"})
            return
        raw = self.rfile.read(length) if length else b"{}"
        # Routing fields only — the body is NOT validated here (the
        # backend is the authority on images/priority vocabulary; a
        # router that second-guesses it would have to track every
        # backend schema change). A malformed body still routes and
        # comes back 400 from the backend.
        model = klass = client_id = None
        try:
            peek = json.loads(raw)
            if isinstance(peek, dict):
                model = peek.get("model")
                klass = peek.get("priority") or None
                cid = peek.get("client_id")
                client_id = cid if isinstance(cid, str) else None
        except (ValueError, TypeError):
            pass
        canary = ctx.canary
        within = None
        is_canary_row = False
        if canary is not None and canary.state == SHADOW:
            cohort = set(canary.backends)
            if canary.wants(client_id):
                within, is_canary_row = cohort, True
            else:
                # The baseline cohort must NOT land on a canary backend
                # (its reply would carry the unjudged epoch).
                within = {b.name for b in ctx.fleet.backends()
                          if b.name not in cohort}
        # Router response cache (request-path economics, the same keyed
        # cache as the backends'): exact-byte repeats replay the cached
        # 200 body without a dispatch. Disabled for the duration of a
        # fleet canary SHADOW — cohort replies carry the unjudged
        # epoch, and a cache would leak them across cohorts.
        cache = ctx.cache if ctx.cache is not None and ctx.cache.enabled \
            and within is None else None
        ckey, gen = None, 0
        if cache is not None:
            ckey = request_key(raw, model, "fleet", "route")
            hit_body, _hit_epoch, gen = cache.get(ckey)
            if hit_body is not None:
                ctx.log.record(time.perf_counter() - t0, 200, klass)
                self._reply_raw(200, hit_body,
                                headers={"X-Cache": "hit"})
                return
        exclude: Set[str] = set()
        attempt = 0
        while True:
            backend = ctx.fleet.acquire(model=model, klass=klass,
                                        client_id=client_id,
                                        exclude=exclude, within=within)
            if backend is None and within is not None:
                # Cohort empty (canary backends all died): availability
                # beats the experiment — fall back to the whole fleet.
                backend = ctx.fleet.acquire(model=model, klass=klass,
                                            client_id=client_id,
                                            exclude=exclude)
            if backend is None:
                # The loud fleet-wide 503: ZERO routable backends (or
                # all excluded by a failed retry). Nothing quieter is
                # honest — there is no capacity to shed toward.
                ctx.fleet.fleet_503s += 1
                ctx.log.record(time.perf_counter() - t0, 503, klass)
                ctx.event("fleet_503", model=model,
                          excluded=sorted(exclude))
                self._reply(
                    503,
                    {"error": "no routable backends in the fleet",
                     "fleet": {r["name"]: r["state"]
                               for r in ctx.fleet.snapshot_rows()},
                     "retry_after_s": 1.0},
                    headers={"Retry-After": 1})
                return
            try:
                status, headers, body = http_exchange(
                    f"{backend.url}/predict", method="POST", body=raw,
                    connect_timeout=ctx.connect_timeout,
                    read_timeout=ctx.read_timeout)
            except TransportError as err:
                ctx.fleet.release(backend, klass)
                reason = classify_failure(err)
                ctx.fleet.note_failure(backend.name, reason)
                if is_canary_row:
                    self._note_canary(False)
                if attempt == 0 and retry_safe(err):
                    attempt += 1
                    exclude.add(backend.name)
                    ctx.fleet.retries += 1
                    ctx.fleet.failovers += 1
                    ctx.event("fleet_failover", backend=backend.name,
                              reason=reason)
                    continue
                ctx.log.record(time.perf_counter() - t0, 502, klass)
                self._reply(502, {
                    "error": f"backend {backend.name} failed: {reason}",
                    "backend": backend.name,
                    "retried": attempt > 0})
                return
            ctx.fleet.release(backend, klass)
            if status == 503 and attempt == 0 and b'"draining"' in body:
                # The backend's drain gate REFUSED the request before
                # any work — a proof of non-execution as strong as
                # connection-refused, so the one-retry budget applies.
                # (An overload 503 is different: it must pass through —
                # retrying it just moves the overload sideways.)
                attempt += 1
                exclude.add(backend.name)
                ctx.fleet.retries += 1
                ctx.event("fleet_drain_retry", backend=backend.name)
                continue
            ctx.fleet.note_success(backend.name)
            if is_canary_row:
                self._note_canary(status < 500)
            ctx.log.record(time.perf_counter() - t0, status, klass)
            passthrough = {}
            if "Retry-After" in headers:
                # 503/429 back-pressure contracts pass through
                # UNTOUCHED: the backend derived Retry-After from its
                # measured drain rate and the router has no better
                # information.
                passthrough["Retry-After"] = headers["Retry-After"]
            if cache is not None and status == 200:
                # Insert stamped with the probe-time generation: a
                # backend epoch change the poller observed mid-flight
                # bumped it, and put() drops this (possibly-stale)
                # body instead of installing it.
                cache.put(ckey, body, len(body) + 64,
                          epoch=backend.epoch, generation=gen)
                passthrough["X-Cache"] = "miss"
            self._reply_raw(status, body, headers=passthrough)
            return

    def _note_canary(self, ok: bool) -> None:
        canary = self.ctx.canary
        if canary is None:
            return
        verdict = canary.note_result(ok)
        if verdict is not None:
            self.ctx.event("fleet_canary_verdict", verdict=verdict,
                           **{k: canary.snapshot()[k] for k in
                              ("compared_rows", "disagreed_rows")})
            self.ctx.canary_verdict(verdict)

    def _do_rollout(self) -> None:
        """``POST /rollout`` — body ``{"source": checkpoint_path,
        "dirs": {name: dir}?, "backends": [name]?, "canary":
        {"fraction": f?, "promote_after": n?, "budget": b?,
        "backends": [name]?}?}``. Without ``canary``: the full rolling
        reload, synchronous. With it: publish to the cohort and return;
        the verdict promotes or rolls back in the background."""
        ctx = self.ctx
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_BODY_BYTES:
            self._reply(413, {"error": "oversized /rollout body"})
            return
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict) or not payload.get("source"):
                raise ValueError(
                    "body must be JSON {\"source\": checkpoint_path, "
                    "...}")
        except (ValueError, TypeError, json.JSONDecodeError) as exc:
            self._reply(400, {"error": str(exc)})
            return
        try:
            kwargs = {
                "dir_overrides": payload.get("dirs"),
                "drain_timeout_s": payload.get("drain_timeout_s"),
                "verify_timeout_s": payload.get("verify_timeout_s"),
            }
            if payload.get("canary"):
                result = ctx.canary_rollout(payload["source"],
                                            payload["canary"], **kwargs)
            else:
                result = ctx.rollout(payload["source"],
                                     backends=payload.get("backends"),
                                     **kwargs)
        except (ValueError,) as exc:
            self._reply(400, {"error": str(exc)})
            return
        except RuntimeError as exc:
            self._reply(409, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 - an admin op never kills routing
            self._reply(500, {"error": repr(exc)})
            return
        self._reply(200 if result.get("ok") else 502, result)


class _RouterServer(ThreadingHTTPServer):
    # Same rationale as the backend server: bursts must reach the
    # router's dispatch (which has a whole fleet to absorb them), not
    # die as kernel-level connection-refused at backlog 5.
    request_queue_size = 128


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-mnist route",
        description="Fleet federation: route /predict over N backend "
                    "serve processes with health-gated failover, "
                    "rolling deploys, fleet canaries, and two-tier "
                    "autoscaling.")
    p.add_argument("--backends", type=str, default="",
                   help="comma-separated host:port list of backend "
                        "serve processes (the static fleet; the health "
                        "poller owns their state from here on)")
    p.add_argument("--backends-dir", type=str, default=None,
                   metavar="DIR",
                   help="dynamic discovery: watch DIR for backend_*.json "
                        "records written by serve processes started with "
                        "--register-dir DIR; new records join the fleet "
                        "on probation, vanished records leave (static "
                        "--backends members are never reaped). Composes "
                        "with --backends")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8100,
                   help="router port (0 = ephemeral). Default 8100")
    p.add_argument("--health-interval", type=float, default=0.5,
                   help="seconds between /healthz sweeps. Default 0.5")
    p.add_argument("--quarantine-after", type=int, default=3,
                   help="consecutive probe/dispatch failures before a "
                        "backend is quarantined (not routed, still "
                        "probed). Default 3")
    p.add_argument("--probation-successes", type=int, default=3,
                   help="consecutive successes a re-admitted backend "
                        "needs on probation before it is HEALTHY again "
                        "(one failure on probation re-quarantines). "
                        "Default 3")
    p.add_argument("--connect-timeout", type=float, default=1.0,
                   help="per-request backend connect timeout (seconds); "
                        "refusal inside it is the retry-safe failure. "
                        "Default 1.0")
    p.add_argument("--read-timeout", type=float, default=30.0,
                   help="per-request backend read timeout (seconds); a "
                        "timeout is NEVER retried (the backend may be "
                        "executing). Default 30")
    p.add_argument("--hash-replicas", type=int, default=64,
                   help="points per backend on the consistent-hash "
                        "affinity ring. Default 64")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="rollout: max wait for a drained backend's "
                        "in-flight to reach zero. Default 30")
    p.add_argument("--verify-timeout-s", type=float, default=60.0,
                   help="rollout: max wait for a published epoch to be "
                        "serving on /healthz. Default 60")
    p.add_argument("--fleet-min", type=int, default=0,
                   help="fleet autoscaler floor (backend processes); 0 "
                        "disables the fleet autoscaler. Default 0")
    p.add_argument("--fleet-max", type=int, default=0,
                   help="fleet autoscaler ceiling; required with "
                        "--fleet-min")
    p.add_argument("--fleet-slo-p95-ms", type=float, default=100.0,
                   help="merged fleet p95 above which the fleet scales "
                        "UP a backend process. Default 100")
    p.add_argument("--fleet-interval-s", type=float, default=2.0,
                   help="fleet autoscaler control period. Default 2")
    p.add_argument("--fleet-cooldown-s", type=float, default=10.0,
                   help="min seconds between fleet scale actions. "
                        "Default 10")
    p.add_argument("--fleet-down-after", type=int, default=3,
                   help="consecutive calm ticks before a scale-down. "
                        "Default 3")
    p.add_argument("--spawn-backend", type=str, default=None,
                   metavar="ARGS",
                   help="argv template for scale-up, e.g. 'serve "
                        "--model linear --checkpoint-dir /ckpt' (the "
                        "router appends --port 0 and parses the bound "
                        "port). Without it --fleet-min/max only RECORD "
                        "decisions (dry run)")
    p.add_argument("--cache-mb", type=float, default=0.0,
                   help="router response-cache byte budget in MB "
                        "(bounded LRU, same keyed cache as the "
                        "backends'): an exact-byte repeat of a routed "
                        "/predict replays the cached 200 body without "
                        "a backend dispatch; ANY backend epoch change "
                        "the health poller observes invalidates every "
                        "entry in O(1). Default 0 = DISABLED: a "
                        "router cache also starves the per-backend "
                        "load signal the fleet tier routes on, so it "
                        "is an explicit opt-in (the backends' own "
                        "caches already absorb duplicates fleet-wide)")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the router response cache")
    p.add_argument("--metrics-file", type=str, default=None,
                   help="append router JSONL events (quarantines, "
                        "failovers, rollout steps, canary verdicts, "
                        "scale actions) to this file via the shared "
                        "profiling sink")
    return p


def create_router(args) -> ThreadingHTTPServer:
    """Build the fleet + poller (+ autoscaler) and bind the router
    socket (bound, not serving — callers run serve_forever, so tests
    boot on port 0 in-process). ``server.ctx.close()`` tears it down."""
    backends = [tok.strip() for tok in (args.backends or "").split(",")
                if tok.strip()]
    backends_dir = getattr(args, "backends_dir", None)
    if not backends and not backends_dir \
            and not (args.fleet_min and args.spawn_backend):
        raise SystemExit(
            "--backends host:port,... is required (or --backends-dir "
            "DIR for dynamic discovery, or --fleet-min N with "
            "--spawn-backend to boot an all-spawned fleet)")
    sink = None
    if getattr(args, "metrics_file", None):
        from pytorch_distributed_mnist_tpu.utils.profiling import JsonlSink

        sink = JsonlSink(args.metrics_file)

    ctx_ref: List[RouterContext] = []

    def _emit(kind: str, **fields) -> None:
        if ctx_ref:
            ctx_ref[0].event(kind, **fields)

    cache_mb = float(getattr(args, "cache_mb", 64.0) or 0.0)
    if getattr(args, "no_cache", False) or cache_mb < 0:
        cache_mb = 0.0
    cache = ResponseCache(int(cache_mb * (1 << 20)))
    fleet = Fleet(quarantine_after=args.quarantine_after,
                  probation_successes=args.probation_successes,
                  hash_replicas=args.hash_replicas, on_event=_emit,
                  cache=cache if cache.enabled else None)
    for url in backends:
        fleet.add(url)
    poller = HealthPoller(fleet, interval_s=args.health_interval,
                          connect_timeout=args.connect_timeout,
                          read_timeout=max(2.0, args.connect_timeout),
                          backends_dir=backends_dir)
    scaler = None
    if args.fleet_min:
        if not args.fleet_max:
            raise SystemExit("--fleet-min requires --fleet-max")
        scaler = FleetAutoscaler(
            args.fleet_min, args.fleet_max,
            slo_p95_ms=args.fleet_slo_p95_ms,
            cooldown_s=args.fleet_cooldown_s,
            down_after=args.fleet_down_after)
    ctx = RouterContext(
        fleet, poller, sink=sink,
        connect_timeout=args.connect_timeout,
        read_timeout=args.read_timeout,
        drain_timeout_s=args.drain_timeout_s,
        verify_timeout_s=args.verify_timeout_s,
        fleet_autoscaler=scaler,
        spawn_template=args.spawn_backend,
        cache=cache if cache.enabled else None)
    ctx_ref.append(ctx)
    if scaler is not None and args.spawn_backend:
        scaler.start_fn = ctx.spawn_backend
        scaler.stop_fn = ctx.stop_backend
        scaler.dry_run = False
    poller.start()
    if scaler is not None:
        ctx.start_scaler(args.fleet_interval_s)
    httpd = _RouterServer((args.host, args.port), _RouterHandler)
    httpd.daemon_threads = True
    httpd.ctx = ctx  # type: ignore[attr-defined]
    return httpd


def main(argv: Optional[list] = None) -> None:
    args = build_parser().parse_args(argv)
    httpd = create_router(args)
    host, port = httpd.server_address[:2]
    n = len(httpd.ctx.fleet.names())  # type: ignore[attr-defined]
    print(f"routing on http://{host}:{port}  "
          f"({n} backend(s); /predict, /healthz, /stats, /rollout)",
          flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        print("router shutting down", flush=True)
    finally:
        httpd.ctx.close()  # type: ignore[attr-defined]
        httpd.server_close()


if __name__ == "__main__":
    main()
