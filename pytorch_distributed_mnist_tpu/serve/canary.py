"""Shadow-traffic accuracy canary: "is int8 safe" as a production control.

A quantized serving plane (``serve/programs.py``'s precision axis) is an
accuracy claim as much as a speed claim — and offline sweeps validate it
against yesterday's checkpoint, not the one the fleet hot-reloaded five
minutes ago. The canary turns the claim into a per-publish control loop:

- **Shadow.** The BASELINE (f32) plane answers every request; a
  configurable fraction of live batches is ADDITIONALLY dispatched to
  the quantized CANDIDATE plane. Both planes ride JAX async dispatch,
  so the shadow forward overlaps the baseline's — the client pays one
  result fetch, not two serial forwards. On completion the two logit
  sets are compared: per-row argmax disagreements and per-row max
  |Δlogit| accumulate (``/stats``' ``canary`` block), and the reply is
  ALWAYS the baseline's — a broken candidate can cost nothing but its
  own shadow work.
- **Promote.** After ``promote_after`` shadowed rows with disagreements
  inside the budget, the candidate becomes PRIMARY: dispatch routes to
  the quantized plane alone and the throughput/HBM win materializes.
  In-flight batches complete on the plane that dispatched them.
- **Roll back.** The budget is ``budget * promote_after`` disagreeing
  rows (shadow-plane ERRORS count too — a crashing candidate must never
  promote). Exceeding it rolls the canary back: the baseline keeps
  answering, the candidate goes idle, and the decision is PERMANENT FOR
  THAT PUBLISH — no flapping retry against weights already judged bad.
  The server keeps serving throughout; rollback is a routing decision,
  never an outage.
- **Reset per publish.** The reload watcher's one callback
  (``swap_params`` — the same ``CheckpointWatcher(validate_fn=)`` path
  every plane reloads through) fans the new f32 params to BOTH planes
  (each quantizes at install, per the precision contract) and restarts
  the cycle at SHADOW: every publish re-earns promotion.

Transitions land as ``serve_canary`` JSONL events in the shared
``--metrics-file`` stream (the PR 3 sink, via
``ServeLog.record_pool_event``) and as counters in ``/stats``.

The canary deliberately does NOT invent a data plane: baseline and
candidate are ordinary engines/pools — the PR 10 quarantine/failover/
regroup machinery heals each side independently, and the pool surface
(``dispatch``/``complete``/``swap_params``/``warmup``) is all the canary
touches. ``TPUMNIST_CANARY_FAULT=disagree`` is the chaos-harness hook:
every shadow comparison counts as disagreement, rehearsing the
rollback-under-traffic scenario (``tools/chaos.py --canary-rollback``).
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Optional, Tuple

import numpy as np

# Chaos/e2e-test injection: "disagree" (or "1") makes every shadow
# comparison count as a full disagreement — the single-process stand-in
# for a quantized publish whose accuracy really did regress.
CANARY_FAULT_ENV = "TPUMNIST_CANARY_FAULT"

SHADOW = "shadow"
PRIMARY = "primary"
ROLLED_BACK = "rolled_back"


def _dispatch(plane, images):
    """One dispatch against either data-plane surface: a pool's
    ``dispatch`` or a bare engine's ``dispatch_logits`` (both enqueue
    without waiting and pair with ``plane.complete(handle)``)."""
    fn = getattr(plane, "dispatch", None)
    if fn is not None:
        return fn(images)
    return plane.dispatch_logits(images)


class _CanaryHandle:
    """One dispatched batch: the handle whose plane ANSWERS, plus the
    shadow handle (when this batch was sampled) — completion compares
    the two and the reply never waits on anything but its own plane's
    fetch ordering."""

    __slots__ = ("reply", "reply_plane", "shadow")

    def __init__(self, reply, reply_plane: str, shadow=None) -> None:
        self.reply = reply
        self.reply_plane = reply_plane  # "baseline" | "candidate"
        self.shadow = shadow


class ShadowCanary:
    """Routes traffic between a baseline (f32) plane and a quantized
    candidate plane per the state machine in the module docstring.

    Exposes the engine-compatible surface the server's handlers,
    batcher, and reload watcher use (``dispatch``/``complete``/
    ``predict_complete``/``swap_params``/``warmup``/``preprocess``/
    ``buckets``/``max_batch``/``params_epoch``), so it drops in wherever
    one engine or pool did. Counter mutation and state transitions run
    under one lock; device work (dispatch enqueues, completion fetches)
    and event emission always run outside it.
    """

    def __init__(self, baseline, candidate, precision: str,
                 fraction: float = 0.1, promote_after: int = 200,
                 budget: float = 0.02, serve_log=None,
                 max_delta_samples: int = 4096) -> None:
        if not (0.0 < fraction <= 1.0):
            raise ValueError(
                f"canary fraction must be in (0, 1], got {fraction}")
        if promote_after < 1:
            raise ValueError(
                f"canary promote_after must be >= 1, got {promote_after}")
        if budget < 0.0:
            raise ValueError(f"canary budget must be >= 0, got {budget}")
        self.baseline = baseline
        self.candidate = candidate
        self.precision = precision
        self.fraction = float(fraction)
        self.promote_after = int(promote_after)
        self.budget = float(budget)
        self.serve_log = serve_log
        # Disagreement allowance per promotion window, in ROWS: blowing
        # it rolls back immediately, staying inside it for promote_after
        # rows promotes.
        self._allowed = self.budget * self.promote_after
        self._injected = os.environ.get(
            CANARY_FAULT_ENV, "").strip().lower() in ("1", "disagree")
        self._lock = threading.Lock()
        self._state = SHADOW
        self._acc = 0.0  # deterministic fraction sampler (no RNG)
        self._publishes = 0
        self._promotions = 0
        self._rollbacks = 0
        # Swap hooks (ISSUE 19): run under the canary lock on every
        # publish reset AND on a promotion — the two transitions that
        # change what a repeated request would be answered with
        # (rollback keeps the baseline answering, so it needs no
        # invalidation). O(1) arithmetic only.
        self._swap_hooks = []
        self._deltas = collections.deque(maxlen=max_delta_samples)
        self._reset_counters_locked()

    def _reset_counters_locked(self) -> None:
        self._shadow_batches = 0
        self._compared_rows = 0
        self._disagreed_rows = 0
        self._shadow_errors = 0
        self._skewed = 0
        self._acc = 0.0
        self._deltas.clear()

    # -- engine-compatible surface ----------------------------------------

    @property
    def buckets(self):
        return self.baseline.buckets

    @property
    def max_batch(self) -> int:
        return self.baseline.max_batch

    @property
    def params_epoch(self) -> Optional[int]:
        """The serving epoch of the plane currently ANSWERING."""
        with self._lock:
            plane = self.candidate if self._state == PRIMARY \
                else self.baseline
        return plane.params_epoch

    def preprocess(self, images) -> np.ndarray:
        return self.baseline.preprocess(images)

    def warmup(self) -> None:
        """AOT-warm BOTH planes before the socket opens: a shadowed or
        newly-promoted batch must never pay a compile either."""
        self.baseline.warmup()
        self.candidate.warmup()

    def swap_params(self, params, epoch: Optional[int] = None,
                    path: Optional[str] = None):
        """The reload watcher's one callback, fanned to both planes (each
        applies its own install-time quantization and swap-ordering
        rule), then the canary cycle RESETS to shadow: a new publish —
        including one arriving after a rollback — re-earns promotion
        from zero. Returns the baseline's install result (the watcher's
        staleness contract follows the plane that answers by default)."""
        installed = self.baseline.swap_params(params, epoch=epoch, path=path)
        cand_installed = self.candidate.swap_params(params, epoch=epoch,
                                                    path=path)
        if not installed and not cand_installed:
            # Both planes refused the publish as STALE (the engines'
            # swap-ordering rule): nothing changed, so nothing re-earns
            # — resetting here would silently demote a promoted
            # candidate over a checkpoint that never served.
            return installed
        with self._lock:
            prev = self._state
            self._state = SHADOW
            self._publishes += 1
            self._reset_counters_locked()
            for hook in self._swap_hooks:
                hook(epoch)
        self._record_event("reset", previous_state=prev, epoch=epoch)
        return installed

    def add_swap_hook(self, hook) -> None:
        """Register ``hook(epoch)`` to run under the canary lock on each
        publish reset and on promotion (the response cache's
        ``bump_generation`` seam — O(1) arithmetic only)."""
        with self._lock:
            self._swap_hooks.append(hook)

    # -- dispatch / complete ----------------------------------------------

    def dispatch(self, images) -> _CanaryHandle:
        """Route one formed batch: the current PRIMARY plane answers;
        in shadow state, a ``fraction`` of batches additionally dispatch
        on the candidate (sampled by a deterministic accumulator — exact
        rate, no RNG). A candidate dispatch failure is contained here
        and counted against the budget: the client's reply never depends
        on the candidate."""
        with self._lock:
            state = self._state
            shadow = False
            if state == SHADOW:
                self._acc += self.fraction
                if self._acc >= 1.0 - 1e-9:
                    self._acc -= 1.0
                    shadow = True
                    self._shadow_batches += 1
        if state == PRIMARY:
            return _CanaryHandle(_dispatch(self.candidate, images),
                                 "candidate")
        reply = _dispatch(self.baseline, images)
        shadow_handle = None
        if shadow:
            try:
                shadow_handle = _dispatch(self.candidate, images)
            except Exception as exc:  # noqa: BLE001 - shadow must not fail the reply
                self._note_shadow_error(int(np.shape(images)[0]), exc)
        return _CanaryHandle(reply, "baseline", shadow_handle)

    def complete(self, handle: _CanaryHandle) \
            -> Tuple[np.ndarray, Optional[int]]:
        """Fetch the answering plane's logits; when this batch carried a
        shadow, fetch and judge the candidate's too (the shadow forward
        ran CONCURRENTLY under async dispatch — this is a fetch, not a
        second forward)."""
        plane = self.candidate if handle.reply_plane == "candidate" \
            else self.baseline
        logits, epoch = plane.complete(handle.reply)
        if handle.shadow is not None:
            self._judge(handle.shadow, logits, epoch)
        return logits, epoch

    def predict_complete(self, handle: _CanaryHandle) \
            -> Tuple[np.ndarray, Optional[int]]:
        logits, epoch = self.complete(handle)
        return np.argmax(logits, axis=-1), epoch

    # -- the state machine -------------------------------------------------

    def _judge(self, shadow_handle, base_logits: np.ndarray,
               base_epoch: Optional[int]) -> None:
        rows = int(base_logits.shape[0])
        try:
            cand_logits, cand_epoch = self.candidate.complete(shadow_handle)
        except Exception as exc:  # noqa: BLE001 - contained; counts against budget
            self._note_shadow_error(rows, exc)
            return
        if cand_epoch != base_epoch:
            # A hot reload landed between the two planes' param captures:
            # the rows would judge two different checkpoints. Skip the
            # comparison (counted, for observability) — the next shadowed
            # batch compares like-for-like.
            with self._lock:
                self._skewed += 1
            return
        disagreed = int(np.sum(
            np.argmax(cand_logits, axis=-1) != np.argmax(base_logits,
                                                         axis=-1)))
        if self._injected:
            disagreed = rows
        deltas = np.max(np.abs(cand_logits.astype(np.float32)
                               - base_logits.astype(np.float32)),
                        axis=tuple(range(1, base_logits.ndim)))
        transition = None
        with self._lock:
            self._compared_rows += rows
            self._disagreed_rows += disagreed
            self._deltas.extend(float(d) for d in deltas)
            transition = self._walk_locked()
        self._emit_transition(transition)

    def _note_shadow_error(self, rows: int, exc: BaseException) -> None:
        """A candidate dispatch/completion failure: contained (the reply
        already came from the baseline) but counted as ``rows``
        disagreeing rows — an erroring quantized plane must neither
        promote nor keep burning shadow work past the budget."""
        print(f"serve canary: shadow ({self.precision}) failed, counted "
              f"against the budget: {exc!r}", flush=True)
        transition = None
        with self._lock:
            self._shadow_errors += 1
            self._compared_rows += rows
            self._disagreed_rows += rows
            transition = self._walk_locked()
        self._emit_transition(transition)

    def _walk_locked(self) -> Optional[str]:
        """Walk the promote/rollback thresholds (caller holds the lock);
        returns the transition taken, for the caller to emit OUTSIDE the
        lock. Rollback outranks promotion when one batch crosses both."""
        if self._state != SHADOW:
            return None
        if self._disagreed_rows > self._allowed:
            self._state = ROLLED_BACK
            self._rollbacks += 1
            return "rolled_back"
        if self._compared_rows >= self.promote_after:
            self._state = PRIMARY
            self._promotions += 1
            # The answering plane just changed: cached baseline answers
            # must not outlive the promote.
            for hook in self._swap_hooks:
                hook(None)
            return "promoted"
        return None

    def _emit_transition(self, transition: Optional[str]) -> None:
        if transition is None:
            return
        with self._lock:
            detail = {"compared_rows": self._compared_rows,
                      "disagreed_rows": self._disagreed_rows,
                      "shadow_errors": self._shadow_errors}
        if transition == "promoted":
            print(f"serve canary: PROMOTED {self.precision} to primary "
                  f"after {detail['compared_rows']} clean shadowed rows "
                  f"({detail['disagreed_rows']} disagreements within "
                  f"budget {self._allowed:.1f})", flush=True)
        else:
            print(f"serve canary: ROLLED BACK {self.precision} — "
                  f"{detail['disagreed_rows']} disagreeing rows of "
                  f"{detail['compared_rows']} compared exceeded the "
                  f"budget ({self._allowed:.1f}); baseline keeps "
                  f"serving, permanent for this publish", flush=True)
        self._record_event(transition, **detail)

    def _record_event(self, event: str, **fields) -> None:
        if self.serve_log is not None:
            self.serve_log.record_pool_event(
                "serve_canary", event=event, precision=self.precision,
                state=self.state, **fields)

    # -- observability -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        """The ``/stats`` ``canary`` block: state, sampling shape, the
        disagreement counters, and the per-row max-|Δlogit| quantiles of
        the recent shadow window."""
        from pytorch_distributed_mnist_tpu.utils.profiling import _percentile

        with self._lock:
            deltas = sorted(self._deltas)
            compared = self._compared_rows
            snap = {
                "precision": self.precision,
                "state": self._state,
                "fraction": self.fraction,
                "promote_after": self.promote_after,
                "budget": self.budget,
                "shadow_batches": self._shadow_batches,
                "compared_rows": compared,
                "disagreed_rows": self._disagreed_rows,
                "disagree_rate": round(self._disagreed_rows / compared, 6)
                if compared else 0.0,
                "shadow_errors": self._shadow_errors,
                "skewed_comparisons": self._skewed,
                "publishes": self._publishes,
                "promotions": self._promotions,
                "rollbacks": self._rollbacks,
            }
        snap["logit_delta"] = {
            "p50": round(_percentile(deltas, 0.50), 6),
            "p95": round(_percentile(deltas, 0.95), 6),
            "p99": round(_percentile(deltas, 0.99), 6),
            "max": round(deltas[-1], 6) if deltas else 0.0,
            "count": len(deltas),
        }
        return snap
