"""The serving control plane: policy above the data plane.

The data plane (engine/pool/batcher) answers requests as fast as the
chips allow; this module decides WHICH requests get that capacity when
there is not enough of it, and how much capacity there should be:

- **Priority shedding** (:class:`ShedPolicy`): ``/predict`` requests
  carry a priority class (``interactive`` > ``batch`` > ``best_effort``),
  the batcher's bounded queue is priority-ORDERED, and each class has an
  admission watermark — a fraction of the queue past which that class is
  shed with 503. ``best_effort`` sheds first (half-full queue), ``batch``
  next (three-quarters), ``interactive`` last (the full queue, exactly
  the pre-policy admission bound). A 503 carries ``Retry-After`` derived
  from the batcher's measured drain rate: overload stops being a
  coin flip every class loses equally and becomes a policy.

- **Per-client quotas** (:class:`TokenBucket` / :class:`ClientQuotas`):
  one token bucket per (client, class) rejects an abuser with 429
  BEFORE the request consumes a queue slot — admission control protects
  the server, quotas protect the OTHER clients. Pure arithmetic under
  the lock (never a sleep: a blocked handler thread would be the quota
  consuming the capacity it exists to protect); the refusal carries the
  bucket's own refill time as ``Retry-After``.

- **SLO-driven autoscaling** (:class:`AutoScaler`): a background
  controller samples the ROLLING-window p95 and queue depth the
  :class:`~pytorch_distributed_mnist_tpu.utils.profiling.ServeLog`
  collects (lifetime quantiles can't see current load) and actuates the
  PR 10 ``EnginePool.resize`` path — add replicas on an SLO breach,
  remove them after a sustained calm. Hysteresis (the scale-down bar is
  a fraction of the scale-up bar, plus a consecutive-calm streak) and a
  cooldown after every actuation keep it from flapping; every decision
  is a ``serve_autoscale`` JSONL event through the shared sink, and
  ``dry_run`` records the decisions without actuating (the twin/canary
  mode). The controller snapshots state under its lock and ACTS outside
  it — ``resize`` builds and AOT-warms a whole layout, and holding any
  lock across that would stall ``/stats`` for the build (the
  lock-discipline fixture shape).

- **Weighted-fair multi-model dispatch** (:class:`WeightedFairGate`):
  N models served from one chip budget each get a weight; when more
  than one model has queued work, dispatch grants interleave in weight
  proportion (start-time fair queueing over per-model virtual time), so
  one model's backlog cannot starve another's. An idle model neither
  blocks the busy one nor banks credit for a catch-up burst (its
  virtual time is floored to the grant clock on re-entry).

Pure stdlib on purpose — no jax import: policy must be unit-testable
with stubs and importable from the analyzer fixtures, the chaos twins,
and ``bench.py`` without touching a backend.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

#: Priority classes, best-served first. The order IS the queue order and
#: the REVERSE of the shed order: ``best_effort`` sheds first,
#: ``interactive`` last.
PRIORITY_CLASSES: Tuple[str, ...] = ("interactive", "batch", "best_effort")

_RANK = {name: rank for rank, name in enumerate(PRIORITY_CLASSES)}

#: Default admission watermarks (fraction of the batcher queue a class
#: may fill before it is shed). ``interactive`` at 1.0 keeps the exact
#: pre-policy admission bound for the default class.
DEFAULT_WATERMARKS: Dict[str, float] = {
    "interactive": 1.0,
    "batch": 0.75,
    "best_effort": 0.5,
}


def priority_rank(klass: str) -> int:
    """Queue rank of a priority class (0 = most urgent). Raises
    ``ValueError`` on an unknown class — the HTTP layer turns that into
    a 400 naming the vocabulary."""
    try:
        return _RANK[klass]
    except KeyError:
        raise ValueError(
            f"unknown priority {klass!r}; one of "
            f"{list(PRIORITY_CLASSES)}") from None


class ShedPolicy:
    """Per-class admission watermarks over a bounded queue.

    ``admits(klass, depth, max_queue)`` is the admission decision the
    batcher asks under its own lock (pure arithmetic);
    ``retry_after_s`` converts the queue overhang into the honest
    back-off hint a 503 carries — how long the measured drain rate
    needs to bring the queue back under this class's watermark.
    """

    def __init__(self, watermarks: Optional[Dict[str, float]] = None
                 ) -> None:
        marks = dict(DEFAULT_WATERMARKS)
        for klass, frac in (watermarks or {}).items():
            priority_rank(klass)  # vocabulary check
            frac = float(frac)
            if not 0.0 < frac <= 1.0:
                raise ValueError(
                    f"watermark for {klass!r} must be in (0, 1], "
                    f"got {frac}")
            marks[klass] = frac
        self.watermarks = marks

    def admit_depth(self, klass: str, max_queue: int) -> int:
        """Queue slots class ``klass`` may occupy/see: depth >= this
        sheds. At least 1 — a watermark must never shed an empty
        queue."""
        return max(1, int(self.watermarks[klass] * max_queue))

    def admits(self, klass: str, depth: int, max_queue: int) -> bool:
        return depth < self.admit_depth(klass, max_queue)

    def retry_after_s(self, klass: str, depth: int, max_queue: int,
                      drain_rps: float, incoming: float = 1.0) -> float:
        """Seconds until the queue plausibly re-admits ``klass``: the
        load above its watermark divided by the measured drain rate.
        ``depth``, ``drain_rps`` and ``incoming`` (the refused
        request's own price) share ONE unit — request counts by
        default, cost units when the batcher prices admission — so a
        cost-priced 503's hint derives from drained COST, not drained
        count. Clamped to [0.1, 30] — an idle-drain estimate of hours
        is not a useful client hint, and sub-100ms retries just
        re-offer the overload."""
        over = depth - self.admit_depth(klass, max_queue) + float(incoming)
        rate = max(float(drain_rps), 1.0)
        return round(min(30.0, max(0.1, over / rate)), 3)


class DrainRate:
    """Units-per-second the data plane is actually completing, over a
    short sliding window — the denominator of every ``Retry-After``.
    The unit is whatever the caller notes: request counts by default,
    COST units on a priced batcher (fractional notes are preserved — a
    drained cache hit at ~0 cost must not round up to a full request).
    Thread-safe; the batcher's completion stage notes each delivered
    batch."""

    def __init__(self, window_s: float = 10.0) -> None:
        self._lock = threading.Lock()
        self.window_s = float(window_s)
        self._events: collections.deque = collections.deque(maxlen=4096)

    def note(self, n: float = 1, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((now, float(n)))

    def rate(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        cutoff = now - self.window_s
        with self._lock:
            while self._events and self._events[0][0] < cutoff:
                self._events.popleft()
            total = sum(n for _, n in self._events)
        return total / self.window_s


class TokenBucket:
    """One client×class rate limiter: ``rate`` tokens/sec refill up to
    ``burst``. ``admit`` is pure arithmetic — it never sleeps; a refusal
    returns the refill time the 429's ``Retry-After`` carries."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float,
                 now: Optional[float] = None) -> None:
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.t_last = time.monotonic() if now is None else now

    def admit(self, now: Optional[float] = None,
              cost: float = 1.0) -> Tuple[bool, float]:
        """``(admitted, retry_after_s)`` — retry_after is 0.0 on
        admission, else the seconds until ``cost`` tokens exist."""
        now = time.monotonic() if now is None else now
        self.tokens = min(self.burst,
                          self.tokens + max(0.0, now - self.t_last)
                          * self.rate)
        self.t_last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True, 0.0
        return False, round((cost - self.tokens) / self.rate, 3)


def parse_quota_spec(spec: str) -> Dict[str, float]:
    """``--quota-rps`` grammar -> {class: rps}.

    ``"100"`` bounds every class at 100 req/s per client;
    ``"100,interactive=20"`` overrides one class;
    ``"batch=50"`` bounds only that class (others unlimited).
    0 (or an absent class) = unlimited for that class.
    """
    rates: Dict[str, float] = {}
    default: Optional[float] = None
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" in tok:
            klass, _, val = tok.partition("=")
            klass = klass.strip()
            priority_rank(klass)
            rates[klass] = float(val)
        else:
            if default is not None:
                raise ValueError(
                    f"--quota-rps {spec!r}: more than one bare default "
                    f"rate")
            default = float(tok)
    if default is not None:
        for klass in PRIORITY_CLASSES:
            rates.setdefault(klass, default)
    for klass, rate in rates.items():
        if rate < 0:
            raise ValueError(
                f"--quota-rps: rate for {klass!r} must be >= 0, "
                f"got {rate}")
    return rates


class ClientQuotas:
    """Per-client token buckets with per-class rates.

    One bucket per (client_id, class); clients the server has never
    seen get a fresh bucket at the class's burst. The map is an LRU
    bounded at ``max_clients`` — an adversary minting client_ids per
    request must not grow server memory without bound (evicting an old
    client merely refills its burst, which is the conservative
    direction). Requests with no ``client_id`` share one anonymous
    bucket per class, so anonymity is not a quota bypass.
    """

    def __init__(self, rps_by_class: Dict[str, float],
                 burst_s: float = 2.0, max_clients: int = 4096) -> None:
        for klass in rps_by_class:
            priority_rank(klass)
        self.rps_by_class = {k: float(v) for k, v in rps_by_class.items()}
        self.burst_s = float(burst_s)
        self.max_clients = int(max_clients)
        self._lock = threading.Lock()
        self._buckets: "collections.OrderedDict[Tuple[str, str], TokenBucket]" = \
            collections.OrderedDict()
        self._rejected = 0

    @property
    def enabled(self) -> bool:
        return any(r > 0 for r in self.rps_by_class.values())

    def admit(self, client_id: Optional[str], klass: str,
              now: Optional[float] = None,
              cost: float = 1.0) -> Tuple[bool, float]:
        """``(admitted, retry_after_s)`` for one request. ``cost`` is
        the request's price in cost units (the TokenBucket was always
        cost-capable; a cost-pricing server finally wires real prices
        through — an expensive-bucket request spends its measured
        multiple, a cache hit spends ~0, and the default 1.0 keeps
        count-based quotas byte-identical). Arithmetic only under the
        lock — never a sleep, never IO (a handler thread parked inside
        here would hold queue capacity hostage to the very client being
        limited)."""
        rate = self.rps_by_class.get(klass, 0.0)
        if rate <= 0:
            return True, 0.0
        key = (client_id or "", klass)
        now = time.monotonic() if now is None else now
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = TokenBucket(rate, burst=rate * self.burst_s,
                                     now=now)
                self._buckets[key] = bucket
            else:
                self._buckets.move_to_end(key)
            while len(self._buckets) > self.max_clients:
                self._buckets.popitem(last=False)
            admitted, retry_after = bucket.admit(now=now, cost=cost)
            if not admitted:
                self._rejected += 1
        return admitted, retry_after

    def snapshot(self) -> Dict:
        """The ``/stats`` ``quota`` block."""
        with self._lock:
            return {
                "rps_by_class": dict(self.rps_by_class),
                "clients_tracked": len({c for c, _ in self._buckets}),
                "rejected": self._rejected,
            }


class AutoScaler:
    """The SLO feedback loop: rolling-window p95 / queue depth in,
    ``EnginePool.resize`` out.

    ``stats_fn() -> {"p95_ms": float, "queue_depth": int}`` is sampled
    every ``interval_s`` on a background thread (the ``ServeLog``'s
    ``window_stats`` — CURRENT load, not lifetime averages). The
    controller state machine:

    - **breach** (p95 > ``slo_p95_ms`` OR depth >= ``queue_high``):
      scale UP one step, unless already at ``max_devices`` or inside
      the cooldown.
    - **calm** (p95 < ``slo_p95_ms * down_frac`` AND depth <=
      ``queue_low``): one more tick of the calm streak; after
      ``down_after`` consecutive calm ticks, scale DOWN one step toward
      ``min_devices``. The lowered bar + streak is the hysteresis band —
      a p95 hovering at the SLO can trigger neither direction twice.
    - anything between: hold, streak resets.

    ``step`` is the scale quantum: 1 replica on the replicated plane,
    one whole MESH GROUP (``mesh_size`` chips) on a sharded pool —
    ``resize`` validates ``serve_mesh | serve_devices``, so any finer
    step could never actuate there (the server wiring also requires
    mesh-multiple min/max bounds for the same reason).

    A cooldown after every actuation bounds the resize rate (a resize
    builds + AOT-warms a whole layout; back-to-back resizes would spend
    the capacity they're trying to add). Every scale decision lands as
    a ``serve_autoscale`` event in the shared JSONL sink and in the
    in-memory decision log ``/stats`` surfaces; ``dry_run`` records
    without actuating. The tick snapshots state under the controller
    lock and calls ``resize`` strictly OUTSIDE it (and outside the
    pool/stats locks): the actuation is the slow part.
    """

    def __init__(
        self,
        pool,
        stats_fn: Callable[[], Dict],
        slo_p95_ms: float,
        queue_high: int,
        queue_low: Optional[int] = None,
        min_devices: int = 1,
        max_devices: Optional[int] = None,
        step: int = 1,
        interval_s: float = 2.0,
        cooldown_s: float = 10.0,
        down_frac: float = 0.5,
        down_after: int = 3,
        dry_run: bool = False,
        serve_log=None,
        model: Optional[str] = None,
        now_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        if slo_p95_ms <= 0:
            raise ValueError(f"slo_p95_ms must be > 0, got {slo_p95_ms}")
        if queue_high < 1:
            raise ValueError(f"queue_high must be >= 1, got {queue_high}")
        if min_devices < 1:
            raise ValueError(
                f"min_devices must be >= 1, got {min_devices}")
        if max_devices is not None and max_devices < min_devices:
            raise ValueError(
                f"max_devices {max_devices} < min_devices {min_devices}")
        if not 0.0 < down_frac < 1.0:
            raise ValueError(
                f"down_frac must be in (0, 1) — the hysteresis band — "
                f"got {down_frac}")
        if down_after < 1:
            raise ValueError(f"down_after must be >= 1, got {down_after}")
        self.pool = pool
        self.stats_fn = stats_fn
        self.slo_p95_ms = float(slo_p95_ms)
        self.queue_high = int(queue_high)
        self.queue_low = (max(0, queue_high // 4)
                          if queue_low is None else int(queue_low))
        self.min_devices = int(min_devices)
        self.max_devices = max_devices if max_devices is None \
            else int(max_devices)
        self.step = max(1, int(step))
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self.down_frac = float(down_frac)
        self.down_after = int(down_after)
        self.dry_run = bool(dry_run)
        self.serve_log = serve_log
        self.model = model
        self._now = now_fn
        self._lock = threading.Lock()
        self._calm_streak = 0
        self._last_action_t: Optional[float] = None
        self._scale_ups = 0
        self._scale_downs = 0
        self._errors = 0
        self._decisions: collections.deque = collections.deque(maxlen=64)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the state machine --------------------------------------------------

    def decide(self, p95_ms: float, queue_depth: int, n_devices: int,
               now: float) -> Optional[Dict]:
        """One controller step over one sample: mutates the streak /
        cooldown state and returns a scale decision dict, or ``None``
        to hold. Decision only — actuation is :meth:`tick`'s job, so
        the unit matrix drives this directly with synthetic samples."""
        breach = (p95_ms > self.slo_p95_ms
                  or queue_depth >= self.queue_high)
        calm = (p95_ms < self.slo_p95_ms * self.down_frac
                and queue_depth <= self.queue_low)
        with self._lock:
            in_cooldown = (self._last_action_t is not None
                           and now - self._last_action_t
                           < self.cooldown_s)
            if breach:
                self._calm_streak = 0
                if in_cooldown:
                    return None
                at_max = (self.max_devices is not None
                          and n_devices >= self.max_devices)
                if at_max:
                    return None
                target = n_devices + self.step
                if self.max_devices is not None:
                    target = min(target, self.max_devices)
                self._last_action_t = now
                return {
                    "action": "scale_up",
                    "from_devices": n_devices, "to_devices": target,
                    "reason": (
                        f"p95 {p95_ms:.1f}ms > SLO {self.slo_p95_ms}ms"
                        if p95_ms > self.slo_p95_ms else
                        f"queue depth {queue_depth} >= high watermark "
                        f"{self.queue_high}"),
                    "p95_ms": round(p95_ms, 3),
                    "queue_depth": int(queue_depth),
                }
            if not calm:
                # The hysteresis band: neither breach nor calm. The calm
                # streak resets — scale-down needs SUSTAINED headroom.
                self._calm_streak = 0
                return None
            self._calm_streak += 1
            if (self._calm_streak < self.down_after or in_cooldown
                    or n_devices <= self.min_devices):
                return None
            target = max(self.min_devices, n_devices - self.step)
            self._last_action_t = now
            self._calm_streak = 0
            return {
                "action": "scale_down",
                "from_devices": n_devices, "to_devices": target,
                "reason": (
                    f"p95 {p95_ms:.1f}ms < {self.down_frac:.0%} of SLO "
                    f"and queue <= {self.queue_low} for "
                    f"{self.down_after} samples"),
                "p95_ms": round(p95_ms, 3),
                "queue_depth": int(queue_depth),
            }

    def tick(self) -> Optional[Dict]:
        """Sample -> decide -> (maybe) actuate. Returns the recorded
        decision, or ``None`` on hold. The resize call runs with NO
        controller lock held — snapshot, release, act."""
        stats = self.stats_fn()
        decision = self.decide(
            float(stats.get("p95_ms", 0.0)),
            int(stats.get("queue_depth", 0)),
            int(self.pool.n_devices), self._now())
        if decision is None:
            return None
        decision["dry_run"] = self.dry_run
        if self.model is not None:
            decision["model"] = self.model
        if not self.dry_run:
            try:
                # The actuation: the PR 10 resize path (build + warm the
                # new layout while the old serves; atomic swap; zero
                # dropped in-flight requests by construction).
                self.pool.resize(n_devices=decision["to_devices"])
            except Exception as exc:  # noqa: BLE001 - controller survives
                # A concurrent /resize (409-shaped RuntimeError) or a
                # failed build must not kill the control loop; record
                # and let the next sample re-decide.
                decision["error"] = repr(exc)
                with self._lock:
                    self._errors += 1
        with self._lock:
            if "error" not in decision:
                if decision["action"] == "scale_up":
                    self._scale_ups += 1
                else:
                    self._scale_downs += 1
            self._decisions.append(dict(decision))
        if self.serve_log is not None:
            self.serve_log.record_pool_event("serve_autoscale", **decision)
        print(f"serve autoscale: {decision['action']} "
              f"{decision['from_devices']} -> {decision['to_devices']} "
              f"device(s) ({decision['reason']})"
              + (" [dry run]" if self.dry_run else "")
              + (f" FAILED: {decision['error']}"
                 if "error" in decision else ""),
              flush=True)
        return decision

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "AutoScaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serve-autoscale")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 - controller never dies
                print(f"serve autoscale: tick failed: {exc!r}", flush=True)

    def snapshot(self) -> Dict:
        """The ``/stats`` ``autoscaler`` block: configuration, counters,
        and the recent decision log (what the chaos twin asserts in
        dry-run mode)."""
        with self._lock:
            decisions = [dict(d) for d in self._decisions]
            return {
                "dry_run": self.dry_run,
                "slo_p95_ms": self.slo_p95_ms,
                "queue_high": self.queue_high,
                "queue_low": self.queue_low,
                "cooldown_s": self.cooldown_s,
                "min_devices": self.min_devices,
                "max_devices": self.max_devices,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "errors": self._errors,
                "calm_streak": self._calm_streak,
                "decisions": decisions,
                "last_decision": decisions[-1] if decisions else None,
            }


class WeightedFairGate:
    """Start-time fair queueing over per-model dispatch grants.

    Each model's batcher has ONE dispatch thread; before dispatching a
    batch it calls :meth:`grant` with its row count. When several
    models have a dispatch waiting, grants go to the model with the
    lowest virtual time, and each grant charges ``rows / weight`` — so
    over a sustained backlog the models' granted rows converge to the
    weight ratio, regardless of who queues faster. A model with no
    waiter never blocks anyone (work-conserving), and a model returning
    from idle has its virtual time floored to the grant clock, so it
    gets its fair share FORWARD from now — not a monopoly burst
    repaying the idle period.

    ``grant`` blocks (on the gate's condition variable) only while
    other models are ahead in virtual time; the caller dispatches
    OUTSIDE the gate's lock.
    """

    def __init__(self, weights: Dict[str, float]) -> None:
        if not weights:
            raise ValueError("WeightedFairGate needs at least one model")
        for model, w in weights.items():
            if w <= 0:
                raise ValueError(
                    f"weight for {model!r} must be > 0, got {w}")
        self.weights = {m: float(w) for m, w in weights.items()}
        self._cv = threading.Condition()
        self._vtime = {m: 0.0 for m in self.weights}
        self._floor = 0.0
        self._waiting: Dict[str, int] = {}
        self._granted_rows = {m: 0 for m in self.weights}
        self._grants = {m: 0 for m in self.weights}

    def grant(self, model: str, rows: int = 1) -> None:
        """Block until ``model`` is the fairness-eligible dispatcher,
        then charge the grant. One waiter per model (the batcher's
        single dispatch thread)."""
        if model not in self.weights:
            raise ValueError(
                f"unknown model {model!r}; gate serves "
                f"{sorted(self.weights)}")
        rows = max(1, int(rows))
        with self._cv:
            # Re-entry floor: an idle model's stale (small) vtime must
            # not buy it a catch-up monopoly.
            self._vtime[model] = max(self._vtime[model], self._floor)
            self._waiting[model] = rows
            while min(self._waiting,
                      key=lambda m: (self._vtime[m], m)) != model:
                self._cv.wait()
            del self._waiting[model]
            self._floor = max(self._floor, self._vtime[model])
            self._vtime[model] += rows / self.weights[model]
            self._granted_rows[model] += rows
            self._grants[model] += 1
            self._cv.notify_all()

    def snapshot(self) -> Dict:
        with self._cv:
            return {
                "weights": dict(self.weights),
                "granted_rows": dict(self._granted_rows),
                "grants": dict(self._grants),
            }


def parse_weight_spec(spec: str, models: List[str]) -> Dict[str, float]:
    """``--model-weights`` grammar -> {model: weight}; models not named
    default to 1.0. Unknown model names are a flag error."""
    weights = {m: 1.0 for m in models}
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, val = tok.partition("=")
        if not sep:
            raise ValueError(
                f"--model-weights {spec!r}: expected MODEL=WEIGHT, "
                f"got {tok!r}")
        name = name.strip()
        if name not in weights:
            raise ValueError(
                f"--model-weights names {name!r}, which is not in the "
                f"model set {sorted(models)}")
        weights[name] = float(val)
        if weights[name] <= 0:
            raise ValueError(
                f"--model-weights: weight for {name!r} must be > 0")
    return weights
