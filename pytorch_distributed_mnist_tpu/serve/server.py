"""The ``serve`` CLI subcommand: a stdlib HTTP JSON inference endpoint.

``python -m pytorch_distributed_mnist_tpu serve --checkpoint-dir ckpt
--model cnn`` boots: model + template state, newest published checkpoint
(or fresh init with a loud warning), the bucketed
:class:`~pytorch_distributed_mnist_tpu.serve.engine.InferenceEngine`
(all buckets AOT-compiled before the socket opens — a request can never
pay a compile), the
:class:`~pytorch_distributed_mnist_tpu.serve.batcher.MicroBatcher`, and
the :class:`~pytorch_distributed_mnist_tpu.serve.reload.CheckpointWatcher`
sharing the training run's checkpoint directory. With
``--serve-devices N`` (0 = all local devices) the engine becomes an
:class:`~pytorch_distributed_mnist_tpu.serve.pool.EnginePool` — one
replica per chip behind a least-loaded dispatcher — and the batcher
pipelines up to ``--max-inflight`` batches (default replicas+1) between
its form/dispatch and completion stages.

Endpoints (stdlib ``http.server``; one handler thread per connection,
all of them funneling into the batcher's dispatch worker that owns
device submission):

- ``POST /predict`` — body ``{"images": ...}``: one 28x28 image or a
  list of them, raw 0-255 pixel values. Replies
  ``{"predictions": [...], "model_epoch": e, "latency_ms": t}``;
  503 ``{"error": "overloaded"}`` under admission control.
- ``GET /healthz`` — liveness + which checkpoint epoch is serving.
- ``GET /stats`` — the ServeLog snapshot: p50/p95/p99 latency, queue
  depth/waits, batch-size histogram, reload + rejection counters, and
  the serve programs' compile stats (the zero-recompile evidence);
  pooled servers add the topology block (``topology_generation``,
  ``groups``/``active_groups``, ``quarantined_groups``, ``regroups``,
  ``failovers``) the self-healing pool maintains.
- ``POST /resize`` — the admin topology dial (pooled servers):
  ``{"serve_devices": N?, "serve_mesh": M?}`` re-shapes the pool under
  live traffic with zero dropped requests (``serve/pool.py::resize``).
- ``POST /drain`` — the fleet primitive: ``{"drain": true|false}``
  closes/reopens /predict admission (503 + Retry-After) while in-flight
  requests complete; ``/healthz`` and ``/stats`` expose ``draining`` so
  a router's rolling reload (``serve/router.py``) can publish against a
  quiescent backend and rejoin it afterwards.

The deliberately boring transport (no asyncio, no framework dep) is the
point: the serving smarts live in engine/batcher/reload, which are all
driveable in-process by tests and by ``bench.py --mode serve``.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher, Overloaded
from pytorch_distributed_mnist_tpu.serve.control import (
    PRIORITY_CLASSES,
    AutoScaler,
    ClientQuotas,
    ShedPolicy,
    WeightedFairGate,
    parse_quota_spec,
    parse_weight_spec,
    priority_rank,
)
from pytorch_distributed_mnist_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    InferenceEngine,
    load_params_for_serving,
)
from pytorch_distributed_mnist_tpu.serve.canary import (
    SHADOW as CANARY_SHADOW,
)
from pytorch_distributed_mnist_tpu.serve.canary import ShadowCanary
from pytorch_distributed_mnist_tpu.serve.economics import (
    HIT_COST,
    CostModel,
    ResponseCache,
    request_key,
)
from pytorch_distributed_mnist_tpu.serve.programs import (
    precision_engine_name,
    serve_modes,
    serve_precisions,
)
from pytorch_distributed_mnist_tpu.serve.reload import CheckpointWatcher
from pytorch_distributed_mnist_tpu.utils.profiling import (
    JsonlSink,
    ServeLog,
    compile_log,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-mnist serve",
        description="JSON inference endpoint over a training run's "
                    "checkpoint directory",
        allow_abbrev=False,
    )
    p.add_argument("--checkpoint-dir", type=str, default="checkpoints",
                   help="directory the training run publishes checkpoints "
                        "into; the newest is served and newer ones are "
                        "hot-reloaded as they appear")
    p.add_argument("--model", type=str, default="cnn",
                   help="model architecture the checkpoints belong to "
                        "(must match training's --model; a mismatched "
                        "checkpoint is rejected at load, not served)")
    p.add_argument("--model-set", type=str, default=None,
                   metavar="NAME=DIR[,NAME=DIR...]",
                   help="multi-model serving: boot one full engine-set "
                        "(engine/pool + batcher + watcher + canary + "
                        "layout gate) per MODEL=CHECKPOINT_DIR pair from "
                        "ONE process sharing the chip budget; requests "
                        "route on their 'model' field. Overrides "
                        "--model/--checkpoint-dir; every other serving "
                        "flag applies to each model's plane")
    p.add_argument("--model-weights", type=str, default=None,
                   metavar="NAME=W[,NAME=W...]",
                   help="multi-model weighted-fair dispatch: when more "
                        "than one model has queued work, device dispatch "
                        "grants interleave in this weight proportion "
                        "(unnamed models weigh 1.0) — one model's "
                        "backlog cannot starve another's. Requires "
                        "--model-set")
    p.add_argument("--dtype", type=str, default=None, choices=["bf16", "f32"],
                   help="compute dtype override, same semantics as "
                        "training's --dtype")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 picks a free port (printed at startup)")
    p.add_argument("--buckets", type=str,
                   default=",".join(str(b) for b in DEFAULT_BUCKETS),
                   help="comma-separated batch buckets, each AOT-compiled "
                        "at startup; batches pad up to the nearest bucket "
                        "so steady-state serving never recompiles")
    p.add_argument("--serve-devices", type=int, default=1,
                   help="chips the data plane spans (0 = every local "
                        "device). Replicated mode: one engine replica per "
                        "device behind the least-loaded dispatcher. "
                        "Sharded modes: the chips partition into "
                        "--serve-mesh-sized groups. Default 1 is the "
                        "single-device data plane")
    # choices read the LIVE registry at parser-build time, so a mode
    # added through register_serve_mode (the documented extension seam)
    # is accepted without editing this file.
    p.add_argument("--serve-mode", type=str, default="replicated",
                   choices=serve_modes(),
                   help="how one forward spans chips: 'replicated' runs "
                        "the whole model per chip (default, every model); "
                        "'tensor' Megatron-shards the ViT weights over a "
                        "mesh (parallel/tensor.py rules); 'expert' shards "
                        "moe_mlp experts (parallel/expert.py); 'pipeline' "
                        "compiles one INDEPENDENT program per stage chip "
                        "and streams batches between them (MPMD, "
                        "serve/pipeline.py — the mode pipeline-trained "
                        "checkpoints serve under). All share the "
                        "AOT/zero-recompile/hot-reload contract")
    p.add_argument("--serve-mesh", type=int, default=0,
                   help="devices per serving mesh group for sharded "
                        "modes — for --serve-mode pipeline, the STAGE "
                        "count per chain — (0 = all --serve-devices "
                        "chips in ONE group). Must divide "
                        "--serve-devices; the pool then runs one "
                        "spanning engine per group. Ignored (must be "
                        "left 0) in replicated mode")
    # choices read the LIVE precision registry (register_precision is
    # the documented extension seam, mirroring --serve-mode's).
    p.add_argument("--serve-precision", type=str, default="f32",
                   choices=serve_precisions(),
                   help="numeric precision of the serving programs "
                        "(serve/programs.py precision plane): 'f32' is "
                        "the full-precision default; 'bf16' stores "
                        "weights bfloat16 (compute follows the model's "
                        "--dtype policy); 'int8w' "
                        "quantizes weights to int8 (per-leaf symmetric "
                        "scales, dequantized on-chip, f32 compute); "
                        "'int8' additionally stages activations as "
                        "int8 (a quarter of the H2D bytes). "
                        "Quantization happens at param-install time, "
                        "so hot reload stays an atomic swap. Composes "
                        "with every --serve-mode")
    p.add_argument("--no-fuse", action="store_true",
                   help="disable whole-program dispatch and serve every "
                        "request on the SPLIT plane (host-side "
                        "normalize/quantize/pad, float staging) — the "
                        "bitwise reference the fused plane is pinned "
                        "against. Default: fused ON — raw uint8 "
                        "requests run ONE compiled program per bucket "
                        "(normalize + quantization inside XLA, staging "
                        "buffer donated), collapsing host work to a "
                        "bytes-copy. Use --no-fuse for batch-coupled "
                        "models whose pad-row semantics must match the "
                        "host plane exactly (DESIGN.md §7k)")
    p.add_argument("--canary-fraction", type=float, default=0.0,
                   help="shadow-traffic accuracy canary: serve replies "
                        "from the f32 BASELINE while this fraction of "
                        "live batches also runs the --serve-precision "
                        "plane in shadow; argmax disagreements and "
                        "logit deltas accumulate in /stats, the "
                        "precision PROMOTES to primary after "
                        "--canary-promote-after clean rows and AUTO-"
                        "ROLLS-BACK (permanent for that publish; the "
                        "server keeps serving) past --canary-budget. "
                        "0 (default) trusts --serve-precision outright "
                        "and serves it directly; requires a quantized "
                        "--serve-precision when set")
    p.add_argument("--canary-promote-after", type=int, default=200,
                   help="canary: shadowed rows (images) that must "
                        "compare within budget before the quantized "
                        "plane is promoted to primary")
    p.add_argument("--canary-budget", type=float, default=0.02,
                   help="canary: allowed argmax-disagreement fraction "
                        "of the promotion window (budget x promote-"
                        "after rows; shadow-plane errors count); "
                        "exceeding it rolls the publish back")
    p.add_argument("--quarantine-after", type=int, default=3,
                   help="serve-pool self-healing threshold: this many "
                        "CONSECUTIVE dispatch/completion failures on one "
                        "replica/mesh group (any success resets the "
                        "count) quarantine it — dispatch skips it, "
                        "in-flight batches fail over to healthy groups, "
                        "and a background regroup rebuilds it from its "
                        "chips under live traffic. Pooled data plane "
                        "only; input-shaped (4xx) errors never count")
    p.add_argument("--max-inflight", type=int, default=0,
                   help="pipelined dispatch window: batches dispatched "
                        "but not yet completed (0 = auto: replicas+1 on "
                        "a multi-replica pool, 1 otherwise; 1 disables "
                        "pipelining — batch N+1's host-side staging then "
                        "serializes behind batch N's result fetch)")
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="micro-batcher deadline: a request waits at most "
                        "this long for co-riders before its batch flushes")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission control: pending requests beyond this "
                        "are rejected with 503 instead of queuing "
                        "unboundedly")
    p.add_argument("--shed-watermarks", type=str, default=None,
                   metavar="CLASS=FRAC[,...]",
                   help="priority shedding: per-class admission "
                        "watermarks as fractions of --max-queue — a "
                        "class is shed (503 + Retry-After) once the "
                        "queue is past its fraction. Defaults "
                        "best_effort=0.5, batch=0.75, interactive=1.0: "
                        "best_effort sheds first, interactive keeps the "
                        "full queue (exactly the classic admission "
                        "bound). The queue itself is priority-ORDERED: "
                        "interactive requests overtake queued batch/"
                        "best_effort ones")
    p.add_argument("--quota-rps", type=str, default=None,
                   metavar="RPS[,CLASS=RPS...]",
                   help="per-client token-bucket quotas: each client_id "
                        "(anonymous requests share one bucket) may "
                        "submit this many requests/sec per priority "
                        "class, with a 2s burst; an over-quota request "
                        "is rejected 429 + Retry-After BEFORE it "
                        "consumes a queue slot, so one hot client "
                        "cannot starve the rest. A bare number bounds "
                        "every class; CLASS=RPS overrides per class "
                        "(e.g. '100,interactive=20'); unset = no quotas")
    p.add_argument("--quota-burst-s", type=float, default=2.0,
                   help="quota burst allowance in seconds of the class "
                        "rate (bucket capacity = rps x this)")
    p.add_argument("--stats-window-s", type=float, default=60.0,
                   help="rolling-window size for /stats' `window` block "
                        "(p50/p95/p99 + rps over the last N seconds "
                        "only, next to the lifetime quantiles) — what "
                        "the autoscaler and an operator mid-incident "
                        "react to")
    p.add_argument("--autoscale", action="store_true",
                   help="SLO-driven autoscaling: a background controller "
                        "samples the rolling-window p95 and queue depth "
                        "and actuates the pool's /resize path — scale up "
                        "on an SLO breach (--slo-p95-ms, or the queue "
                        "high watermark), scale down after sustained "
                        "calm; hysteresis + cooldown prevent flapping; "
                        "every decision is a serve_autoscale JSONL "
                        "event. Needs the pooled data plane "
                        "(--serve-devices/--max-inflight) and is "
                        "incompatible with an active canary (the two "
                        "planes' topology must not diverge)")
    p.add_argument("--autoscale-dry-run", action="store_true",
                   help="autoscaler twin mode: record every scale "
                        "decision (JSONL + /stats) without actuating "
                        "the resize")
    p.add_argument("--slo-p95-ms", type=float, default=100.0,
                   help="the serving SLO the autoscaler defends: "
                        "rolling-window p95 latency above this is a "
                        "breach (scale up); sustained p95 below half of "
                        "it with an empty-ish queue scales down")
    p.add_argument("--autoscale-queue-high", type=float, default=0.75,
                   help="autoscaler queue-depth high watermark as a "
                        "fraction of --max-queue: depth at/above it is "
                        "a breach even while p95 holds (latency "
                        "quantiles lag; queue depth leads)")
    p.add_argument("--autoscale-interval-s", type=float, default=2.0,
                   help="seconds between autoscaler samples")
    p.add_argument("--autoscale-cooldown-s", type=float, default=10.0,
                   help="seconds after any scale action before the next "
                        "may fire (a resize builds + AOT-warms a whole "
                        "layout; back-to-back resizes would spend the "
                        "capacity they add)")
    p.add_argument("--autoscale-down-after", type=int, default=3,
                   help="consecutive calm samples required before a "
                        "scale-down (with the halved p95 bar, the "
                        "hysteresis that prevents flapping)")
    p.add_argument("--autoscale-min-devices", type=int, default=1,
                   help="autoscaler floor: never scale below this many "
                        "devices")
    p.add_argument("--autoscale-max-devices", type=int, default=0,
                   help="autoscaler ceiling (0 = all local devices)")
    p.add_argument("--cache-mb", type=float, default=64.0,
                   help="response-cache byte budget in MB (bounded LRU): "
                        "an exact-byte repeat of a served request — same "
                        "raw body, model, serve mode and precision — "
                        "answers from the cache without touching the "
                        "batcher or a chip. Entries are stamped with a "
                        "generation counter bumped atomically under the "
                        "param-swap lock, so a hot reload / precision "
                        "swap / canary promote invalidates the whole "
                        "cache in O(1) — a stale logit can never be "
                        "served. 0 disables (same as --no-cache)")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the response cache (and in-flight "
                        "request collapsing keeps working — identical "
                        "concurrent requests still share one compute). "
                        "Replies are byte-identical to the cached path; "
                        "only the X-Cache header and the /stats cache "
                        "block disappear")
    p.add_argument("--price-admission", action="store_true",
                   help="cost-priced admission: each request is priced "
                        "in measured step-cost units (per-bucket bench "
                        "seed refreshed by an online EWMA at serve "
                        "time) instead of counting 1 per request — "
                        "queue watermarks, per-client quotas and "
                        "Retry-After all account in cost units, and a "
                        "cache hit prices at ~0. Default off: every "
                        "request costs 1.0, byte-identical to the "
                        "classic count-based admission")
    p.add_argument("--max-request-images", type=int, default=1024,
                   help="reject /predict requests with more images than "
                        "this (400): one giant request occupies a single "
                        "queue slot, so without a bound it could "
                        "monopolize the batcher past admission control — "
                        "batch client-side instead")
    p.add_argument("--poll-interval", type=float, default=2.0,
                   help="seconds between checkpoint-directory polls for "
                        "hot reload")
    p.add_argument("--no-reload", action="store_true",
                   help="serve the boot-time checkpoint forever (no "
                        "directory watching)")
    p.add_argument("--chunk-peers", type=str, default=None, metavar="URLS",
                   help="comma-separated peer backend base URLs "
                        "(http://host:port) to gossip checkpoint chunks "
                        "from: a delta-published manifest's missing "
                        "chunks are pulled from peers' GET /chunks/<hash> "
                        "before the --chunk-source fallback, so a fleet "
                        "publish costs the source O(chunks), not "
                        "O(replicas)")
    p.add_argument("--chunk-source", type=str, default=None, metavar="DIR",
                   help="source chunk-store directory (the trainer's "
                        "--checkpoint-dir) to fall back to when no peer "
                        "holds a chunk; defaults to the watch directory "
                        "itself, which a shared filesystem already covers")
    p.add_argument("--register-dir", type=str, default=None, metavar="DIR",
                   help="fleet registration directory: write a backend "
                        "record (tmp+rename JSON naming this server's "
                        "URL) on boot, remove it while draining and on "
                        "shutdown — a router's --backends-dir polls it "
                        "for dynamic join/leave without a restart")
    p.add_argument("--require-checkpoint", action="store_true",
                   help="refuse to start without a published checkpoint "
                        "(default: warn and serve fresh-init params, "
                        "hot-reloading the first checkpoint when it "
                        "appears)")
    p.add_argument("--compile-cache", type=str, default=None, metavar="DIR",
                   help="persistent XLA compile cache (same resolution as "
                        "training: flag > TPUMNIST_COMPILE_CACHE > repo "
                        "default; '' disables) — a warm cache turns the "
                        "startup bucket compiles into fetches")
    p.add_argument("--metrics-file", type=str, default=None,
                   help="append serve_stats / serve_reload JSONL lines "
                        "here — the same format/flag as training, so one "
                        "file can carry both sides of a shared run")
    p.add_argument("--stats-interval", type=float, default=30.0,
                   help="seconds between serve_stats lines to "
                        "--metrics-file (0 disables periodic writes)")
    p.add_argument("--seed", type=int, default=0,
                   help="fresh-init param seed when no checkpoint exists")
    p.add_argument("-j", "--workers", type=int, default=4,
                   help="host-side preprocessing threads per engine "
                        "(same flag as training's data loaders): "
                        "normalize, f64->f32 cast, and the pad-into-"
                        "staging copy run in multithreaded C++ when the "
                        "native library is built; no-op on the NumPy "
                        "fallback. Default 4")
    return p


# One oversized body must not buy unbounded JSON parsing on a handler
# thread; 16 MB comfortably fits --max-request-images' worth of pixels.
MAX_BODY_BYTES = 16 << 20


def _estimate_rows(images) -> int:
    """Cheap pure-Python row-count estimate for ADMISSION PRICING only
    (len/isinstance — no numpy before the quota gate): a multi-image
    request is a list whose first element is itself a 2-D image (list
    of lists); anything else prices as one row. The engine's
    preprocess still decides the real shape (and 400s malformed
    bodies); the batcher re-prices at the real row count."""
    if isinstance(images, list) and images \
            and isinstance(images[0], list) \
            and images[0] and isinstance(images[0][0], list):
        return len(images)
    return 1


class _HTTPServer(ThreadingHTTPServer):
    # Overload must reach ADMISSION CONTROL (a 503 with Retry-After),
    # not the kernel: the stdlib default accept backlog of 5 turns a
    # burst into connection-refused at the TCP layer — an unattributed
    # drop no policy ever saw. 128 rides out any burst the bounded
    # request queue is sized to answer.
    request_queue_size = 128


class ModelPlane:
    """One model's complete serving stack: engine/pool, batcher, reload
    watcher, optional canary and autoscaler, and its own
    :class:`ServeLog`. The single-model server is the degenerate case of
    one plane; ``--model-set`` boots N of these from one process, each
    keeping its own watcher/canary/layout-gate while sharing the chip
    budget through the weighted-fair dispatch gate."""

    def __init__(self, model_name: str, engine, batcher, watcher,
                 serve_log, boot_path: Optional[str], pool=None,
                 canary=None, autoscaler=None,
                 checkpoint_dir: Optional[str] = None) -> None:
        self.model_name = model_name
        self.engine = engine
        self.batcher = batcher
        self.watcher = watcher
        self.serve_log = serve_log
        self.boot_path = boot_path
        self.pool = pool
        self.canary = canary
        self.autoscaler = autoscaler
        self.checkpoint_dir = checkpoint_dir

    @property
    def checkpoint_path(self) -> Optional[str]:
        """The checkpoint currently serving: the watcher's view when
        reloading is on, else the boot-time restore."""
        if self.watcher is not None:
            return self.watcher.current_path
        return self.boot_path

    def close(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.watcher is not None:
            self.watcher.stop()
        self.batcher.close()


class ServeContext:
    """Everything one serving process owns; built by :func:`create_server`
    and shared with the HTTP handlers via the server object.

    ``planes`` maps model name -> :class:`ModelPlane`;
    ``default_model`` names the plane a request without a ``model``
    field routes to (the sole plane on a single-model server — where
    requests NEVER need the field). The flat attributes (``engine``,
    ``pool``, ``batcher``, ...) alias the default plane, so everything
    written against the single-model context keeps working."""

    def __init__(self, planes, default_model: str, sink,
                 max_request_images: int = 1024,
                 max_inflight: int = 1,
                 serve_mode: str = "replicated",
                 serve_precision: str = "f32",
                 quotas=None, fair_gate=None, fused: bool = True,
                 cache=None, price_admission: bool = False) -> None:
        self.planes = planes
        self.default_model = default_model
        self.sink = sink
        self.max_request_images = max_request_images
        self.serve_mode = serve_mode
        self.serve_precision = serve_precision
        # Request-path economics (DESIGN.md §7n): the epoch-stamped
        # response cache shared by every plane (keys carry the model
        # name, so one budget serves the whole process) and whether
        # admission accounts in measured cost units.
        self.cache = cache
        self.price_admission = bool(price_admission)
        # Which dispatch plane answers raw uint8 requests: fused
        # whole-program (default) or the --no-fuse split reference.
        self.fused = fused
        self.quotas = quotas
        self.fair_gate = fair_gate
        self.max_inflight = max_inflight
        self.t_start = time.time()
        # Drain state (POST /drain): while draining, /predict admission
        # rejects new work with Retry-After and in-flight requests run
        # to completion — the primitive a fleet router's rolling reload
        # and scale-down both sequence on. `_active_predicts` counts
        # every /predict handler past the drain gate, so `draining &&
        # active_requests == 0` means no request can still be executing.
        self.draining = False
        self._drain_lock = threading.Lock()
        self._active_predicts = 0
        # Fleet registration (--register-dir): the record announcing
        # this backend to a router's --backends-dir poller. Written on
        # boot, removed while draining (a draining backend must leave
        # the discovered set BEFORE the next health sweep routes to
        # it), re-written on undrain, removed on close.
        self._register_path: Optional[str] = None
        self._register_url: Optional[str] = None
        default = planes[default_model]
        # Single-model aliases (the historical surface).
        self.model_name = default.model_name
        self.engine = default.engine
        self.pool = default.pool
        self.batcher = default.batcher
        self.watcher = default.watcher
        self.canary = default.canary
        self.serve_log = default.serve_log
        self.boot_path = default.boot_path

    @property
    def multi_model(self) -> bool:
        return len(self.planes) > 1

    @property
    def checkpoint_path(self) -> Optional[str]:
        return self.planes[self.default_model].checkpoint_path

    def plane_for(self, model: Optional[str]) -> ModelPlane:
        """Route one request's ``model`` field to its plane. ``None``
        routes to the default ONLY on a single-model server — a
        multi-model server requires the field (silently defaulting
        would misroute every legacy client the moment a second model
        is added)."""
        if model is None:
            if self.multi_model:
                raise ValueError(
                    f"multi-model server: the request body must name "
                    f"'model' (one of {sorted(self.planes)})")
            return self.planes[self.default_model]
        plane = self.planes.get(model)
        if plane is None:
            raise ValueError(
                f"unknown model {model!r}; this server serves "
                f"{sorted(self.planes)}")
        return plane

    def predict_begin(self) -> None:
        with self._drain_lock:
            self._active_predicts += 1

    def predict_end(self) -> None:
        with self._drain_lock:
            self._active_predicts -= 1

    def active_requests(self) -> int:
        with self._drain_lock:
            return self._active_predicts

    def set_draining(self, draining: bool) -> bool:
        """Flip the drain gate; returns the previous state. Idempotent —
        a second drain (or undrain) is a no-op, so a router retrying the
        admin call cannot wedge the state."""
        with self._drain_lock:
            prev, self.draining = self.draining, bool(draining)
        if prev != draining and self._register_path is not None:
            # Registration follows the drain gate (file IO outside the
            # lock): a drained backend un-registers so a dynamic router
            # drops it at the next sweep; undrain re-announces it.
            if draining:
                _remove_register_record(self._register_path)
            else:
                _write_register_record(self._register_path,
                                       self._register_url)
        return prev

    def chunk_dirs(self) -> list:
        """Every plane's checkpoint directory — where the local chunk
        stores live; the ``GET /chunks/<hash>`` route searches them in
        plane order (digests are content-addressed, so a hit in any
        store is THE chunk)."""
        return [p.checkpoint_dir for p in self.planes.values()
                if p.checkpoint_dir]

    def enable_registration(self, register_dir: str, url: str) -> None:
        os.makedirs(register_dir, exist_ok=True)
        safe = url.split("//", 1)[-1].replace(":", "_").replace("/", "_")
        self._register_path = os.path.join(
            register_dir, f"backend_{safe}.json")
        self._register_url = url
        _write_register_record(self._register_path, url)
        print(f"registered backend {url} in {register_dir}", flush=True)

    def write_all_stats(self, **extra) -> None:
        if self.cache is not None and self.cache.enabled:
            # The cache block rides the periodic serve_stats JSONL
            # lines (PR 3 sink) — no separate event stream to tail.
            extra.setdefault("cache", self.cache.snapshot())
        for plane in self.planes.values():
            plane.serve_log.write_stats(**extra)

    def close(self) -> None:
        if self._register_path is not None:
            _remove_register_record(self._register_path)
            self._register_path = None
        for plane in self.planes.values():
            plane.close()
        if self.sink is not None:
            self.write_all_stats(final=True)


def _write_register_record(path: str, url: Optional[str]) -> None:
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"url": url}, f)
    os.replace(tmp, path)


def _remove_register_record(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass  # already gone (double drain, shutdown after drain)


class _Handler(BaseHTTPRequestHandler):
    # Per-request stderr lines would swamp the log at serving rates.
    def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
        pass

    @property
    def ctx(self) -> ServeContext:
        return self.server.ctx  # type: ignore[attr-defined]

    def _reply(self, code: int, payload: dict,
               headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                self.send_header(name, str(value))
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            # The client gave up (short timeout under overload) and
            # closed the socket: nobody is listening, and a per-request
            # traceback from socketserver would be exactly the log spam
            # the silenced log_message avoids.
            pass

    def _plane_stats(self, plane: ModelPlane) -> dict:
        """One plane's /stats payload — the historical single-model
        schema, byte-compatible for the default configuration."""
        ctx = self.ctx
        stats = plane.serve_log.snapshot()
        compile_stats = compile_log.stats()

        def _is_planes(name: str) -> bool:
            if not name.startswith("serve_forward_"):
                return False
            if not ctx.multi_model:
                return True
            # Multi-model: engine/replica names carry the model as
            # their first dotted segment after '@' ('serve_forward_b8@
            # linear.r0'), so each plane's block shows only its own
            # programs.
            _, _, engine_name = name.partition("@")
            return engine_name.split(".")[0] == plane.model_name

        stats["compile"] = {
            "programs": {
                name: rec for name, rec in
                compile_stats["programs"].items() if _is_planes(name)
            },
            "totals": compile_stats["totals"],
        }
        stats["buckets"] = list(plane.engine.buckets)
        stats["model_epoch"] = plane.engine.params_epoch
        stats["serve_mode"] = ctx.serve_mode
        # Always present (like serve_mode): what precision the
        # serving programs lower at — loadgen's report and the
        # --expect-precision smoke read it.
        stats["serve_precision"] = ctx.serve_precision
        # Always present: which dispatch plane answers raw uint8
        # requests — True is the fused whole-program plane (raw bytes
        # -> logits in one XLA program per bucket, donated staging),
        # False the --no-fuse split reference. loadgen's report and
        # the --expect-fused smoke read it.
        stats["fused"] = ctx.fused
        if ctx.fused:
            # The donation lifecycle's observable (DESIGN.md §7k):
            # every fused dispatch donates its staging buffer, which is
            # then RETIRED — counted here per bucket, summed across the
            # pool's replicas — never re-listed for reuse.
            src = plane.pool if plane.pool is not None else plane.engine
            stats["donated_staging_retired"] = src.fused_staging_retired()
        if ctx.cache is not None and ctx.cache.enabled:
            # Request-path economics block: cache hit/miss/eviction
            # counters, the invalidation generation, and how many
            # duplicate in-flight requests collapsed onto one compute.
            cache_block = ctx.cache.snapshot()
            cache_block["collapsed"] = plane.batcher.collapsed
            stats["cache"] = cache_block
        if ctx.price_admission and plane.batcher.cost_model is not None:
            # Cost-table provenance: per-bucket prices (bench seed
            # refreshed by the serve-time EWMA) admission accounts in.
            stats["cost_model"] = plane.batcher.cost_model.snapshot()
        if plane.canary is not None:
            # The shadow-canary block: state machine position,
            # sampling shape, disagreement counters, logit-delta
            # quantiles (serve/canary.py::snapshot).
            stats["canary"] = plane.canary.snapshot()
        if plane.autoscaler is not None:
            # The control-loop block: configuration, scale counters,
            # and the recent decision log (what the dry-run chaos twin
            # asserts before the real resize is trusted).
            stats["autoscaler"] = plane.autoscaler.snapshot()
        if plane.pool is not None:
            stats["serve_devices"] = plane.pool.n_devices
            stats["max_inflight"] = ctx.max_inflight
            # The self-healing/resize topology block (read LIVE from
            # the pool, so a /resize or regroup shows up on the next
            # fetch): generation counter, group counts, quarantine
            # state, failover/regroup totals. loadgen's
            # --expect-groups smoke asserts active_groups; its report
            # carries topology_generation.
            topo = plane.pool.topology()
            for key in ("topology_generation", "groups",
                        "active_groups", "quarantined_groups",
                        "regroups", "failovers"):
                stats[key] = topo[key]
            if ctx.serve_mode != "replicated":
                # The mesh shape the sharded plane is running:
                # loadgen's report and --expect-mode smoke read
                # these.
                stats["mesh_devices"] = plane.pool.mesh_size
                stats["mesh_groups"] = plane.pool.n_replicas
            if "pipeline_stages" in topo:
                # Staged (pipeline) modes: chips per chain — what
                # loadgen --expect-stages asserts.
                stats["pipeline_stages"] = topo["pipeline_stages"]
            if "slice_straddling_groups" in topo:
                # Slice-alignment warning (present only when a DCN
                # slice topology exists): mesh groups whose chips
                # straddle slices — their intra-group collectives
                # ride the slow cross-slice axis. loadgen reports
                # carry it.
                stats["slice_straddling_groups"] = \
                    topo["slice_straddling_groups"]
        return stats

    def do_GET(self) -> None:  # noqa: N802 - stdlib name
        ctx = self.ctx
        if self.path == "/healthz":
            payload = {
                "ok": True,
                "model": ctx.model_name,
                "model_epoch": ctx.engine.params_epoch,
                "checkpoint": ctx.checkpoint_path,
                "uptime_s": round(time.time() - ctx.t_start, 3),
                # Drain state rides on /healthz (not a separate probe):
                # a draining backend is ALIVE but not routable — the
                # router must distinguish "drain in progress" from
                # "dead" or it would quarantine every rolling deploy.
                "draining": ctx.draining,
            }
            if ctx.multi_model:
                payload["models"] = {
                    name: plane.engine.params_epoch
                    for name, plane in sorted(ctx.planes.items())}
            self._reply(200, payload)
        elif self.path == "/stats":
            # Top level = the default plane's historical schema; the
            # multi-model server ADDS a per-plane `models` block (and
            # `model_set`), and quotas add their own block — every
            # change is schema-additive.
            stats = self._plane_stats(ctx.planes[ctx.default_model])
            if ctx.multi_model:
                stats["model_set"] = sorted(ctx.planes)
                stats["models"] = {
                    name: self._plane_stats(plane)
                    for name, plane in sorted(ctx.planes.items())}
                if ctx.fair_gate is not None:
                    stats["fair_dispatch"] = ctx.fair_gate.snapshot()
            if ctx.quotas is not None:
                stats["quota"] = ctx.quotas.snapshot()
            # Drain observables: the rolling-reload sequencer polls
            # `draining && active_requests == 0` before publishing.
            stats["draining"] = ctx.draining
            stats["active_requests"] = ctx.active_requests()
            self._reply(200, stats)
        elif self.path.startswith("/chunks/"):
            self._do_chunk(self.path[len("/chunks/"):])
        else:
            self._reply(404, {"error": f"no route {self.path!r}"})

    def _do_chunk(self, digest: str) -> None:
        """``GET /chunks/<sha256>`` — the gossip plane: serve one chunk
        from this backend's local store(s) so peers fetch a publish's
        bytes from each other instead of all hammering the source.
        Content-addressed, so the reply needs no freshness logic: a hex
        digest either resolves to its immutable bytes or 404s. NOT
        gated by drain: a draining backend stops taking predict traffic
        but keeps seeding chunks — a rolling reload is exactly when
        peers need them."""
        import re as _re

        if not _re.fullmatch(r"[0-9a-f]{64}", digest):
            self._reply(404, {"error": "malformed chunk digest"})
            return
        ctx = self.ctx
        for directory in ctx.chunk_dirs():
            path = os.path.join(directory, "chunks", digest)
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            # `Range: bytes=N-` resumes a torn fetch from byte N
            # (DeltaFetcher retries a mid-body disconnect with the
            # partial offset instead of re-downloading): 206 + a
            # Content-Range naming the suffix; N past the end is 416.
            # Content addressing makes this trivially safe — the bytes
            # behind a digest can never change between attempts. A
            # malformed/unsupported Range falls back to the full 200.
            start = 0
            range_header = (self.headers.get("Range") or "").strip()
            if range_header:
                match = _re.fullmatch(r"bytes=(\d+)-", range_header)
                if match:
                    start = int(match.group(1))
                    if start >= len(data):
                        self._reply(
                            416, {"error": f"range start {start} past "
                                           f"chunk end {len(data)}"},
                            headers={"Content-Range":
                                     f"bytes */{len(data)}"})
                        return
            body = data[start:] if start else data
            try:
                self.send_response(206 if start else 200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                if start:
                    self.send_header(
                        "Content-Range",
                        f"bytes {start}-{len(data) - 1}/{len(data)}")
                self.end_headers()
                self.wfile.write(body)
            except OSError:
                pass  # client went away mid-transfer
            return
        self._reply(404, {"error": f"no chunk {digest}"})

    def do_POST(self) -> None:  # noqa: N802 - stdlib name
        if self.path == "/resize":
            self._do_resize()
            return
        if self.path == "/drain":
            self._do_drain()
            return
        if self.path != "/predict":
            self._reply(404, {"error": f"no route {self.path!r}"})
            return
        ctx = self.ctx
        # The active counter brackets the WHOLE predict path (parse
        # included) and the drain gate sits inside it, so once a drain
        # observer sees `draining && active_requests == 0` no handler
        # can still be ahead of the gate — publish-after-drain never
        # races a request that slipped past a narrower window.
        ctx.predict_begin()
        try:
            if ctx.draining:
                self._reject_draining()
                return
            self._do_predict()
        finally:
            ctx.predict_end()

    def _reject_draining(self) -> None:
        """503 while the drain gate is closed: same admission-control
        contract as overload shedding — Retry-After derived from the
        batcher's measured drain rate, so the client's back-off tracks
        how long the in-flight work plausibly takes to finish."""
        ctx = self.ctx
        length = int(self.headers.get("Content-Length", 0))
        if 0 < length <= MAX_BODY_BYTES:
            # Drain the request body so the reply lands on a clean
            # socket instead of a client-side broken pipe.
            self.rfile.read(length)
        depth = sum(p.batcher.queue_depth() for p in ctx.planes.values())
        rate = max(p.batcher.drain_rps() for p in ctx.planes.values())
        retry_after = min(30.0, max(1.0, depth / rate if rate > 0 else 1.0))
        self._reply(
            503,
            {"error": "draining", "draining": True,
             "retry_after_s": round(retry_after, 3)},
            headers={"Retry-After": max(1, round(retry_after))})

    def _do_drain(self) -> None:
        """``POST /drain`` — the fleet primitive: body ``{"drain":
        true|false}`` (default true) closes/reopens the /predict
        admission gate. In-flight requests complete; ``/stats`` exposes
        ``draining`` + ``active_requests`` so a rolling reload can wait
        for quiescence before publishing."""
        ctx = self.ctx
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_BODY_BYTES:
            self._reply(413, {"error": "oversized /drain body"})
            return
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
            drain = payload.get("drain", True)
            if not isinstance(drain, bool):
                raise ValueError("'drain' must be a boolean")
        except (ValueError, TypeError, json.JSONDecodeError) as exc:
            self._reply(400, {"error": str(exc)})
            return
        prev = ctx.set_draining(drain)
        if prev != drain:
            ctx.serve_log.record_pool_event(
                "serve_drain", draining=drain,
                active_requests=ctx.active_requests())
        self._reply(200, {"ok": True, "draining": drain,
                          "was_draining": prev,
                          "active_requests": ctx.active_requests()})

    def _do_predict(self) -> None:
        ctx = self.ctx
        t0 = time.perf_counter()
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_BODY_BYTES:
            # Refuse BEFORE reading/parsing: a multi-GB body must not buy
            # memory and JSON-parse time on this handler thread.
            self._reply(413, {"error": f"body over {MAX_BODY_BYTES} bytes;"
                                       f" batch client-side"})
            return
        raw_body = self.rfile.read(length) or b"{}"
        try:
            payload = json.loads(raw_body)
            # Control-plane fields first, all cheap string work: the
            # model route, the priority class (vocabulary-checked), and
            # the client identity — so quota refusal below happens
            # before any per-pixel array work is paid.
            plane = ctx.plane_for(payload.get("model"))
            # None (no priority field) stays None end to end: treated
            # as the most urgent class but never recorded as one, so a
            # server whose clients don't speak priorities keeps the
            # classless /stats schema.
            klass = payload.get("priority") or None
            if klass is not None:
                priority_rank(klass)  # 400 on an unknown class
            client_id = payload.get("client_id")
            if client_id is not None and not isinstance(client_id, str):
                raise ValueError("client_id must be a string")
        except (ValueError, TypeError, json.JSONDecodeError) as exc:
            self._reply(400, {"error": str(exc)})
            return
        # Response-cache probe (still pure byte/hash work — no numpy):
        # the key is the RAW request bytes plus everything else that
        # shapes the answer (model, serve mode, precision); the probe
        # snapshots the invalidation generation so an insert after a
        # concurrent swap is dropped, never served stale.
        cache = ctx.cache if ctx.cache is not None and ctx.cache.enabled \
            else None
        if cache is not None and plane.canary is not None \
                and plane.canary.state == CANARY_SHADOW:
            # A SHADOW canary judges only dispatched traffic: serving
            # duplicates from cache (or collapsing them onto one
            # dispatch — the key is also the collapse key) would starve
            # the comparison stream and stall promotion. Same rule as
            # the router during a fleet canary; normal caching resumes
            # on promote or rollback.
            cache = None
        ckey, hit_value, gen = None, None, 0
        if cache is not None:
            ckey = request_key(raw_body, plane.model_name,
                               ctx.serve_mode, ctx.serve_precision)
            hit_value, _hit_epoch, gen = cache.get(ckey)
        if ctx.quotas is not None:
            # Per-client quotas run BEFORE the request consumes a queue
            # slot (or any preprocessing): 429 is the CLIENT's overload
            # — admission control (503 below) is the server's. Under
            # --price-admission the bucket drains in measured cost
            # units: a cache hit is ~free, a big-bucket miss costs its
            # bench/EWMA price (row count estimated from JSON nesting —
            # cheap; the engine still decides the real shape below).
            cost = 1.0
            if ctx.price_admission:
                if hit_value is not None:
                    cost = HIT_COST
                elif plane.batcher.cost_model is not None:
                    cost = plane.batcher.cost_model.price(
                        _estimate_rows(payload.get("images")))
            admitted, retry_after = ctx.quotas.admit(
                client_id, klass or PRIORITY_CLASSES[0], cost=cost)
            if not admitted:
                plane.serve_log.record_rejection(klass=klass, quota=True)
                self._reply(
                    429,
                    {"error": "quota exceeded",
                     "priority": klass or PRIORITY_CLASSES[0],
                     "retry_after_s": retry_after},
                    headers={"Retry-After": max(1, round(retry_after))})
                return
        if hit_value is not None:
            # Cache hit: replay the stored predictions + epoch without
            # touching the batcher or a chip. The body is built by the
            # SAME code path as a miss (latency_ms is per-request
            # either way); only the X-Cache header differs. A hit is
            # still a SERVED request — it counts in the ServeLog like
            # any other (zero queue wait), so request totals, rps and
            # the rolling window the autoscaler reads stay honest.
            predictions, hit_epoch = hit_value
            latency_s = time.perf_counter() - t0
            plane.serve_log.record_request(
                latency_s, queue_wait_s=0.0,
                images=len(predictions), klass=klass)
            reply = {
                "predictions": list(predictions),
                "model_epoch": hit_epoch,
                "latency_ms": round(latency_s * 1e3, 3),
            }
            if ctx.multi_model:
                reply["model"] = plane.model_name
            self._reply(200, reply, headers={"X-Cache": "hit"})
            return
        try:
            images = payload.get("images")
            if images is None:
                raise ValueError("body must be JSON {\"images\": ...}")
            arr = np.asarray(images, dtype=np.float32)
            # Raw 0-255 pixels over the wire; quantize to the exact uint8
            # domain training reads from disk, then the engine applies
            # the training normalize. One preprocessing path, no drift.
            raw = np.clip(np.rint(arr), 0, 255).astype(np.uint8)
            batch = plane.engine.preprocess(raw)
            if batch.shape[0] > ctx.max_request_images:
                # One request = one queue slot: an unbounded row count
                # would monopolize the batcher past admission control.
                raise ValueError(
                    f"{batch.shape[0]} images in one request (max "
                    f"{ctx.max_request_images}); batch client-side")
        except (ValueError, TypeError, json.JSONDecodeError) as exc:
            self._reply(400, {"error": str(exc)})
            return
        try:
            # Each output row is (label, epoch-of-the-params-that-
            # computed-it) — see create_server's infer wrapper — so the
            # reply can never attribute a batch to a checkpoint a
            # concurrent hot reload installed after it ran. The cache
            # key doubles as the collapse key: a concurrent identical
            # request joins this one's pending future instead of
            # re-dispatching (it already paid quota at its own price).
            submit_cost = 1.0
            if ctx.price_admission and plane.batcher.cost_model is not None:
                submit_cost = plane.batcher.cost_model.price(
                    int(batch.shape[0]))
            out = plane.batcher.predict(batch, klass=klass,
                                        collapse_key=ckey,
                                        cost=submit_cost)
        except Overloaded as exc:
            # The shed reply: Retry-After (derived from the batcher's
            # measured drain rate) tells the client when this priority
            # class plausibly re-admits — back-off becomes a contract,
            # not a guess.
            payload = {"error": "overloaded", "detail": str(exc),
                       "priority": klass or PRIORITY_CLASSES[0]}
            headers = None
            if exc.retry_after_s is not None:
                payload["retry_after_s"] = exc.retry_after_s
                headers = {"Retry-After": max(1, round(exc.retry_after_s))}
            self._reply(503, payload, headers=headers)
            return
        except TimeoutError as exc:
            self._reply(504, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 - a request never kills the server
            self._reply(500, {"error": repr(exc)})
            return
        epoch = int(out[0, 1])
        model_epoch = None if epoch < 0 else epoch
        predictions = [int(v) for v in out[:, 0]]
        reply = {
            "predictions": predictions,
            "model_epoch": model_epoch,
            "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
        if ctx.multi_model:
            reply["model"] = plane.model_name
        headers = None
        if cache is not None:
            # Insert stamped with the PROBE-TIME generation: if a hot
            # reload / precision swap / canary promote bumped it while
            # this request computed, put() drops the entry — the cache
            # can only ever replay the current generation's params.
            cache.put(ckey, (predictions, model_epoch),
                      len(raw_body) + 16 * len(predictions) + 64,
                      epoch=model_epoch, generation=gen)
            headers = {"X-Cache": "miss"}
        self._reply(200, reply, headers=headers)

    def _do_resize(self) -> None:
        """``POST /resize`` — the admin topology dial: body
        ``{"serve_devices": N?, "serve_mesh": M?}`` re-shapes the pool
        under live traffic (new layout built + AOT-warmed while the old
        one keeps serving; atomic swap; in-flight batches drain on the
        old engines — zero dropped requests). Replies with the old and
        new topology. An operator's curl today, the autoscaler's
        actuator tomorrow (ROADMAP item 1)."""
        ctx = self.ctx
        # Multi-model: an optional "model" field routes the resize to
        # that plane's pool (peeked before the full parse below so the
        # plane's canary/pool checks see the right plane).
        length_peek = int(self.headers.get("Content-Length", 0))
        if length_peek > MAX_BODY_BYTES:
            self._reply(413, {"error": "oversized /resize body"})
            return
        raw_body = self.rfile.read(length_peek)
        try:
            peek = json.loads(raw_body or b"{}")
            plane = ctx.plane_for(
                peek.get("model") if isinstance(peek, dict) else None)
        except (ValueError, TypeError, json.JSONDecodeError) as exc:
            self._reply(400, {"error": str(exc)})
            return
        if plane.pool is None:
            self._reply(400, {
                "error": "resize needs the pooled data plane; start "
                         "with --serve-devices/--max-inflight/"
                         "--serve-mode (the default single-engine "
                         "server has no pool to re-shape)"})
            return
        if plane.canary is not None:
            # A resize mid-canary would re-shape only the baseline pool
            # while the candidate keeps the old topology — the two
            # planes' capacity (and failure surface) would silently
            # diverge under the comparison. Deliberately refused.
            self._reply(400, {
                "error": "resize is not supported while a precision "
                         "canary is active (--canary-fraction); the "
                         "baseline and shadow planes must keep the "
                         "same topology — restart to change it"})
            return
        try:
            payload = json.loads(raw_body or b"{}")
            if not isinstance(payload, dict):
                raise ValueError(
                    "body must be a JSON object with serve_devices "
                    "and/or serve_mesh")
            n_devices = payload.get("serve_devices")
            mesh_size = payload.get("serve_mesh")
            if n_devices is None and mesh_size is None:
                raise ValueError(
                    "body must be JSON with serve_devices and/or "
                    "serve_mesh")
            if n_devices is not None:
                n_devices = int(n_devices)
            if mesh_size is not None:
                mesh_size = int(mesh_size)
        except (ValueError, TypeError, json.JSONDecodeError) as exc:
            self._reply(400, {"error": str(exc)})
            return
        t0 = time.perf_counter()
        try:
            result = plane.pool.resize(n_devices=n_devices,
                                       mesh_size=mesh_size)
        except ValueError as exc:
            # An invalid target topology (device bounds, mesh
            # divisibility, a replicated mesh) — flag-language message,
            # nothing changed.
            self._reply(400, {"error": str(exc)})
            return
        except RuntimeError as exc:
            # One resize at a time: the concurrent caller backs off.
            self._reply(409, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 - an admin op never kills serving
            self._reply(500, {"error": repr(exc)})
            return
        self._reply(200, {
            "ok": True,
            **result,
            "warm_s": round(time.perf_counter() - t0, 3),
        })


def _parse_buckets(spec: str):
    try:
        buckets = tuple(int(tok) for tok in spec.split(",") if tok.strip())
    except ValueError:
        raise SystemExit(f"--buckets must be comma-separated ints, "
                         f"got {spec!r}") from None
    if not buckets or min(buckets) < 1:
        raise SystemExit(f"--buckets needs at least one positive size, "
                         f"got {spec!r}")
    return buckets


def _parse_model_set(spec: str, list_models) -> "dict":
    """``--model-set NAME=DIR[,NAME=DIR...]`` -> ordered
    ``{model: checkpoint_dir}``; flag-language SystemExits on unknown
    models, duplicates, or a malformed pair."""
    entries: dict = {}
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, directory = tok.partition("=")
        name, directory = name.strip(), directory.strip()
        if not sep or not name or not directory:
            raise SystemExit(
                f"--model-set: expected MODEL=CHECKPOINT_DIR, got "
                f"{tok!r}")
        if name not in list_models():
            raise SystemExit(f"--model-set names unknown model {name!r}; "
                             f"available: {list_models()}")
        if name in entries:
            raise SystemExit(
                f"--model-set names {name!r} twice (one engine-set per "
                f"model; point retrains at one directory)")
        entries[name] = directory
    if not entries:
        raise SystemExit("--model-set needs at least one MODEL=DIR pair")
    return entries


def _parse_watermarks(spec: Optional[str]) -> ShedPolicy:
    if not spec:
        return ShedPolicy()
    marks = {}
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        klass, sep, frac = tok.partition("=")
        if not sep:
            raise SystemExit(
                f"--shed-watermarks: expected CLASS=FRACTION, got "
                f"{tok!r}")
        try:
            marks[klass.strip()] = float(frac)
        except ValueError:
            raise SystemExit(
                f"--shed-watermarks: {frac!r} is not a number") from None
    try:
        return ShedPolicy(marks)
    except ValueError as exc:
        raise SystemExit(f"--shed-watermarks: {exc}") from None


def _build_plane(args, model_name: str, checkpoint_dir: str, *,
                 shape: dict, sink, shed_policy, fair_gate,
                 multi_model: bool) -> ModelPlane:
    """One model's full serving stack over the resolved data-plane
    ``shape`` — the single-model server builds exactly one of these;
    ``--model-set`` builds one per model (each with its own ServeLog,
    reload watcher, canary, layout gate, and — when autoscaling — its
    own controller over its own pool)."""
    import jax

    from pytorch_distributed_mnist_tpu.models import get_model, model_accepts
    from pytorch_distributed_mnist_tpu.serve.programs import (
        check_checkpoint_layout,
        make_serve_template,
        staged_mode,
        validate_serve_mode,
    )
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        _epoch_checkpoints,
        checkpoint_parallel_layout,
        checkpoint_world,
    )

    devices = shape["devices"]
    n_devices = shape["n_devices"]
    serve_mode = shape["serve_mode"]
    mesh_size = shape["mesh_size"]
    sharded = shape["sharded"]
    max_inflight = shape["max_inflight"]
    pooled = shape["pooled"]
    n_groups = shape["n_groups"]

    model_kwargs = {}
    if getattr(args, "dtype", None):
        import jax.numpy as jnp

        model_kwargs["compute_dtype"] = {
            "bf16": jnp.bfloat16, "f32": jnp.float32}[args.dtype]
    model = get_model(model_name, **model_kwargs)

    if sharded:
        try:
            # The mode/model PAIR check (mode registered, rule table for
            # this model) must precede the template build: a mode's
            # make_template hook assumes its model family (pipeline
            # splits block layers), so an unservable pair has to die
            # with flag language HERE, not a traceback in there. The
            # full check with the real mesh and params runs below.
            validate_serve_mode(serve_mode, model_name, 1)
        except ValueError as exc:
            raise SystemExit(str(exc)) from None
    template = make_serve_template(serve_mode, model,
                                   jax.random.key(args.seed))
    try:
        # ONE rule source (programs.validate_serve_mode): a mesh on the
        # replicated plane, a mode without a rule table for the model,
        # and a sharded weight dim that doesn't divide the mesh (the
        # template's shapes are every loadable checkpoint's shapes) all
        # fail HERE with flag language, before any mesh or program is
        # built.
        validate_serve_mode(serve_mode, model_name, mesh_size,
                            template.params if sharded else None)
    except ValueError as exc:
        raise SystemExit(str(exc)) from None

    # Precision plane + canary shape (argparse choices already bound
    # --serve-precision to the live registry): a canary only makes
    # sense shadowing a QUANTIZED plane against the f32 baseline.
    serve_precision = getattr(args, "serve_precision", "f32") or "f32"
    # Whole-program dispatch (ON by default, --no-fuse for the split
    # reference plane): raw uint8 requests run one fused program per
    # bucket — normalize/quantize inside XLA, staging donated. Under a
    # canary BOTH planes fuse (the batcher hands both the same raw
    # batch; mixed planes would compare different dispatch paths, not
    # different precisions).
    fuse = not getattr(args, "no_fuse", False)
    canary_fraction = float(getattr(args, "canary_fraction", 0.0) or 0.0)
    canary_promote_after = int(getattr(args, "canary_promote_after", 200))
    canary_budget = float(getattr(args, "canary_budget", 0.02))
    if canary_fraction:
        if serve_precision == "f32":
            raise SystemExit(
                "--canary-fraction shadows a quantized plane against "
                "the f32 baseline; pass a quantized --serve-precision "
                f"({serve_precisions()[1:]}) or drop the flag")
        if not (0.0 < canary_fraction <= 1.0):
            raise SystemExit(
                f"--canary-fraction {canary_fraction}: must be in "
                f"(0, 1]")
        if canary_promote_after < 1:
            raise SystemExit(
                f"--canary-promote-after {canary_promote_after}: must "
                f"be >= 1")
        if canary_budget < 0:
            raise SystemExit(
                f"--canary-budget {canary_budget}: must be >= 0")

    # Boot restore walks newest -> oldest: one corrupt latest file must
    # not turn a server RESTART (the natural operator response to any
    # incident) into a total outage — the same availability stance the
    # hot-reload watcher takes, and the serving analog of --resume auto's
    # fall-back-to-next-older (quarantining stays the trainer's job).
    # The parallel-layout gate applies PER CANDIDATE, on the meta-only
    # read and before the expensive template load: a layout-mismatched
    # newest file (a retrain under new parallelism flags sharing the
    # directory) is skipped in favor of an older compatible epoch, and
    # only when mismatches are the SOLE reason nothing is servable does
    # boot fail — loudly, naming the valid --serve-mode choices, never
    # by silently serving fresh-init params instead of the trained model.
    boot_path, params, epoch = None, None, None
    layout_rejection = None  # newest layout-mismatch (path, message)
    for _, candidate in reversed(_epoch_checkpoints(checkpoint_dir)):
        try:
            try:
                layout = checkpoint_parallel_layout(candidate)
            except Exception:  # noqa: BLE001 - unreadable meta: let the
                layout = None  # load attempt below classify the damage
            check_checkpoint_layout(layout, serve_mode, model_name)
        except ValueError as exc:
            if layout_rejection is None:
                layout_rejection = (candidate, str(exc))
            print(f"WARNING: cannot serve checkpoint {candidate!r} "
                  f"({exc}); trying the next-older epoch", flush=True)
            continue
        try:
            params, epoch = load_params_for_serving(candidate, template)
            boot_path = candidate
            break
        except Exception as exc:  # noqa: BLE001 - keep walking older epochs
            print(f"WARNING: cannot serve checkpoint {candidate!r} "
                  f"({exc!r}); trying the next-older epoch", flush=True)
    if boot_path is not None:
        # World provenance by meta inspection (the training world's
        # shape, stamped at save): a checkpoint from an N-host world is
        # served here after a cross-topology reshard — worth one log
        # line, since epoch metrics in a shared metrics file may
        # straddle world sizes (the elastic shrink path).
        try:
            world = checkpoint_world(boot_path)
        except Exception:  # noqa: BLE001 - provenance only; it loaded
            world = None
        provenance = (f", saved at world {world['processes']}x"
                      f"{world['devices']} processes x devices"
                      if world else "")
        print(f"serving checkpoint {boot_path!r} (epoch {epoch}"
              f"{provenance})", flush=True)
    elif layout_rejection is not None:
        raise SystemExit(
            f"{layout_rejection[0]!r}: {layout_rejection[1]}")
    elif getattr(args, "require_checkpoint", False):
        raise SystemExit(
            f"--require-checkpoint: no loadable published checkpoint in "
            f"{checkpoint_dir!r}")
    else:
        params, epoch = template.params, None
        print(f"WARNING: no loadable checkpoint in "
              f"{checkpoint_dir!r}; serving fresh-init params "
              f"(seed {args.seed}) until one is published", flush=True)

    serve_log = ServeLog(
        window_s=float(getattr(args, "stats_window_s", 60.0) or 60.0))
    if sink is not None:
        # One plane, one source tag: a multi-model process's JSONL
        # lines stay attributable per model in the shared file.
        serve_log.set_sink(
            sink, source=f"serve/{model_name}" if multi_model else "serve")

    # Multi-model names: the model is the first dotted segment of every
    # engine/replica name ('linear.r0', 'cnn.tensor.g0'), so /stats
    # rows, CompileLog programs, and recompile verdicts stay per model.
    name_prefix = f"{model_name}." if multi_model else ""

    def _tag(labels, epoch):
        # Row-tagged outputs (label, epoch): the epoch is captured WITH
        # the params inside the engine, and all rows of one batcher batch
        # ride one engine call (hence ONE replica), so per-request slices
        # stay consistent and the HTTP reply reports the checkpoint that
        # really computed it.
        tag = np.full_like(labels, -1 if epoch is None else epoch)
        return np.stack([labels, tag], axis=1)

    def _gated(dispatch_fn):
        """Wrap a dispatch with the weighted-fair gate: the grant runs
        on the batcher's dispatch thread (blocking only while OTHER
        models are ahead in virtual time), the dispatch itself after
        the grant — outside the gate's lock."""
        if fair_gate is None:
            return dispatch_fn

        def gated(images):
            fair_gate.grant(model_name, int(images.shape[0]))
            return dispatch_fn(images)

        return gated

    t0 = time.perf_counter()
    pool = None
    canary = None
    # Request-path economics: the per-bucket cost table (seeded from
    # the bucket geometry, EWMA-refreshed by the batcher per completed
    # batch) and whether admission accounts in its cost units.
    cost_model = CostModel(_parse_buckets(args.buckets))
    priced = bool(getattr(args, "price_admission", False))

    def _model_for(precision: str):
        """The model instance one precision plane lowers: the int8
        plane (and only it) gets the MXU-native int8 matmul injected
        through the model's ``dot_general`` field — PER-PRECISION
        instances, so a canary's f32 baseline never runs the kernel it
        is supposed to referee. Params are field-independent: the same
        checkpoint tree serves both instances."""
        if precision == "int8" and model_accepts(model_name, "dot_general"):
            from pytorch_distributed_mnist_tpu.ops.pallas import (
                int8_dot_general,
            )

            return get_model(model_name, dot_general=int8_dot_general,
                             **model_kwargs)
        return model

    def _make_plane(precision: str):
        """ONE data plane at ``precision`` over the resolved shape —
        the single builder both the direct path and the canary's two
        planes go through, so they cannot drift."""
        plane_model = _model_for(precision)
        if pooled:
            from pytorch_distributed_mnist_tpu.serve.pool import EnginePool

            return EnginePool(
                plane_model.apply, params, devices=devices[:n_devices],
                buckets=_parse_buckets(args.buckets), serve_log=serve_log,
                params_epoch=epoch, workers=getattr(args, "workers", 4),
                serve_mode=serve_mode, mesh_size=mesh_size,
                model_name=model_name, model=plane_model,
                quarantine_after=getattr(args, "quarantine_after", 3),
                precision=precision, name_prefix=name_prefix,
                fuse=fuse,
            )
        return InferenceEngine(
            plane_model.apply, params,
            buckets=_parse_buckets(args.buckets),
            serve_log=serve_log, params_epoch=epoch,
            workers=getattr(args, "workers", 4), precision=precision,
            name=precision_engine_name(
                model_name if multi_model else None, precision),
            fuse=fuse,
        )

    if canary_fraction:
        # Shadow canary: the f32 BASELINE answers, the quantized
        # candidate shadows --canary-fraction of batches; both planes
        # AOT-warm before the socket opens. /stats' topology block and
        # /resize talk to the baseline pool (the plane answering by
        # default); the candidate heals itself through the same pool
        # machinery.
        baseline = _make_plane("f32")
        candidate = _make_plane(serve_precision)
        pool = baseline if pooled else None
        if pooled and serve_log is not None:
            # Each pool registers its per-replica probe at construction;
            # the candidate (built second) would otherwise own /stats'
            # replica rows. The BASELINE answers by default — its rows
            # are the ones the probe should show.
            serve_log.set_replicas_probe(baseline.snapshot)
        canary = ShadowCanary(
            baseline, candidate, serve_precision,
            fraction=canary_fraction, promote_after=canary_promote_after,
            budget=canary_budget, serve_log=serve_log)
        engine = canary
        canary.warmup()
        batcher = MicroBatcher(
            None, max_batch=canary.max_batch,
            max_wait_s=args.max_wait_ms / 1e3, max_queue=args.max_queue,
            serve_log=serve_log,
            dispatch_fn=_gated(canary.dispatch),
            complete_fn=lambda handle: _tag(*canary.predict_complete(handle)),
            max_inflight=max_inflight, shed_policy=shed_policy,
            cost_model=cost_model, priced=priced,
        ).start()
    elif pooled:
        pool = _make_plane(serve_precision)
        engine = pool
        pool.warmup()
        batcher = MicroBatcher(
            None, max_batch=pool.max_batch,
            max_wait_s=args.max_wait_ms / 1e3, max_queue=args.max_queue,
            serve_log=serve_log,
            dispatch_fn=_gated(pool.dispatch),
            complete_fn=lambda handle: _tag(*pool.predict_complete(handle)),
            max_inflight=max_inflight, shed_policy=shed_policy,
            cost_model=cost_model, priced=priced,
        ).start()
    else:
        engine = _make_plane(serve_precision)
        engine.warmup()

        def infer(images):
            return _tag(*engine.predict_with_epoch(images))

        batcher = MicroBatcher(
            _gated(infer), max_batch=engine.max_batch,
            max_wait_s=args.max_wait_ms / 1e3, max_queue=args.max_queue,
            serve_log=serve_log, shed_policy=shed_policy,
            cost_model=cost_model, priced=priced,
        ).start()
    stats = compile_log.stats()["programs"]
    compiled_ms = sum(rec["wall_ms"] for name, rec in stats.items()
                      if name.startswith("serve_forward_"))
    if canary is not None:
        plane = (f"f32 baseline + {serve_precision} shadow canary "
                 f"(fraction {canary_fraction}, promote after "
                 f"{canary_promote_after} rows, budget {canary_budget})"
                 + (f" x {n_devices} device(s), {serve_mode}"
                    if pooled else ""))
    elif sharded and staged_mode(serve_mode):
        plane = (f"MPMD {serve_mode}: {n_groups} chain(s) x "
                 f"{mesh_size} per-chip stage programs x "
                 f"{len(engine.buckets)} buckets, in-flight window "
                 f"{max_inflight}")
    elif sharded:
        plane = (f"{serve_mode}-sharded: {n_groups} mesh group(s) x "
                 f"{mesh_size} chips x {len(engine.buckets)} buckets, "
                 f"in-flight window {max_inflight}")
    elif pooled:
        plane = (f"{n_devices} replica(s) x {len(engine.buckets)} "
                 f"buckets, in-flight window {max_inflight}")
    else:
        plane = f"{len(engine.buckets)} bucket programs"
    if serve_precision != "f32" and canary is None:
        plane = f"{serve_precision} {plane}"
    if fuse:
        plane = f"whole-program fused {plane}"
    print(f"{model_name}: AOT-compiled {plane} "
          f"{list(engine.buckets)} in {time.perf_counter() - t0:.1f}s "
          f"(compile wall {compiled_ms:.0f} ms); steady-state serving "
          f"never recompiles", flush=True)

    watcher = None
    if not getattr(args, "no_reload", False):
        # engine is the pool in the pooled case: ONE host-side checkpoint
        # load fans out to an atomic (and stale-rejecting) per-replica
        # swap.
        def _validate_reload(path: str) -> None:
            # The boot-time layout gate, re-applied per reload: a
            # checkpoint published with a mismatched training parallel
            # layout is skipped (permanent for that file) instead of
            # silently served under the wrong mode.
            check_checkpoint_layout(
                checkpoint_parallel_layout(path), serve_mode, model_name)

        # The delta-distribution loader: manifests are satisfied by
        # fetching only missing chunks (peers first, source dir
        # fallback) and patching/re-quantizing only dirty leaves; npz
        # and .ckpt paths fall through to the byte-identical whole-file
        # load, so directories that never see a manifest behave exactly
        # as before. Fetch-side quantization only when ONE plane owns
        # the loader's output — a canary's f32 baseline must never
        # receive pre-quantized leaves.
        from pytorch_distributed_mnist_tpu.distrib.fetch import DeltaFetcher
        from pytorch_distributed_mnist_tpu.serve.programs import (
            get_precision,
        )

        peers = [u.strip() for u in
                 (getattr(args, "chunk_peers", None) or "").split(",")
                 if u.strip()]
        fetcher = DeltaFetcher(
            checkpoint_dir,
            precision=(get_precision(serve_precision)
                       if canary is None else None),
            peers=peers,
            source_dir=getattr(args, "chunk_source", None),
            workers=getattr(args, "workers", 4),
        )
        watcher = CheckpointWatcher(
            checkpoint_dir, template, engine.swap_params,
            poll_interval_s=args.poll_interval, serve_log=serve_log,
            current_path=boot_path, validate_fn=_validate_reload,
            loader=fetcher.load,
        ).start()
        watcher.fetcher = fetcher  # observability: chaos/bench read stats

    autoscaler = None
    if getattr(args, "autoscale", False):
        # The SLO control loop over THIS plane's pool: samples the
        # plane's rolling-window p95/queue depth, actuates its resize.
        # Validation (pooled plane required, no canary, sane bounds,
        # mesh-multiple min/max on sharded modes) happened in
        # create_server before any plane was built. On a sharded pool
        # the scale STEP is one whole mesh group (mesh_size chips) —
        # resize validates serve_mesh | serve_devices, so a +1-chip
        # step could never actuate there.
        max_devices = getattr(args, "autoscale_max_devices", 0) or \
            (len(devices) - len(devices) % mesh_size)
        queue_high = max(1, int(getattr(args, "autoscale_queue_high",
                                        0.75) * args.max_queue))
        min_devices = getattr(args, "autoscale_min_devices", 1)
        if sharded:
            min_devices = max(min_devices, mesh_size)
        autoscaler = AutoScaler(
            pool, serve_log.window_stats,
            slo_p95_ms=getattr(args, "slo_p95_ms", 100.0),
            queue_high=queue_high,
            min_devices=min_devices,
            max_devices=max_devices,
            step=mesh_size,
            interval_s=getattr(args, "autoscale_interval_s", 2.0),
            cooldown_s=getattr(args, "autoscale_cooldown_s", 10.0),
            down_after=getattr(args, "autoscale_down_after", 3),
            dry_run=getattr(args, "autoscale_dry_run", False),
            serve_log=serve_log,
            model=model_name if multi_model else None,
        ).start()
        print(f"autoscaler: SLO p95 {autoscaler.slo_p95_ms}ms, queue "
              f"high {queue_high}, {autoscaler.min_devices}.."
              f"{max_devices} device(s), cooldown "
              f"{autoscaler.cooldown_s}s"
              + (" [dry run]" if autoscaler.dry_run else ""), flush=True)

    return ModelPlane(
        model_name, engine, batcher, watcher, serve_log, boot_path,
        pool=pool, canary=canary, autoscaler=autoscaler,
        checkpoint_dir=checkpoint_dir)


def create_server(args) -> ThreadingHTTPServer:
    """Build the model plane(s) — engine/pool + batcher + watcher (+
    canary/autoscaler) per model — and bind the HTTP server (socket
    bound, not yet serving — callers run ``serve_forever`` themselves, so
    tests can boot on port 0 in-process). ``server.ctx.close()`` tears
    the serving stack down."""
    import jax

    from pytorch_distributed_mnist_tpu.models import list_models
    from pytorch_distributed_mnist_tpu.serve.programs import staged_mode
    from pytorch_distributed_mnist_tpu.utils import compile_cache

    # The model set: --model-set wins (multi-model), else the classic
    # --model/--checkpoint-dir pair is a one-plane set.
    model_set_spec = getattr(args, "model_set", None)
    if model_set_spec:
        model_dirs = _parse_model_set(model_set_spec, list_models)
    else:
        if args.model not in list_models():
            raise SystemExit(f"unknown --model {args.model!r}; "
                             f"available: {list_models()}")
        model_dirs = {args.model: args.checkpoint_dir}
    multi_model = len(model_dirs) > 1

    cache_dir = compile_cache.configure(getattr(args, "compile_cache", None))
    if cache_dir:
        print(f"compile cache: {cache_dir}", flush=True)

    # Data-plane shape: --serve-devices chips (0 = all local devices),
    # --serve-mode deciding how a forward spans them (replicated per
    # chip, tensor/expert-sharded over --serve-mesh-chip groups, or a
    # pipeline of per-chip stage programs), with a --max-inflight
    # pipelined dispatch window (0 = auto). The default (replicated, 1
    # device, window 1) is the single-device plane, built exactly as it
    # always was. Shared by every model plane: N models serve from ONE
    # chip budget.
    devices = jax.local_devices()
    n_devices = getattr(args, "serve_devices", 1)
    if n_devices == 0:
        n_devices = len(devices)
    if n_devices < 0 or n_devices > len(devices):
        raise SystemExit(
            f"--serve-devices {n_devices}: this host has "
            f"{len(devices)} local device(s)")
    serve_mode = getattr(args, "serve_mode", "replicated")
    serve_mesh = getattr(args, "serve_mesh", 0)
    sharded = serve_mode != "replicated"
    mesh_size = 1
    if sharded:
        mesh_size = serve_mesh or n_devices
        if n_devices % mesh_size:
            raise SystemExit(
                f"--serve-mesh {mesh_size} must divide --serve-devices "
                f"{n_devices} (the pool runs one spanning engine per "
                f"mesh group)")
    elif serve_mesh not in (0, 1):
        mesh_size = serve_mesh  # rejected by per-plane validation
    max_inflight = getattr(args, "max_inflight", 0)
    if max_inflight < 0:
        raise SystemExit(f"--max-inflight {max_inflight}: must be >= 0")
    n_groups = n_devices // mesh_size
    if max_inflight == 0:
        # Auto window: one in-flight batch per engine plus one forming.
        # A single sharded group still defaults to 2 — host staging of
        # batch N+1 overlaps the mesh executing batch N. A STAGED mode's
        # group is a pipeline of per-chip programs, so its window sizes
        # per CHIP (stages x groups + 1): the pipe needs >= stages
        # batches in flight before every stage chip is busy.
        if sharded and staged_mode(serve_mode):
            max_inflight = n_devices + 1
        elif sharded:
            max_inflight = n_groups + 1
        else:
            max_inflight = n_devices + 1 if n_devices > 1 else 1
    pooled = n_devices > 1 or max_inflight > 1 or sharded
    shape = {"devices": devices, "n_devices": n_devices,
             "serve_mode": serve_mode, "mesh_size": mesh_size,
             "sharded": sharded, "max_inflight": max_inflight,
             "pooled": pooled, "n_groups": n_groups}

    # Control-plane configuration, validated BEFORE any plane is built
    # so a bad flag dies in milliseconds, not after the AOT compiles.
    shed_policy = _parse_watermarks(getattr(args, "shed_watermarks", None))
    quotas = None
    quota_spec = getattr(args, "quota_rps", None)
    if quota_spec:
        try:
            rates = parse_quota_spec(quota_spec)
            quotas = ClientQuotas(
                rates, burst_s=getattr(args, "quota_burst_s", 2.0))
        except ValueError as exc:
            raise SystemExit(f"--quota-rps: {exc}") from None
        if not quotas.enabled:
            quotas = None  # every class unlimited: no quota plane
    if getattr(args, "autoscale_dry_run", False) \
            and not getattr(args, "autoscale", False):
        raise SystemExit("--autoscale-dry-run modifies --autoscale; "
                         "pass both")
    if getattr(args, "autoscale", False):
        if not pooled:
            raise SystemExit(
                "--autoscale actuates the pool's resize path; start "
                "the pooled data plane (--serve-devices N / "
                "--max-inflight) — the single-engine server has no "
                "topology to scale")
        if float(getattr(args, "canary_fraction", 0.0) or 0.0):
            raise SystemExit(
                "--autoscale cannot run under an active precision "
                "canary (--canary-fraction): a resize would re-shape "
                "only the baseline pool and the two planes' topology "
                "must not diverge")
        if getattr(args, "autoscale_min_devices", 1) < 1:
            raise SystemExit("--autoscale-min-devices must be >= 1")
        max_dev = getattr(args, "autoscale_max_devices", 0)
        if max_dev and max_dev > len(devices):
            raise SystemExit(
                f"--autoscale-max-devices {max_dev}: this host has "
                f"{len(devices)} local device(s)")
        if sharded:
            # The autoscaler steps by whole MESH GROUPS (resize
            # validates serve_mesh | serve_devices): bounds that are
            # not mesh multiples would make every actuation a
            # validation error — reject them with flag language
            # instead of letting the controller spin on 400s.
            min_dev = getattr(args, "autoscale_min_devices", 1)
            if min_dev > 1 and min_dev % mesh_size:
                raise SystemExit(
                    f"--autoscale-min-devices {min_dev}: the sharded "
                    f"pool scales by whole {mesh_size}-chip mesh "
                    f"groups; pass a multiple of --serve-mesh")
            if max_dev and max_dev % mesh_size:
                raise SystemExit(
                    f"--autoscale-max-devices {max_dev}: the sharded "
                    f"pool scales by whole {mesh_size}-chip mesh "
                    f"groups; pass a multiple of --serve-mesh")
    fair_gate = None
    weight_spec = getattr(args, "model_weights", None)
    if weight_spec and not multi_model:
        raise SystemExit("--model-weights shapes multi-model dispatch; "
                         "it requires --model-set with >= 2 models")
    if multi_model:
        try:
            weights = parse_weight_spec(weight_spec or "",
                                        list(model_dirs))
        except ValueError as exc:
            raise SystemExit(str(exc)) from None
        fair_gate = WeightedFairGate(weights)

    sink = None
    metrics_file = getattr(args, "metrics_file", None)
    if metrics_file:
        sink = JsonlSink(metrics_file)

    planes = {}
    for model_name, checkpoint_dir in model_dirs.items():
        planes[model_name] = _build_plane(
            args, model_name, checkpoint_dir, shape=shape, sink=sink,
            shed_policy=shed_policy, fair_gate=fair_gate,
            multi_model=multi_model)
    default_model = next(iter(model_dirs))
    # Response cache (request-path economics): one shared budget for
    # the whole process — keys carry the model name, so planes cannot
    # collide. The invalidation hook registers on every plane's
    # answering engine (pool/canary/engine all expose add_swap_hook):
    # a hot reload, precision swap, or canary promote bumps the
    # generation under that plane's params lock — O(1), atomic with
    # the swap the entries must not outlive.
    cache_mb = float(getattr(args, "cache_mb", 64.0) or 0.0)
    if getattr(args, "no_cache", False) or cache_mb < 0:
        cache_mb = 0.0
    resp_cache = ResponseCache(int(cache_mb * (1 << 20)))
    if resp_cache.enabled:
        for plane in planes.values():
            plane.engine.add_swap_hook(resp_cache.bump_generation)
    if multi_model:
        print(f"multi-model serving: {sorted(planes)} from one "
              f"{n_devices}-device budget (weighted-fair dispatch "
              f"{fair_gate.weights}); requests route on their 'model' "
              f"field", flush=True)

    httpd = _HTTPServer((args.host, args.port), _Handler)
    httpd.daemon_threads = True
    httpd.ctx = ServeContext(  # type: ignore[attr-defined]
        planes, default_model, sink,
        max_request_images=getattr(args, "max_request_images", 1024),
        max_inflight=max_inflight, serve_mode=serve_mode,
        serve_precision=getattr(args, "serve_precision", "f32") or "f32",
        quotas=quotas, fair_gate=fair_gate,
        fused=not getattr(args, "no_fuse", False),
        cache=resp_cache if resp_cache.enabled else None,
        price_admission=getattr(args, "price_admission", False))
    register_dir = getattr(args, "register_dir", None)
    if register_dir:
        # Announce AFTER the socket is bound (the real port is known —
        # port 0 boots included) and the planes are warm: a router that
        # discovers this record can route to it immediately.
        port = httpd.server_address[1]
        adv_host = args.host if args.host not in ("", "0.0.0.0", "::") \
            else "127.0.0.1"
        httpd.ctx.enable_registration(
            register_dir, f"http://{adv_host}:{port}")
    return httpd


def main(argv: Optional[list] = None) -> None:
    args = build_parser().parse_args(argv)
    httpd = create_server(args)
    host, port = httpd.server_address[:2]
    print(f"serving on http://{host}:{port}  "
          f"(/predict, /healthz, /stats)", flush=True)
    stats_interval = getattr(args, "stats_interval", 0.0)
    stats_timer = None
    if httpd.ctx.sink is not None and stats_interval > 0:
        import threading

        stop = threading.Event()

        def _periodic():
            while not stop.wait(stats_interval):
                httpd.ctx.write_all_stats()

        stats_timer = (threading.Thread(target=_periodic, daemon=True,
                                        name="serve-stats"), stop)
        stats_timer[0].start()
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        print("shutting down", flush=True)
    finally:
        if stats_timer is not None:
            stats_timer[1].set()
        httpd.ctx.close()
        httpd.server_close()


if __name__ == "__main__":
    main()
