"""Forward-program registry: model x serve-mode -> mesh-lowered programs.

The single-device engine can only REPLICATE a forward per chip
(``serve/pool.py``): a model too big or too slow for one chip has no
serving path, and the repo's parallel-mode assets — the tensor-parallel
rule table (``parallel/tensor.py``) and the expert-parallel one
(``parallel/expert.py``) — are unservable. This registry is the missing
seam: given a model name and a serve mode, it builds the serving mesh,
derives the param/input/output shardings from the SAME rule tables
training uses (serving can never disagree with training on layout), and
hands the engine a :class:`MeshPlacement` it AOT-lowers its bucket
programs against — one pjit program per bucket over the mesh, same
zero-steady-state-recompile discipline, ``CompileLog`` names
``serve_forward_b{b}@{mode}``, params still an ARGUMENT of the compiled
programs so checkpoint hot-reload stays an atomic reference swap.

Modes (``SERVE_MODES``; extensible via :func:`register_serve_mode`):

- ``replicated`` — the PR 3/4 plane: one full forward per chip, fanned
  out by the pool. Servable by every model; the default, and built
  exactly as it always was (no placement object involved).
- ``tensor`` — Megatron column/row-parallel forward over a ``model``
  mesh axis (``vit_tp_rules``): qkv/mlp1 shard their output features,
  proj/mlp2 their input, XLA inserts the partial-sum AllReduce. One
  request's batch stays whole; the WEIGHTS and the per-token FLOPs
  split across the mesh — intra-request parallelism.
- ``expert`` — expert-parallel MoE forward over an ``expert`` mesh axis
  (``moe_ep_rules``): each device holds and computes only its local
  experts; the one-hot combine's sum over experts is the AllReduce.

Inputs and logits stay replicated over the mesh (every mesh device sees
the whole batch; MNIST batches are KBs — the win is weight/FLOP
placement, not activation sharding), which also keeps the engine's
host-side staging/bucketing machinery mode-agnostic: ``complete()``
reads a fully-replicated output exactly as it reads a single-device one.

A sharded engine SPANS its mesh devices, so the pool partitions local
chips into mesh GROUPS (``build_group_placements``) instead of
one-replica-per-device: 8 chips at ``--serve-mesh 2`` = 4 two-chip
engines behind the same least-loaded dispatcher.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.parallel.expert import moe_ep_rules
from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
    pipeline_stage_rules,
)
from pytorch_distributed_mnist_tpu.parallel.tensor import leaf_spec, vit_tp_rules

REPLICATED = "replicated"


class ServeMode:
    """One registered parallel serving mode: the mesh axis it shards
    over and, per model family, the rule table deriving every param
    leaf's ``PartitionSpec`` (the SAME table training's state sharding
    uses — ``parallel/tensor.py`` / ``parallel/expert.py``).

    Three optional hooks extend the registry beyond the one-pjit-over-
    the-mesh (SPMD) lowering, so a mode whose programs are NOT one mesh
    program — MPMD pipeline serving (``serve/pipeline.py``) compiles one
    independent program PER chip — still rides every generic path
    (layout gate, divisibility walk, pool groups, ``/stats``, bench)
    without special-casing:

    - ``engine_factory``: builds the group's engine instead of the
      default ``MeshPlacement`` + ``InferenceEngine`` pair
      (:func:`build_group_engine` routes).
    - ``make_template(model, rng) -> TrainState``: the template state
      checkpoints restore onto, for modes whose TRAINING param layout is
      not the standard flax tree (pipeline's ``{embed, blocks, head}``).
    - ``staged``: the mode's mesh axis is a PIPELINE of stages, not a
      spanning shard — the auto in-flight window sizes per CHIP (the
      pipe needs >= stages batches to fill) and ``/stats`` reports
      ``pipeline_stages``.
    """

    def __init__(self, name: str, axis: str,
                 rules_by_model: Dict[str, Callable],
                 engine_factory: Optional[Callable] = None,
                 make_template: Optional[Callable] = None,
                 staged: bool = False) -> None:
        self.name = name
        self.axis = axis
        self.rules_by_model = dict(rules_by_model)
        self.engine_factory = engine_factory
        self.make_template = make_template
        self.staged = staged

    def rules_for(self, model_name: str):
        try:
            rules_fn = self.rules_by_model[model_name]
        except KeyError:
            raise ValueError(
                f"--serve-mode {self.name} has no sharding rule table for "
                f"--model {model_name!r} (servable modes for it: "
                f"{servable_modes(model_name)})"
            ) from None
        return rules_fn(self.axis)


_MODES: Dict[str, ServeMode] = {}


def register_serve_mode(name: str, axis: str,
                        rules_by_model: Dict[str, Callable],
                        engine_factory: Optional[Callable] = None,
                        make_template: Optional[Callable] = None,
                        staged: bool = False) -> ServeMode:
    """Register a parallel serving mode (the extension point: a new
    parallel module's rule table becomes servable by adding one entry,
    no engine/pool/server change). See :class:`ServeMode` for the
    optional hooks non-SPMD modes use."""
    if name == REPLICATED or name in _MODES:
        raise ValueError(f"serve mode {name!r} already registered")
    mode = ServeMode(name, axis, rules_by_model,
                     engine_factory=engine_factory,
                     make_template=make_template, staged=staged)
    _MODES[name] = mode
    return mode


register_serve_mode("tensor", "model", {"vit": vit_tp_rules})
register_serve_mode("expert", "expert", {"moe_mlp": moe_ep_rules})


def serve_modes() -> List[str]:
    """Every registered mode, ``replicated`` first (the default)."""
    return [REPLICATED] + sorted(_MODES)


def get_serve_mode(mode: str) -> ServeMode:
    """The registered :class:`ServeMode` for ``mode`` (raises with the
    registry's vocabulary for unknown names; ``replicated`` has no
    ServeMode object and is rejected here too — callers branch on it
    BEFORE reaching for mode hooks)."""
    return _get_mode(mode)


def staged_mode(mode: str) -> bool:
    """Whether ``mode`` is a registered STAGED (pipeline-of-programs)
    mode — the ``/stats`` ``pipeline_stages`` field and the per-chip
    auto-window read this; replicated and unknown names are simply not
    staged."""
    spec = _MODES.get(mode)
    return spec is not None and spec.staged


def make_serve_template(mode: str, model, rng):
    """The template STATE checkpoints restore onto under ``mode``.

    Modes whose TRAINING param layout is not the standard flax tree
    (pipeline's stage-stacked ``{embed, blocks, head}``) override via
    the registry's ``make_template`` hook; everything else — replicated
    included — uses the standard ``create_train_state`` template, byte
    for byte the pre-registry boot path."""
    if mode != REPLICATED:
        spec = _get_mode(mode)
        if spec.make_template is not None:
            return spec.make_template(model, rng)
    from pytorch_distributed_mnist_tpu.train.state import create_train_state

    return create_train_state(model, rng)


def registered_mode_models() -> List[tuple]:
    """Every (mode, model) pair with a rule table, sorted — what the
    bench's sharded block iterates, so a mode added through
    ``register_serve_mode`` joins the throughput comparison and the
    per-bucket x mode recompile verdict without editing bench.py."""
    return [(name, model) for name, mode in sorted(_MODES.items())
            for model in sorted(mode.rules_by_model)]


def servable_modes(model_name: str) -> List[str]:
    """The serve modes with a rule table for ``model_name`` (always
    includes ``replicated``) — the vocabulary every rejection message
    speaks."""
    return [REPLICATED] + sorted(
        name for name, mode in _MODES.items()
        if model_name in mode.rules_by_model
    )


def _get_mode(mode: str) -> ServeMode:
    try:
        return _MODES[mode]
    except KeyError:
        raise ValueError(
            f"unknown serve mode {mode!r}; registered: {serve_modes()}"
        ) from None


class MeshPlacement:
    """How one sharded engine commits params and lowers its programs.

    Built once per engine (per mesh group) by :func:`build_placement`;
    the engine calls ``place_params`` at construction and on every
    hot-reload swap, ``place_input`` per dispatched bucket, and
    ``jit_forward`` once to get the pjit the bucket programs AOT-lower
    from. The param sharding TREE is precomputed from the template
    params — swap_params installs checkpoints with identical tree
    structure (the template-load contract), so one tree serves the
    engine's whole life.
    """

    def __init__(self, mode: str, mesh: Mesh, param_shardings,
                 name: str) -> None:
        self.mode = mode
        self.mesh = mesh
        self.name = name  # engine/CompileLog suffix: mode, or mode.g{i}
        self.devices = tuple(mesh.devices.flat)
        self.param_shardings = param_shardings
        self.input_sharding = NamedSharding(mesh, P())
        self.output_sharding = NamedSharding(mesh, P())

    def place_params(self, tree):
        return jax.device_put(tree, self.param_shardings)

    def place_input(self, arr):
        return jax.device_put(arr, self.input_sharding)

    def jit_forward(self, forward):
        return jax.jit(
            forward,
            in_shardings=(self.param_shardings, self.input_sharding),
            out_shardings=self.output_sharding,
        )


def _sharded_leaf_dims(params, rules) -> Dict[str, list]:
    """leaf-path -> [(dim, size), ...] for every param leaf the rule
    table actually shards; empty means the mode is a no-op for this
    model."""
    out: Dict[str, list] = {}

    def visit(path, leaf):
        spec = leaf_spec(path, rules)
        shape = jax.numpy.shape(leaf)
        dims = [(dim, shape[dim]) for dim, axis in enumerate(spec)
                if axis is not None]
        if dims:
            out[jax.tree_util.keystr(path)] = dims

    jax.tree_util.tree_map_with_path(visit, params)
    return out


def validate_serve_mode(mode: str, model_name: str, mesh_devices: int,
                        params=None) -> None:
    """Reject unservable model x mode x mesh combinations with flag
    language BEFORE any mesh or program is built.

    Checks: the mode is registered and has a rule table for the model,
    and (with ``params``) every sharded weight dim divides by the mesh
    size — e.g. ``--serve-mesh 8`` over a ViT whose qkv features don't
    split 8 ways, or more experts' worth of mesh than the MoE has
    experts, fails here with the leaf named, not as a pjit trace error.
    """
    if mode == REPLICATED:
        if mesh_devices != 1:
            raise ValueError(
                f"--serve-mode replicated serves one engine per chip; a "
                f"{mesh_devices}-device mesh needs a sharded mode "
                f"({servable_modes(model_name)[1:] or 'none for this model'})"
            )
        return
    spec = _get_mode(mode)
    rules = spec.rules_for(model_name)  # raises for unservable models
    if mesh_devices < 1:
        raise ValueError(f"serve mesh needs >= 1 device, got {mesh_devices}")
    if params is not None:
        sharded = _sharded_leaf_dims(params, rules)
        if not sharded:
            raise ValueError(
                f"--serve-mode {mode}: no param leaf of model "
                f"{model_name!r} matches the {mode} rule table — the mesh "
                f"would replicate everything; use --serve-mode replicated"
            )
        for path, dims in sorted(sharded.items()):
            for dim, size in dims:
                if size % mesh_devices:
                    raise ValueError(
                        f"--serve-mode {mode} over {mesh_devices} devices: "
                        f"param {path} dim {dim} (size {size}) does not "
                        f"divide evenly; pick a mesh size dividing {size}"
                    )


def build_placement(mode: str, model_name: str, devices: Sequence,
                    params, name: Optional[str] = None) -> MeshPlacement:
    """Mesh + sharding derivation for ONE engine spanning ``devices``.

    ``name`` defaults to the mode itself, giving the ISSUE-specified
    ``serve_forward_b{b}@{mode}`` CompileLog names on a single-group
    plane; multi-group pools pass ``{mode}.g{i}`` so compile stats and
    the zero-recompile verdicts stay attributable per group.
    """
    devices = list(devices)
    validate_serve_mode(mode, model_name, len(devices), params)
    spec = _get_mode(mode)
    rules = spec.rules_for(model_name)
    mesh = Mesh(_device_array(devices), (spec.axis,))
    param_shardings = jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, leaf_spec(path, rules)), params
    )
    return MeshPlacement(mode, mesh, param_shardings, name or mode)


def _device_array(devices):
    import numpy as np

    return np.asarray(devices, dtype=object).reshape(len(devices))


def partition_groups(devices: Sequence, mesh_size: int) -> List[list]:
    """Partition ``devices`` into ``mesh_size``-chip groups (the pool's
    sharded/staged plane: one spanning engine per group), rejecting
    indivisible shapes with flag language.

    Slice-aligned: when a DCN slice topology exists (real
    ``device.slice_index`` or the emulated ``TPUMNIST_DCN_SLICES``
    map), chips are ordered slice-major before chunking, so each
    group's intra-mesh collectives ride one slice's ICI whenever the
    mesh size fits in a slice — a group straddles slices only when it
    cannot fit, and the pool's ``/stats`` topology flags exactly those
    groups (``slice_straddling_groups``)."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import (
        device_slice_map,
    )

    devices = list(devices)
    if mesh_size < 1:
        raise ValueError(f"mesh size must be >= 1, got {mesh_size}")
    if len(devices) % mesh_size:
        raise ValueError(
            f"{len(devices)} serve device(s) do not partition into "
            f"{mesh_size}-device mesh groups; --serve-mesh must divide "
            f"--serve-devices"
        )
    smap = device_slice_map(devices)
    if smap is not None:
        order = sorted(range(len(devices)), key=lambda i: (smap[i], i))
        devices = [devices[i] for i in order]
    return [devices[i:i + mesh_size]
            for i in range(0, len(devices), mesh_size)]


def group_name(mode: str, index: int, n_groups: int) -> str:
    """One group's engine/CompileLog name: the bare mode when a single
    group spans the whole pool, ``{mode}.g{i}`` otherwise — so compile
    stats and the zero-recompile verdicts stay attributable per group
    (and, for staged modes, per stage under ``{name}.s{k}``)."""
    return mode if n_groups == 1 else f"{mode}.g{index}"


def build_group_placements(mode: str, model_name: str, devices: Sequence,
                           mesh_size: int, params) -> List[MeshPlacement]:
    """Partition ``devices`` into ``mesh_size``-chip groups, one
    :class:`MeshPlacement` per group — the pool's sharded plane: a
    sharded engine SPANS its mesh, so an 8-chip host at mesh 2 runs 4
    two-chip engines, not 8 one-chip replicas."""
    groups = partition_groups(devices, mesh_size)
    return [
        build_placement(mode, model_name, group, params,
                        name=group_name(mode, i, len(groups)))
        for i, group in enumerate(groups)
    ]


def build_group_engine(mode: str, model_name: str, devices: Sequence,
                       params, name: str, *, apply_fn, buckets,
                       input_shape, serve_log, params_epoch, workers,
                       model=None):
    """ONE engine spanning ``devices`` for ``mode`` — the single builder
    the pool's boot, regroup, and resize paths all share, which is what
    keeps a registered mode's engine construction from drifting between
    them. SPMD modes get the default ``MeshPlacement`` +
    ``InferenceEngine`` lowering; a mode with an ``engine_factory``
    (MPMD pipeline) builds its own engine behind the same surface."""
    spec = _get_mode(mode)
    if spec.engine_factory is not None:
        return spec.engine_factory(
            model=model, model_name=model_name, apply_fn=apply_fn,
            params=params, devices=list(devices), name=name,
            buckets=buckets, input_shape=input_shape, serve_log=serve_log,
            params_epoch=params_epoch, workers=workers)
    from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine

    placement = build_placement(mode, model_name, list(devices), params,
                                name=name)
    return InferenceEngine(
        apply_fn, params, buckets=buckets, input_shape=input_shape,
        serve_log=serve_log, params_epoch=params_epoch,
        placement=placement, name=name, workers=workers)


def check_checkpoint_layout(layout: Optional[dict], mode: str,
                            model_name: str) -> None:
    """Boot/reload gate: the checkpoint's recorded training parallel
    layout must match the serving mode.

    Training stamps ``parallel_layout`` (tensor/sequence/expert/pipeline
    widths) into checkpoint meta; a checkpoint trained with expert or
    tensor sharding served ``replicated`` silently loses the very
    parallelism the operator trained for (or, for a model that only fits
    sharded, fails outright) — reject with the valid ``--serve-mode``
    choices named. ``None`` (pre-layout checkpoints, unit-test saves)
    passes: no provenance, nothing to contradict.

    Sequence parallelism is activation-only (identical params), so it
    never constrains serving. Pipeline-trained checkpoints — whose
    stage-stacked param tree no SPMD serving template matches, and which
    PR 8 therefore rejected by name — now name ``--serve-mode pipeline``
    as the valid choice: the MPMD plane (``serve/pipeline.py``) restores
    onto the pipelined template and splits by stage itself.
    """
    if not layout:
        return
    trained_axis = {"tensor": "tensor", "expert": "expert",
                    "pipeline": "pipeline"}
    for key, want_mode in trained_axis.items():
        if int(layout.get(key, 1)) > 1 and mode != want_mode:
            raise ValueError(
                f"checkpoint was trained with {key}-parallel "
                f"{layout[key]}; serve it with --serve-mode {want_mode} "
                f"(valid modes for --model {model_name}: "
                f"{servable_modes(model_name)})"
            )


# MODE: pipeline (MPMD, serve/pipeline.py). Registered HERE like every
# built-in mode so the registry is complete whenever it is importable —
# regardless of whether anything imported serve.pipeline first — with
# the heavy hooks imported lazily on first USE (an engine build / a
# template make), not at registry import.
def _pipeline_factory(**kwargs):
    from pytorch_distributed_mnist_tpu.serve.pipeline import (
        pipeline_engine_factory,
    )

    return pipeline_engine_factory(**kwargs)


def _pipeline_template(model, rng):
    from pytorch_distributed_mnist_tpu.serve.pipeline import (
        make_pipeline_template,
    )

    return make_pipeline_template(model, rng)


register_serve_mode(
    "pipeline", "stage", {"vit": pipeline_stage_rules},
    engine_factory=_pipeline_factory,
    make_template=_pipeline_template,
    staged=True,
)

# Import-time snapshot for docs/tests; anything validating a mode must
# call serve_modes()/_get_mode (the live registry) so modes registered
# after import — the extension seam — are honored.
SERVE_MODES = serve_modes()
