"""Forward-program registry: model x serve-mode -> mesh-lowered programs.

The single-device engine can only REPLICATE a forward per chip
(``serve/pool.py``): a model too big or too slow for one chip has no
serving path, and the repo's parallel-mode assets — the tensor-parallel
rule table (``parallel/tensor.py``) and the expert-parallel one
(``parallel/expert.py``) — are unservable. This registry is the missing
seam: given a model name and a serve mode, it builds the serving mesh,
derives the param/input/output shardings from the SAME rule tables
training uses (serving can never disagree with training on layout), and
hands the engine a :class:`MeshPlacement` it AOT-lowers its bucket
programs against — one pjit program per bucket over the mesh, same
zero-steady-state-recompile discipline, ``CompileLog`` names
``serve_forward_b{b}@{mode}``, params still an ARGUMENT of the compiled
programs so checkpoint hot-reload stays an atomic reference swap.

Modes (``SERVE_MODES``; extensible via :func:`register_serve_mode`):

- ``replicated`` — the PR 3/4 plane: one full forward per chip, fanned
  out by the pool. Servable by every model; the default, and built
  exactly as it always was (no placement object involved).
- ``tensor`` — Megatron column/row-parallel forward over a ``model``
  mesh axis (``vit_tp_rules``): qkv/mlp1 shard their output features,
  proj/mlp2 their input, XLA inserts the partial-sum AllReduce. One
  request's batch stays whole; the WEIGHTS and the per-token FLOPs
  split across the mesh — intra-request parallelism.
- ``expert`` — expert-parallel MoE forward over an ``expert`` mesh axis
  (``moe_ep_rules``): each device holds and computes only its local
  experts; the one-hot combine's sum over experts is the AllReduce.

Inputs and logits stay replicated over the mesh (every mesh device sees
the whole batch; MNIST batches are KBs — the win is weight/FLOP
placement, not activation sharding), which also keeps the engine's
host-side staging/bucketing machinery mode-agnostic: ``complete()``
reads a fully-replicated output exactly as it reads a single-device one.

A sharded engine SPANS its mesh devices, so the pool partitions local
chips into mesh GROUPS (``build_group_placements``) instead of
one-replica-per-device: 8 chips at ``--serve-mesh 2`` = 4 two-chip
engines behind the same least-loaded dispatcher.

**The precision plane** (``--serve-precision``; ``SERVE_PRECISIONS``,
extensible via :func:`register_precision`) is the registry's second
axis, orthogonal to the mode axis above: every bucket x mode pair can
lower at ``f32`` (the default — byte-identical to the pre-precision
engine), ``bf16`` (weights stored bfloat16; compute follows the
model's own compute-dtype policy — bf16 on the TPU default), ``int8w``
(weight-only int8: per-leaf symmetric scales, weights dequantized
on-chip, f32 compute), or ``int8`` (int8w plus int8 activations: the
HOST quantizes the staged batch with the fixed normalize-range scale —
quartering the H2D bytes — and the program dequantizes on-chip). Quantization happens at param-INSTALL time
(:meth:`ServePrecision.quantize`, host-side, outside every engine
lock): the per-leaf scales are computed once per publish and stored
alongside the int8 values in :class:`QuantLeaf` pytree nodes, so the
quantized tree — scales included — remains an ARGUMENT of every
compiled program (never a baked constant: a new publish's scales must
not recompile anything) and hot-reload stays the same atomic reference
swap. ``CompileLog`` names gain the precision suffix
(``serve_forward_b{b}@{mode}.{prec}``; f32 keeps the historical names).

**The fused (whole-program) plane** (ISSUE 16): every bucket x mode x
precision pair can ALSO lower a fused program taking the raw staged
uint8 bytes — normalize (and, on int8, activation quantization) runs
inside XLA via :func:`fused_normalize`/:func:`quant_i8_traced`, both
bitwise-pinned to their host twins, and the staged buffer is DONATED
(:meth:`MeshPlacement.jit_fused_forward`). ``CompileLog`` names gain a
``.fused`` tag after the bucket (``serve_forward_b{b}.fused@{mode}``),
keeping every ``serve_forward_`` prefix filter working. The split plane
stays compiled alongside as the bitwise reference (``--no-fuse``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.parallel.expert import moe_ep_rules
from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
    pipeline_stage_rules,
)
from pytorch_distributed_mnist_tpu.parallel.tensor import leaf_spec, vit_tp_rules

REPLICATED = "replicated"


class ServeMode:
    """One registered parallel serving mode: the mesh axis it shards
    over and, per model family, the rule table deriving every param
    leaf's ``PartitionSpec`` (the SAME table training's state sharding
    uses — ``parallel/tensor.py`` / ``parallel/expert.py``).

    Three optional hooks extend the registry beyond the one-pjit-over-
    the-mesh (SPMD) lowering, so a mode whose programs are NOT one mesh
    program — MPMD pipeline serving (``serve/pipeline.py``) compiles one
    independent program PER chip — still rides every generic path
    (layout gate, divisibility walk, pool groups, ``/stats``, bench)
    without special-casing:

    - ``engine_factory``: builds the group's engine instead of the
      default ``MeshPlacement`` + ``InferenceEngine`` pair
      (:func:`build_group_engine` routes).
    - ``make_template(model, rng) -> TrainState``: the template state
      checkpoints restore onto, for modes whose TRAINING param layout is
      not the standard flax tree (pipeline's ``{embed, blocks, head}``).
    - ``staged``: the mode's mesh axis is a PIPELINE of stages, not a
      spanning shard — the auto in-flight window sizes per CHIP (the
      pipe needs >= stages batches to fill) and ``/stats`` reports
      ``pipeline_stages``.
    """

    def __init__(self, name: str, axis: str,
                 rules_by_model: Dict[str, Callable],
                 engine_factory: Optional[Callable] = None,
                 make_template: Optional[Callable] = None,
                 staged: bool = False) -> None:
        self.name = name
        self.axis = axis
        self.rules_by_model = dict(rules_by_model)
        self.engine_factory = engine_factory
        self.make_template = make_template
        self.staged = staged

    def rules_for(self, model_name: str):
        try:
            rules_fn = self.rules_by_model[model_name]
        except KeyError:
            raise ValueError(
                f"--serve-mode {self.name} has no sharding rule table for "
                f"--model {model_name!r} (servable modes for it: "
                f"{servable_modes(model_name)})"
            ) from None
        return rules_fn(self.axis)


_MODES: Dict[str, ServeMode] = {}


def register_serve_mode(name: str, axis: str,
                        rules_by_model: Dict[str, Callable],
                        engine_factory: Optional[Callable] = None,
                        make_template: Optional[Callable] = None,
                        staged: bool = False) -> ServeMode:
    """Register a parallel serving mode (the extension point: a new
    parallel module's rule table becomes servable by adding one entry,
    no engine/pool/server change). See :class:`ServeMode` for the
    optional hooks non-SPMD modes use."""
    if name == REPLICATED or name in _MODES:
        raise ValueError(f"serve mode {name!r} already registered")
    mode = ServeMode(name, axis, rules_by_model,
                     engine_factory=engine_factory,
                     make_template=make_template, staged=staged)
    _MODES[name] = mode
    return mode


register_serve_mode("tensor", "model", {"vit": vit_tp_rules})
register_serve_mode("expert", "expert", {"moe_mlp": moe_ep_rules})


def serve_modes() -> List[str]:
    """Every registered mode, ``replicated`` first (the default)."""
    return [REPLICATED] + sorted(_MODES)


def get_serve_mode(mode: str) -> ServeMode:
    """The registered :class:`ServeMode` for ``mode`` (raises with the
    registry's vocabulary for unknown names; ``replicated`` has no
    ServeMode object and is rejected here too — callers branch on it
    BEFORE reaching for mode hooks)."""
    return _get_mode(mode)


def staged_mode(mode: str) -> bool:
    """Whether ``mode`` is a registered STAGED (pipeline-of-programs)
    mode — the ``/stats`` ``pipeline_stages`` field and the per-chip
    auto-window read this; replicated and unknown names are simply not
    staged."""
    spec = _MODES.get(mode)
    return spec is not None and spec.staged


def make_serve_template(mode: str, model, rng):
    """The template STATE checkpoints restore onto under ``mode``.

    Modes whose TRAINING param layout is not the standard flax tree
    (pipeline's stage-stacked ``{embed, blocks, head}``) override via
    the registry's ``make_template`` hook; everything else — replicated
    included — uses the standard ``create_train_state`` template, byte
    for byte the pre-registry boot path."""
    if mode != REPLICATED:
        spec = _get_mode(mode)
        if spec.make_template is not None:
            return spec.make_template(model, rng)
    from pytorch_distributed_mnist_tpu.train.state import create_train_state

    return create_train_state(model, rng)


def registered_mode_models() -> List[tuple]:
    """Every (mode, model) pair with a rule table, sorted — what the
    bench's sharded block iterates, so a mode added through
    ``register_serve_mode`` joins the throughput comparison and the
    per-bucket x mode recompile verdict without editing bench.py."""
    return [(name, model) for name, mode in sorted(_MODES.items())
            for model in sorted(mode.rules_by_model)]


def servable_modes(model_name: str) -> List[str]:
    """The serve modes with a rule table for ``model_name`` (always
    includes ``replicated``) — the vocabulary every rejection message
    speaks."""
    return [REPLICATED] + sorted(
        name for name, mode in _MODES.items()
        if model_name in mode.rules_by_model
    )


def _get_mode(mode: str) -> ServeMode:
    try:
        return _MODES[mode]
    except KeyError:
        raise ValueError(
            f"unknown serve mode {mode!r}; registered: {serve_modes()}"
        ) from None


# -- the precision plane -----------------------------------------------------

F32 = "f32"


class QuantLeaf(NamedTuple):
    """One int8-quantized param leaf: the int8 values (original shape)
    and the f32 symmetric scale, TOGETHER as one pytree node — so the
    scale rides the quantized tree through ``device_put``, the sharding
    derivation, and into the compiled programs as an ARGUMENT. Baking a
    publish's scales into the lowered program as constants would force a
    recompile per hot reload (the recompile-hazard the analyzer fixtures
    encode); keeping them leaf-shaped keeps reload a reference swap."""

    q: object  # int8 values, the original leaf's shape
    s: object  # f32 scalar scale (dequant: q.astype(f32) * s)


def _act_scale() -> np.float32:
    """The FIXED int8 activation scale: normalized MNIST pixels live in
    the closed, data-independent range ``[(0-mean)/std, (1-mean)/std]``
    (max |x| at pixel 255), so one symmetric scale covers every request
    — no per-batch calibration, no per-batch scale argument, nothing
    that could vary a compiled program's inputs. Computed in f32 ops so
    the host quantizer and the on-chip dequant agree bitwise."""
    from pytorch_distributed_mnist_tpu.data.mnist import MNIST_MEAN, MNIST_STD

    max_abs = ((np.float32(1.0) - np.float32(MNIST_MEAN))
               / np.float32(MNIST_STD))
    return np.float32(max_abs / np.float32(127.0))


ACT_SCALE = _act_scale()


def _quant_i8_host(x: np.ndarray, scale: np.float32,
                   workers: int) -> np.ndarray:
    """The ONE host-side f32 -> int8 quantizer (weight leaves and the
    int8 activation staging both go through here): the native v4
    ``tm_quant_i8`` kernel when built, else the bitwise-identical NumPy
    expression — both round-to-nearest-even after multiplying by the
    SAME precomputed f32 reciprocal (never a division: divide vs
    multiply-by-reciprocal round differently, and the native-vs-
    fallback equivalence is pinned bitwise)."""
    from pytorch_distributed_mnist_tpu.data import native

    x = np.ascontiguousarray(x, np.float32)
    q = native.quant_i8(x, float(scale), workers=workers)
    if q is None:
        inv = np.float32(1.0) / scale
        scaled = np.rint(x * inv)
        # NaN -> 0 explicitly (astype(int8) of NaN is platform-defined,
        # and the native kernel pins 0); ±inf clip like any overflow.
        scaled = np.where(np.isnan(scaled), np.float32(0.0), scaled)
        q = np.clip(scaled, -127, 127).astype(np.int8)
    return q


def quantize_leaf_i8(leaf, workers: int = 4) -> QuantLeaf:
    """Symmetric per-leaf int8 quantization (host-side, install-time):
    ``scale = max|leaf| / 127``, ``q = clip(rne(leaf / scale), ±127)``
    via :func:`_quant_i8_host`. An all-zero leaf gets scale 1.0
    (quantizes to zeros either way)."""
    x = np.ascontiguousarray(np.asarray(leaf), np.float32)
    max_abs = float(np.max(np.abs(x))) if x.size else 0.0
    scale = np.float32(max_abs) / np.float32(127.0) \
        if max_abs > 0.0 else np.float32(1.0)
    return QuantLeaf(q=_quant_i8_host(x, scale, workers), s=scale)


def dequantize_params(tree):
    """In-program dequantization of a :meth:`ServePrecision.quantize`'d
    tree: every :class:`QuantLeaf` becomes its f32 leaf (``q * s``),
    everything else passes through. Pure jnp ops — this runs INSIDE the
    jitted bucket programs, on tracers."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.q.astype(jnp.float32) * leaf.s
        if isinstance(leaf, QuantLeaf) else leaf,
        tree, is_leaf=lambda x: isinstance(x, QuantLeaf))


def fused_normalize(raw):
    """In-XLA MNIST normalize, BITWISE-equal to the host
    ``normalize_images`` path: raw uint8 ``(N, 28, 28)`` tracer ->
    normalized f32 ``(N, 28, 28, 1)``.

    The constants hide behind ``optimization_barrier`` because XLA's
    algebraic simplifier otherwise rewrites ``x / const`` into
    ``x * (1/const)`` — a ~1-ulp-different result that would break the
    fused-vs-split bitwise pin. With the barrier the divides are genuine
    IEEE divides, matching the host's NumPy expression (and the native
    ``tm_normalize`` kernel, which is pinned bitwise to it) over the
    entire uint8 domain."""
    from pytorch_distributed_mnist_tpu.data.mnist import MNIST_MEAN, MNIST_STD

    c255, mean, std = jax.lax.optimization_barrier(
        (jnp.float32(255.0), jnp.float32(MNIST_MEAN),
         jnp.float32(MNIST_STD)))
    y = raw.astype(jnp.float32) / c255
    y = (y - mean) / std
    return y[..., None]


def quant_i8_traced(x):
    """In-XLA int8 activation quantization, BITWISE-equal to the host
    :func:`_quant_i8_host` staging path: multiply by the SAME
    precomputed f32 reciprocal (barrier-hidden, so XLA cannot re-derive
    it), round-to-nearest-even, clip to ±127. Normalized pixels are
    always finite, so the host quantizer's NaN pin has nothing to do
    here."""
    inv = jax.lax.optimization_barrier(
        jnp.float32(np.float32(1.0) / ACT_SCALE))
    scaled = jax.lax.round(x * inv, jax.lax.RoundingMethod.TO_NEAREST_EVEN)
    return jnp.clip(scaled, -127.0, 127.0).astype(jnp.int8)


def _floating_leaf(leaf) -> bool:
    return jnp.issubdtype(jnp.result_type(leaf), jnp.floating)


class ServePrecision:
    """One registered serving precision: how params quantize at install
    time, how the forward program transforms, and what dtype the staged
    activations ride.

    The hooks the engines call:

    - ``quantize(params, workers)`` — host-side, once per param install
      (boot, hot reload, regroup), OUTSIDE every engine lock: the slow
      part rides the same slow-part-outside-the-lock discipline as the
      ``device_put`` it precedes.
    - ``wrap_forward(forward)`` — the full-model program transform
      (dequantize weights / cast activations / cast logits back to f32
      so ``complete()`` stays precision-agnostic).
    - ``wrap_stage_forward(forward, first, last)`` — the MPMD per-stage
      transform: the first stage consumes the host-staged input dtype,
      inter-stage D2D hops ride ``hop_dtype`` (bf16 stays bf16; the
      int8 plane hops bf16 — half the hop bytes; re-quantizing
      activations per boundary would need per-publish calibration), and
      only the last stage casts logits back to f32.
    - ``stage_host(images, workers)`` — host-side activation transform
      before staging (int8: native ``tm_quant_i8`` with the fixed
      normalize-range scale; the staged batch and the H2D transfer are
      int8, a quarter of the f32 bytes).
    - ``expand_shardings(params, shardings, replicated)`` — the sharded
      plane's tree expansion: a :class:`QuantLeaf`'s values shard
      exactly as the f32 leaf would, its scalar scale replicates.

    ``f32`` is the identity on every hook — the engines' default path
    stays byte-identical to the pre-precision plane."""

    def __init__(self, name: str, *, weight_cast=None, int8_weights=False,
                 int8_activations=False, act_cast=None,
                 hop_dtype=None) -> None:
        self.name = name
        self.weight_cast = weight_cast  # host-side dtype cast (bf16)
        self.int8_weights = int8_weights
        self.int8_activations = int8_activations
        self.act_cast = act_cast  # in-program activation dtype (bf16)
        self.hop_dtype = hop_dtype if hop_dtype is not None else act_cast
        self.input_dtype = np.int8 if int8_activations else np.float32

    @property
    def identity(self) -> bool:
        """True only for f32: every hook is a no-op and the engines take
        their historical code paths bit-for-bit."""
        return not (self.weight_cast is not None or self.int8_weights
                    or self.int8_activations or self.act_cast is not None)

    def quantize(self, params, workers: int = 4):
        """IDEMPOTENT by design: a pool quantizes ONCE per publish and
        fans the quantized tree to its engines, whose ``_place`` runs
        quantize again — already-``QuantLeaf`` nodes pass through (an
        unguarded tree_map would descend into them and 'quantize' the
        f32 scale leaves), already-cast bf16 leaves re-cast copy-free."""
        if self.int8_weights:
            return jax.tree_util.tree_map(
                lambda leaf: leaf if isinstance(leaf, QuantLeaf)
                else (quantize_leaf_i8(leaf, workers)
                      if _floating_leaf(leaf) else leaf),
                params, is_leaf=lambda x: isinstance(x, QuantLeaf))
        if self.weight_cast is not None:
            cast = self.weight_cast
            return jax.tree_util.tree_map(
                lambda leaf: np.asarray(leaf).astype(cast, copy=False)
                if _floating_leaf(leaf) else leaf, params)
        return params

    def wrap_forward(self, forward):
        if self.identity:
            return forward
        spec = self

        def precision_forward(params, images):
            x = images
            if spec.int8_activations:
                x = x.astype(jnp.float32) * ACT_SCALE
            if spec.act_cast is not None:
                x = x.astype(spec.act_cast)
            p = dequantize_params(params) if spec.int8_weights else params
            return forward(p, x).astype(jnp.float32)

        return precision_forward

    def wrap_stage_forward(self, forward, first: bool, last: bool):
        if self.identity:
            return forward
        spec = self

        def stage_forward(params, x):
            if first:
                if spec.int8_activations:
                    x = x.astype(jnp.float32) * ACT_SCALE
                if spec.act_cast is not None:
                    x = x.astype(spec.act_cast)
            else:
                # The hop arrived at hop_dtype; restore the compute dtype.
                x = x.astype(spec.act_cast if spec.act_cast is not None
                             else jnp.float32)
            p = dequantize_params(params) if spec.int8_weights else params
            y = forward(p, x)
            if last:
                return y.astype(jnp.float32)
            return y.astype(spec.hop_dtype) \
                if spec.hop_dtype is not None else y

        return stage_forward

    def wrap_fused_forward(self, forward):
        """The WHOLE-program transform (ISSUE 16 tentpole): raw staged
        uint8 bytes -> f32 logits in ONE compiled program. The host
        preprocess (``tm_normalize``) and the int8 activation staging
        (``tm_quant_i8``) move into XLA via the bitwise-pinned
        :func:`fused_normalize` / :func:`quant_i8_traced`, then the math
        continues through the SAME :meth:`wrap_forward` transform the
        split plane compiles — the two planes share every op after the
        normalize, which is what makes the fused-vs-split logit pins
        bitwise at exact-fit buckets."""
        spec = self
        split = self.wrap_forward(forward)

        def fused_forward(params, raw):
            x = fused_normalize(raw)
            if spec.int8_activations:
                x = quant_i8_traced(x)
            return split(params, x)

        return fused_forward

    def wrap_fused_stage_forward(self, forward, first: bool, last: bool):
        """The MPMD fusion seam: only stage 0 consumes staged bytes, so
        only its program prepends the in-XLA normalize (+ int8 quant);
        later stages keep their :meth:`wrap_stage_forward` programs
        byte-identical to the split chain."""
        base = self.wrap_stage_forward(forward, first, last)
        if not first:
            return base
        spec = self

        def fused_stage(params, raw):
            x = fused_normalize(raw)
            if spec.int8_activations:
                x = quant_i8_traced(x)
            return base(params, x)

        return fused_stage

    def stage_host(self, images: np.ndarray, workers: int = 4) -> np.ndarray:
        if not self.int8_activations:
            return images
        return _quant_i8_host(images, ACT_SCALE, workers)

    def expand_shardings(self, params, shardings, replicated):
        if not self.int8_weights:
            return shardings
        return jax.tree_util.tree_map(
            lambda leaf, sh: QuantLeaf(q=sh, s=replicated)
            if _floating_leaf(leaf) else sh,
            params, shardings)


_PRECISIONS: Dict[str, ServePrecision] = {}


def register_precision(spec: ServePrecision) -> ServePrecision:
    """Register a serving precision (the extension point mirroring
    :func:`register_serve_mode`: a new quantization scheme becomes a
    ``--serve-precision`` choice and a bench sweep column by adding one
    :class:`ServePrecision`, no engine/pool/server change)."""
    if spec.name in _PRECISIONS:
        raise ValueError(f"serve precision {spec.name!r} already registered")
    _PRECISIONS[spec.name] = spec
    return spec


register_precision(ServePrecision(F32))
# bf16 stores the WEIGHTS in bfloat16 (half the HBM at rest, half the
# reload bytes); the compute dtype stays the MODEL's own policy — the
# models already cast per-layer to their compute_dtype (bf16 by default
# on TPU, the training --dtype flag), so forcing activations from
# outside would fight that policy (and break e.g. the ViT block scan,
# whose carry dtype the model owns). On the TPU-default models this IS
# full bf16 inference; on a --dtype f32 model it is weight-only bf16.
register_precision(ServePrecision("bf16", weight_cast=jnp.bfloat16))
register_precision(ServePrecision("int8w", int8_weights=True))
register_precision(ServePrecision(
    "int8", int8_weights=True, int8_activations=True,
    hop_dtype=jnp.bfloat16))


def serve_precisions() -> List[str]:
    """Every registered precision, ``f32`` first (the default)."""
    return [F32] + sorted(n for n in _PRECISIONS if n != F32)


def get_precision(name: Optional[str]) -> ServePrecision:
    """The registered :class:`ServePrecision` for ``name`` (``None``
    means f32), raising with the registry's vocabulary for unknown
    names."""
    try:
        return _PRECISIONS[name or F32]
    except KeyError:
        raise ValueError(
            f"unknown serve precision {name!r}; registered: "
            f"{serve_precisions()}"
        ) from None


def precision_engine_name(name: Optional[str],
                          precision: Optional[str]) -> Optional[str]:
    """Compose an engine/CompileLog name with its precision suffix —
    ``serve_forward_b{b}@{mode}.{prec}`` per the registry contract. f32
    keeps the historical (suffix-free) names, so every pre-precision
    compile-stats pin and recompile verdict is untouched. A multi-model
    server (``--model-set``) prefixes the MODEL as the name's first
    dotted segment (``linear.r0``, ``cnn.tensor.g0`` — the pool's
    ``name_prefix``), which is how per-plane /stats compile blocks
    attribute programs per model."""
    if not precision or precision == F32:
        return name
    return f"{name}.{precision}" if name else precision


class MeshPlacement:
    """How one sharded engine commits params and lowers its programs.

    Built once per engine (per mesh group) by :func:`build_placement`;
    the engine calls ``place_params`` at construction and on every
    hot-reload swap, ``place_input`` per dispatched bucket, and
    ``jit_forward`` once to get the pjit the bucket programs AOT-lower
    from. The param sharding TREE is precomputed from the template
    params — swap_params installs checkpoints with identical tree
    structure (the template-load contract), so one tree serves the
    engine's whole life.
    """

    def __init__(self, mode: str, mesh: Mesh, param_shardings,
                 name: str) -> None:
        self.mode = mode
        self.mesh = mesh
        self.name = name  # engine/CompileLog suffix: mode, or mode.g{i}
        self.devices = tuple(mesh.devices.flat)
        self.param_shardings = param_shardings
        self.input_sharding = NamedSharding(mesh, P())
        self.output_sharding = NamedSharding(mesh, P())

    def place_params(self, tree):
        return jax.device_put(tree, self.param_shardings)

    def place_input(self, arr):
        return jax.device_put(arr, self.input_sharding)

    def jit_forward(self, forward):
        return jax.jit(
            forward,
            in_shardings=(self.param_shardings, self.input_sharding),
            out_shardings=self.output_sharding,
        )

    def jit_fused_forward(self, forward):
        """The fused (whole-program) pjit: same shardings, but the raw
        staged batch is DONATED — its buffer belongs to XLA after the
        call, which is why the engine retires (never re-pins) the
        staging buffer it copied from."""
        return jax.jit(
            forward,
            in_shardings=(self.param_shardings, self.input_sharding),
            out_shardings=self.output_sharding,
            donate_argnums=(1,),
        )


def _sharded_leaf_dims(params, rules) -> Dict[str, list]:
    """leaf-path -> [(dim, size), ...] for every param leaf the rule
    table actually shards; empty means the mode is a no-op for this
    model."""
    out: Dict[str, list] = {}

    def visit(path, leaf):
        spec = leaf_spec(path, rules)
        shape = jax.numpy.shape(leaf)
        dims = [(dim, shape[dim]) for dim, axis in enumerate(spec)
                if axis is not None]
        if dims:
            out[jax.tree_util.keystr(path)] = dims

    jax.tree_util.tree_map_with_path(visit, params)
    return out


def validate_serve_mode(mode: str, model_name: str, mesh_devices: int,
                        params=None) -> None:
    """Reject unservable model x mode x mesh combinations with flag
    language BEFORE any mesh or program is built.

    Checks: the mode is registered and has a rule table for the model,
    and (with ``params``) every sharded weight dim divides by the mesh
    size — e.g. ``--serve-mesh 8`` over a ViT whose qkv features don't
    split 8 ways, or more experts' worth of mesh than the MoE has
    experts, fails here with the leaf named, not as a pjit trace error.
    """
    if mode == REPLICATED:
        if mesh_devices != 1:
            raise ValueError(
                f"--serve-mode replicated serves one engine per chip; a "
                f"{mesh_devices}-device mesh needs a sharded mode "
                f"({servable_modes(model_name)[1:] or 'none for this model'})"
            )
        return
    spec = _get_mode(mode)
    rules = spec.rules_for(model_name)  # raises for unservable models
    if mesh_devices < 1:
        raise ValueError(f"serve mesh needs >= 1 device, got {mesh_devices}")
    if params is not None:
        sharded = _sharded_leaf_dims(params, rules)
        if not sharded:
            raise ValueError(
                f"--serve-mode {mode}: no param leaf of model "
                f"{model_name!r} matches the {mode} rule table — the mesh "
                f"would replicate everything; use --serve-mode replicated"
            )
        for path, dims in sorted(sharded.items()):
            for dim, size in dims:
                if size % mesh_devices:
                    raise ValueError(
                        f"--serve-mode {mode} over {mesh_devices} devices: "
                        f"param {path} dim {dim} (size {size}) does not "
                        f"divide evenly; pick a mesh size dividing {size}"
                    )


def build_placement(mode: str, model_name: str, devices: Sequence,
                    params, name: Optional[str] = None,
                    precision: Optional[str] = None) -> MeshPlacement:
    """Mesh + sharding derivation for ONE engine spanning ``devices``.

    ``name`` defaults to the mode itself, giving the ISSUE-specified
    ``serve_forward_b{b}@{mode}`` CompileLog names on a single-group
    plane; multi-group pools pass ``{mode}.g{i}`` so compile stats and
    the zero-recompile verdicts stay attributable per group.

    ``precision``: the sharding derivation always walks the RAW f32
    param tree (the rule tables speak the training layout), then
    :meth:`ServePrecision.expand_shardings` maps the result onto the
    quantized tree the engine will actually install — a
    :class:`QuantLeaf`'s int8 values shard exactly as the f32 leaf
    would (same shape), its scalar scale replicates over the mesh.
    """
    devices = list(devices)
    validate_serve_mode(mode, model_name, len(devices), params)
    spec = _get_mode(mode)
    rules = spec.rules_for(model_name)
    mesh = Mesh(_device_array(devices), (spec.axis,))
    param_shardings = jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, leaf_spec(path, rules)), params
    )
    param_shardings = get_precision(precision).expand_shardings(
        params, param_shardings, NamedSharding(mesh, P()))
    return MeshPlacement(mode, mesh, param_shardings, name or mode)


def _device_array(devices):
    import numpy as np

    return np.asarray(devices, dtype=object).reshape(len(devices))


def partition_groups(devices: Sequence, mesh_size: int) -> List[list]:
    """Partition ``devices`` into ``mesh_size``-chip groups (the pool's
    sharded/staged plane: one spanning engine per group), rejecting
    indivisible shapes with flag language.

    Slice-aligned: when a DCN slice topology exists (real
    ``device.slice_index`` or the emulated ``TPUMNIST_DCN_SLICES``
    map), chips are ordered slice-major before chunking, so each
    group's intra-mesh collectives ride one slice's ICI whenever the
    mesh size fits in a slice — a group straddles slices only when it
    cannot fit, and the pool's ``/stats`` topology flags exactly those
    groups (``slice_straddling_groups``)."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import (
        device_slice_map,
    )

    devices = list(devices)
    if mesh_size < 1:
        raise ValueError(f"mesh size must be >= 1, got {mesh_size}")
    if len(devices) % mesh_size:
        raise ValueError(
            f"{len(devices)} serve device(s) do not partition into "
            f"{mesh_size}-device mesh groups; --serve-mesh must divide "
            f"--serve-devices"
        )
    smap = device_slice_map(devices)
    if smap is not None:
        order = sorted(range(len(devices)), key=lambda i: (smap[i], i))
        devices = [devices[i] for i in order]
    return [devices[i:i + mesh_size]
            for i in range(0, len(devices), mesh_size)]


def group_name(mode: str, index: int, n_groups: int) -> str:
    """One group's engine/CompileLog name: the bare mode when a single
    group spans the whole pool, ``{mode}.g{i}`` otherwise — so compile
    stats and the zero-recompile verdicts stay attributable per group
    (and, for staged modes, per stage under ``{name}.s{k}``)."""
    return mode if n_groups == 1 else f"{mode}.g{index}"


def build_group_placements(mode: str, model_name: str, devices: Sequence,
                           mesh_size: int, params) -> List[MeshPlacement]:
    """Partition ``devices`` into ``mesh_size``-chip groups, one
    :class:`MeshPlacement` per group — the pool's sharded plane: a
    sharded engine SPANS its mesh, so an 8-chip host at mesh 2 runs 4
    two-chip engines, not 8 one-chip replicas."""
    groups = partition_groups(devices, mesh_size)
    return [
        build_placement(mode, model_name, group, params,
                        name=group_name(mode, i, len(groups)))
        for i, group in enumerate(groups)
    ]


def build_group_engine(mode: str, model_name: str, devices: Sequence,
                       params, name: str, *, apply_fn, buckets,
                       input_shape, serve_log, params_epoch, workers,
                       model=None, precision: Optional[str] = None,
                       fuse: bool = False):
    """ONE engine spanning ``devices`` for ``mode`` — the single builder
    the pool's boot, regroup, and resize paths all share, which is what
    keeps a registered mode's engine construction from drifting between
    them. SPMD modes get the default ``MeshPlacement`` +
    ``InferenceEngine`` lowering; a mode with an ``engine_factory``
    (MPMD pipeline) builds its own engine behind the same surface.
    ``name`` arrives with its precision suffix already composed
    (:func:`precision_engine_name`); ``precision`` selects the program/
    quantization plane; ``fuse`` turns on the whole-program (raw-bytes
    -> logits, donated staging) dispatch plane on whatever engine the
    mode lowers to."""
    spec = _get_mode(mode)
    if spec.engine_factory is not None:
        return spec.engine_factory(
            model=model, model_name=model_name, apply_fn=apply_fn,
            params=params, devices=list(devices), name=name,
            buckets=buckets, input_shape=input_shape, serve_log=serve_log,
            params_epoch=params_epoch, workers=workers,
            precision=precision, fuse=fuse)
    from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine

    placement = build_placement(mode, model_name, list(devices), params,
                                name=name, precision=precision)
    return InferenceEngine(
        apply_fn, params, buckets=buckets, input_shape=input_shape,
        serve_log=serve_log, params_epoch=params_epoch,
        placement=placement, name=name, workers=workers,
        precision=precision, fuse=fuse)


def check_checkpoint_layout(layout: Optional[dict], mode: str,
                            model_name: str) -> None:
    """Boot/reload gate: the checkpoint's recorded training parallel
    layout must match the serving mode.

    Training stamps ``parallel_layout`` (tensor/sequence/expert/pipeline
    widths) into checkpoint meta; a checkpoint trained with expert or
    tensor sharding served ``replicated`` silently loses the very
    parallelism the operator trained for (or, for a model that only fits
    sharded, fails outright) — reject with the valid ``--serve-mode``
    choices named. ``None`` (pre-layout checkpoints, unit-test saves)
    passes: no provenance, nothing to contradict.

    Sequence parallelism is activation-only (identical params), so it
    never constrains serving. Pipeline-trained checkpoints — whose
    stage-stacked param tree no SPMD serving template matches, and which
    PR 8 therefore rejected by name — now name ``--serve-mode pipeline``
    as the valid choice: the MPMD plane (``serve/pipeline.py``) restores
    onto the pipelined template and splits by stage itself.
    """
    if not layout:
        return
    trained_axis = {"tensor": "tensor", "expert": "expert",
                    "pipeline": "pipeline"}
    for key, want_mode in trained_axis.items():
        if int(layout.get(key, 1)) > 1 and mode != want_mode:
            raise ValueError(
                f"checkpoint was trained with {key}-parallel "
                f"{layout[key]}; serve it with --serve-mode {want_mode} "
                f"(valid modes for --model {model_name}: "
                f"{servable_modes(model_name)})"
            )


# MODE: pipeline (MPMD, serve/pipeline.py). Registered HERE like every
# built-in mode so the registry is complete whenever it is importable —
# regardless of whether anything imported serve.pipeline first — with
# the heavy hooks imported lazily on first USE (an engine build / a
# template make), not at registry import.
def _pipeline_factory(**kwargs):
    from pytorch_distributed_mnist_tpu.serve.pipeline import (
        pipeline_engine_factory,
    )

    return pipeline_engine_factory(**kwargs)


def _pipeline_template(model, rng):
    from pytorch_distributed_mnist_tpu.serve.pipeline import (
        make_pipeline_template,
    )

    return make_pipeline_template(model, rng)


register_serve_mode(
    "pipeline", "stage", {"vit": pipeline_stage_rules},
    engine_factory=_pipeline_factory,
    make_template=_pipeline_template,
    staged=True,
)

# Import-time snapshots for docs/tests; anything validating a mode or
# precision must call serve_modes()/serve_precisions() (the live
# registries) so entries registered after import — the extension seam —
# are honored.
SERVE_MODES = serve_modes()
SERVE_PRECISIONS = serve_precisions()
