"""Forward-program registry: model x serve-mode -> mesh-lowered programs.

The single-device engine can only REPLICATE a forward per chip
(``serve/pool.py``): a model too big or too slow for one chip has no
serving path, and the repo's parallel-mode assets — the tensor-parallel
rule table (``parallel/tensor.py``) and the expert-parallel one
(``parallel/expert.py``) — are unservable. This registry is the missing
seam: given a model name and a serve mode, it builds the serving mesh,
derives the param/input/output shardings from the SAME rule tables
training uses (serving can never disagree with training on layout), and
hands the engine a :class:`MeshPlacement` it AOT-lowers its bucket
programs against — one pjit program per bucket over the mesh, same
zero-steady-state-recompile discipline, ``CompileLog`` names
``serve_forward_b{b}@{mode}``, params still an ARGUMENT of the compiled
programs so checkpoint hot-reload stays an atomic reference swap.

Modes (``SERVE_MODES``; extensible via :func:`register_serve_mode`):

- ``replicated`` — the PR 3/4 plane: one full forward per chip, fanned
  out by the pool. Servable by every model; the default, and built
  exactly as it always was (no placement object involved).
- ``tensor`` — Megatron column/row-parallel forward over a ``model``
  mesh axis (``vit_tp_rules``): qkv/mlp1 shard their output features,
  proj/mlp2 their input, XLA inserts the partial-sum AllReduce. One
  request's batch stays whole; the WEIGHTS and the per-token FLOPs
  split across the mesh — intra-request parallelism.
- ``expert`` — expert-parallel MoE forward over an ``expert`` mesh axis
  (``moe_ep_rules``): each device holds and computes only its local
  experts; the one-hot combine's sum over experts is the AllReduce.

Inputs and logits stay replicated over the mesh (every mesh device sees
the whole batch; MNIST batches are KBs — the win is weight/FLOP
placement, not activation sharding), which also keeps the engine's
host-side staging/bucketing machinery mode-agnostic: ``complete()``
reads a fully-replicated output exactly as it reads a single-device one.

A sharded engine SPANS its mesh devices, so the pool partitions local
chips into mesh GROUPS (``build_group_placements``) instead of
one-replica-per-device: 8 chips at ``--serve-mesh 2`` = 4 two-chip
engines behind the same least-loaded dispatcher.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.parallel.expert import moe_ep_rules
from pytorch_distributed_mnist_tpu.parallel.tensor import leaf_spec, vit_tp_rules

REPLICATED = "replicated"


class ServeMode:
    """One registered parallel serving mode: the mesh axis it shards
    over and, per model family, the rule table deriving every param
    leaf's ``PartitionSpec`` (the SAME table training's state sharding
    uses — ``parallel/tensor.py`` / ``parallel/expert.py``)."""

    def __init__(self, name: str, axis: str,
                 rules_by_model: Dict[str, Callable]) -> None:
        self.name = name
        self.axis = axis
        self.rules_by_model = dict(rules_by_model)

    def rules_for(self, model_name: str):
        try:
            rules_fn = self.rules_by_model[model_name]
        except KeyError:
            raise ValueError(
                f"--serve-mode {self.name} has no sharding rule table for "
                f"--model {model_name!r} (servable modes for it: "
                f"{servable_modes(model_name)})"
            ) from None
        return rules_fn(self.axis)


_MODES: Dict[str, ServeMode] = {}


def register_serve_mode(name: str, axis: str,
                        rules_by_model: Dict[str, Callable]) -> ServeMode:
    """Register a parallel serving mode (the extension point: a new
    parallel module's rule table becomes servable by adding one entry,
    no engine/pool/server change)."""
    if name == REPLICATED or name in _MODES:
        raise ValueError(f"serve mode {name!r} already registered")
    mode = ServeMode(name, axis, rules_by_model)
    _MODES[name] = mode
    return mode


register_serve_mode("tensor", "model", {"vit": vit_tp_rules})
register_serve_mode("expert", "expert", {"moe_mlp": moe_ep_rules})


def serve_modes() -> List[str]:
    """Every registered mode, ``replicated`` first (the default)."""
    return [REPLICATED] + sorted(_MODES)


# Import-time snapshot for docs/tests; anything validating a mode must
# call serve_modes()/_get_mode (the live registry) so modes registered
# after import — the extension seam — are honored.
SERVE_MODES = serve_modes()


def registered_mode_models() -> List[tuple]:
    """Every (mode, model) pair with a rule table, sorted — what the
    bench's sharded block iterates, so a mode added through
    ``register_serve_mode`` joins the throughput comparison and the
    per-bucket x mode recompile verdict without editing bench.py."""
    return [(name, model) for name, mode in sorted(_MODES.items())
            for model in sorted(mode.rules_by_model)]


def servable_modes(model_name: str) -> List[str]:
    """The serve modes with a rule table for ``model_name`` (always
    includes ``replicated``) — the vocabulary every rejection message
    speaks."""
    return [REPLICATED] + sorted(
        name for name, mode in _MODES.items()
        if model_name in mode.rules_by_model
    )


def _get_mode(mode: str) -> ServeMode:
    try:
        return _MODES[mode]
    except KeyError:
        raise ValueError(
            f"unknown serve mode {mode!r}; registered: {serve_modes()}"
        ) from None


class MeshPlacement:
    """How one sharded engine commits params and lowers its programs.

    Built once per engine (per mesh group) by :func:`build_placement`;
    the engine calls ``place_params`` at construction and on every
    hot-reload swap, ``place_input`` per dispatched bucket, and
    ``jit_forward`` once to get the pjit the bucket programs AOT-lower
    from. The param sharding TREE is precomputed from the template
    params — swap_params installs checkpoints with identical tree
    structure (the template-load contract), so one tree serves the
    engine's whole life.
    """

    def __init__(self, mode: str, mesh: Mesh, param_shardings,
                 name: str) -> None:
        self.mode = mode
        self.mesh = mesh
        self.name = name  # engine/CompileLog suffix: mode, or mode.g{i}
        self.devices = tuple(mesh.devices.flat)
        self.param_shardings = param_shardings
        self.input_sharding = NamedSharding(mesh, P())
        self.output_sharding = NamedSharding(mesh, P())

    def place_params(self, tree):
        return jax.device_put(tree, self.param_shardings)

    def place_input(self, arr):
        return jax.device_put(arr, self.input_sharding)

    def jit_forward(self, forward):
        return jax.jit(
            forward,
            in_shardings=(self.param_shardings, self.input_sharding),
            out_shardings=self.output_sharding,
        )


def _sharded_leaf_dims(params, rules) -> Dict[str, list]:
    """leaf-path -> [(dim, size), ...] for every param leaf the rule
    table actually shards; empty means the mode is a no-op for this
    model."""
    out: Dict[str, list] = {}

    def visit(path, leaf):
        spec = leaf_spec(path, rules)
        shape = jax.numpy.shape(leaf)
        dims = [(dim, shape[dim]) for dim, axis in enumerate(spec)
                if axis is not None]
        if dims:
            out[jax.tree_util.keystr(path)] = dims

    jax.tree_util.tree_map_with_path(visit, params)
    return out


def validate_serve_mode(mode: str, model_name: str, mesh_devices: int,
                        params=None) -> None:
    """Reject unservable model x mode x mesh combinations with flag
    language BEFORE any mesh or program is built.

    Checks: the mode is registered and has a rule table for the model,
    and (with ``params``) every sharded weight dim divides by the mesh
    size — e.g. ``--serve-mesh 8`` over a ViT whose qkv features don't
    split 8 ways, or more experts' worth of mesh than the MoE has
    experts, fails here with the leaf named, not as a pjit trace error.
    """
    if mode == REPLICATED:
        if mesh_devices != 1:
            raise ValueError(
                f"--serve-mode replicated serves one engine per chip; a "
                f"{mesh_devices}-device mesh needs a sharded mode "
                f"({servable_modes(model_name)[1:] or 'none for this model'})"
            )
        return
    spec = _get_mode(mode)
    rules = spec.rules_for(model_name)  # raises for unservable models
    if mesh_devices < 1:
        raise ValueError(f"serve mesh needs >= 1 device, got {mesh_devices}")
    if params is not None:
        sharded = _sharded_leaf_dims(params, rules)
        if not sharded:
            raise ValueError(
                f"--serve-mode {mode}: no param leaf of model "
                f"{model_name!r} matches the {mode} rule table — the mesh "
                f"would replicate everything; use --serve-mode replicated"
            )
        for path, dims in sorted(sharded.items()):
            for dim, size in dims:
                if size % mesh_devices:
                    raise ValueError(
                        f"--serve-mode {mode} over {mesh_devices} devices: "
                        f"param {path} dim {dim} (size {size}) does not "
                        f"divide evenly; pick a mesh size dividing {size}"
                    )


def build_placement(mode: str, model_name: str, devices: Sequence,
                    params, name: Optional[str] = None) -> MeshPlacement:
    """Mesh + sharding derivation for ONE engine spanning ``devices``.

    ``name`` defaults to the mode itself, giving the ISSUE-specified
    ``serve_forward_b{b}@{mode}`` CompileLog names on a single-group
    plane; multi-group pools pass ``{mode}.g{i}`` so compile stats and
    the zero-recompile verdicts stay attributable per group.
    """
    devices = list(devices)
    validate_serve_mode(mode, model_name, len(devices), params)
    spec = _get_mode(mode)
    rules = spec.rules_for(model_name)
    mesh = Mesh(_device_array(devices), (spec.axis,))
    param_shardings = jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, leaf_spec(path, rules)), params
    )
    return MeshPlacement(mode, mesh, param_shardings, name or mode)


def _device_array(devices):
    import numpy as np

    return np.asarray(devices, dtype=object).reshape(len(devices))


def build_group_placements(mode: str, model_name: str, devices: Sequence,
                           mesh_size: int, params) -> List[MeshPlacement]:
    """Partition ``devices`` into ``mesh_size``-chip groups, one
    :class:`MeshPlacement` per group — the pool's sharded plane: a
    sharded engine SPANS its mesh, so an 8-chip host at mesh 2 runs 4
    two-chip engines, not 8 one-chip replicas."""
    devices = list(devices)
    if mesh_size < 1:
        raise ValueError(f"mesh size must be >= 1, got {mesh_size}")
    if len(devices) % mesh_size:
        raise ValueError(
            f"{len(devices)} serve device(s) do not partition into "
            f"{mesh_size}-device mesh groups; --serve-mesh must divide "
            f"--serve-devices"
        )
    groups = [devices[i:i + mesh_size]
              for i in range(0, len(devices), mesh_size)]
    single = len(groups) == 1
    return [
        build_placement(mode, model_name, group, params,
                        name=mode if single else f"{mode}.g{i}")
        for i, group in enumerate(groups)
    ]


def check_checkpoint_layout(layout: Optional[dict], mode: str,
                            model_name: str) -> None:
    """Boot/reload gate: the checkpoint's recorded training parallel
    layout must match the serving mode.

    Training stamps ``parallel_layout`` (tensor/sequence/expert/pipeline
    widths) into checkpoint meta; a checkpoint trained with expert or
    tensor sharding served ``replicated`` silently loses the very
    parallelism the operator trained for (or, for a model that only fits
    sharded, fails outright) — reject with the valid ``--serve-mode``
    choices named. ``None`` (pre-layout checkpoints, unit-test saves)
    passes: no provenance, nothing to contradict.

    Sequence parallelism is activation-only (identical params), so it
    never constrains serving; pipeline-trained checkpoints have a
    stage-stacked param tree no serving template matches, so they are
    rejected by name rather than by a leaf-count load error.
    """
    if not layout:
        return
    trained_axis = {"tensor": "tensor", "expert": "expert"}
    for key, want_mode in trained_axis.items():
        if int(layout.get(key, 1)) > 1 and mode != want_mode:
            raise ValueError(
                f"checkpoint was trained with {key}-parallel "
                f"{layout[key]}; serve it with --serve-mode {want_mode} "
                f"(valid modes for --model {model_name}: "
                f"{servable_modes(model_name)})"
            )
    if int(layout.get("pipeline", 1)) > 1:
        raise ValueError(
            "checkpoint was trained with pipeline parallelism; no serve "
            f"mode lowers a stage-stacked param tree (valid modes for "
            f"--model {model_name}: {servable_modes(model_name)})"
        )
