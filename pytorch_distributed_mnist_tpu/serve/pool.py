"""Multi-chip serving data plane: one engine replica per local device.

A single :class:`~pytorch_distributed_mnist_tpu.serve.engine.
InferenceEngine` drives exactly one chip; on an 8-chip host that leaves
7 idle. The pool owns one :class:`EngineReplica` per local device — each
replica is a full engine pinned to its device (params ``device_put``
there, every bucket program AOT-compiled for it through the same
``precompile``/``CompileLog`` path, so compile stats and the
zero-recompile invariant stay per replica) — behind a dispatcher that
hands each formed batch to the least-loaded replica. MNIST inference is
embarrassingly parallel across batches, so replica fan-out is the whole
scaling story: no cross-chip collective runs on the serve path.

Dispatch is two-phase, mirroring the engine's dispatch/complete split:
``dispatch`` picks a replica, enqueues the device execution (JAX async
dispatch — returns immediately), and tracks the replica's in-flight
count; ``complete`` blocks on that batch's fetch and releases the
count. The pipelined batcher calls dispatch from its form/dispatch
worker and complete from its completion worker, so up to
``max_inflight`` batches execute concurrently across replicas while
host-side staging for the next batch proceeds.

Checkpoint hot-reload fans out: the watcher loads the checkpoint from
disk ONCE on the host, then ``swap_params`` installs it per replica
(one ``device_put`` per device). Each replica applies the engine's
swap-ordering rule — epochs compared under the replica's lock, an older
checkpoint never installs over a newer one — and each batch still
reports the epoch of the params that ACTUALLY computed it, captured
under the owning replica's lock.

**Sharded plane** (``serve_mode`` != replicated): a sharded engine
SPANS a mesh, so the pool partitions its chips into ``mesh_size``-chip
mesh GROUPS instead of one-replica-per-device — 8 chips at mesh 2 = 4
two-chip tensor/expert-parallel engines, each built from a
:class:`~pytorch_distributed_mnist_tpu.serve.programs.MeshPlacement`
(``serve/programs.py`` derives the shardings from the training rule
tables). Everything above the engine is group-agnostic: least-loaded
dispatch picks among groups, the hot-reload fan-out installs the ONE
host-side load per group with that group's ``NamedSharding`` tree, and
per-group ``CompileLog`` names (``serve_forward_b{b}@{mode}.g{i}``;
just ``@{mode}`` when one group spans the whole pool) keep the
zero-recompile verdict attributable.

**Self-healing** (ROADMAP item 3: topology change as a routine event,
serve side). A replica/mesh-group failure used to take its chips out of
service until a human restarted the server; now the pool treats it as a
lifecycle:

- **Attribution.** Every dispatch or completion error lands on the
  replica that raised it (input-shaped errors — ``ValueError``/
  ``TypeError``, the request's fault — are exempt: three malformed
  requests must never condemn a healthy group).
- **Failover, never a drop.** The failed batch immediately re-dispatches
  on another healthy replica (the handle keeps the preprocessed rows for
  exactly this), and only when NO healthy replica remains does the error
  reach the caller — so a group death under live traffic costs latency,
  not answers.
- **Quarantine.** ``quarantine_after`` consecutive failures (any success
  resets the count) quarantine the replica: the least-loaded dispatcher
  skips it, the reload fan-out skips it (the rebuild installs the latest
  params anyway).
- **Regroup.** A background thread rebuilds the group from its own chips
  — fresh engine (fresh :class:`MeshPlacement` on the sharded plane),
  AOT warm, then an atomic install under the pool lock (build and warm
  run OUTSIDE it: traffic keeps flowing on the healthy groups for the
  whole rebuild) — and bumps ``topology_generation``. Rebuild failures
  retry with backoff; an unhealable group stays quarantined and says so.

``resize()`` is the same machinery driven on purpose instead of by
failure: build + warm the new replica/group layout in the background,
swap the whole replica list atomically, let in-flight batches complete
on the old engines they hold handles to. ``topology()`` is the
observability surface ``/stats`` and ``loadgen --expect-groups`` read.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from pytorch_distributed_mnist_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    InferenceEngine,
    _InFlightBatch,
)

# Chaos-harness fault injection for the serve plane: "GROUP[:AFTER]"
# makes mesh group / replica GROUP's dispatch raise after AFTER
# successful dispatches — the single-process stand-in for a group's
# chips dying under it (the rebuilt generation of the group serves
# cleanly: the chips come back with the fresh engine). Driven by
# ``tools/chaos.py --serve --serve-fault`` and the self-healing twins.
SERVE_FAULT_ENV = "TPUMNIST_SERVE_FAULT"


def _parse_serve_fault(spec: str) -> Optional[Tuple[int, int]]:
    spec = spec.strip()
    if not spec:
        return None
    parts = spec.split(":")
    try:
        group = int(parts[0])
        after = int(parts[1]) if len(parts) > 1 else 0
        if len(parts) > 2:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"bad {SERVE_FAULT_ENV} spec {spec!r}: expected "
            f"GROUP_INDEX[:AFTER_N_BATCHES]") from None
    return group, after


def _is_input_error(exc: BaseException) -> bool:
    """Errors the REQUEST caused (shape/dtype validation), not the
    replica: they must neither count toward quarantine nor fail over
    (another replica would reject the same rows identically)."""
    return isinstance(exc, (ValueError, TypeError))


class EngineReplica:
    """One pinned (or mesh-group) engine + the pool's dispatch
    bookkeeping for it.

    ``pending`` (batches dispatched, not yet completed) is owned by the
    POOL's lock, not the replica: dispatch-time placement decisions need
    a consistent view across all replicas. ``device`` is the one pinned
    device on the replicated plane; ``devices`` is the full span (a
    1-tuple there, the mesh group on the sharded plane). ``generation``
    counts rebuilds of this group (0 = the boot engine);
    ``consecutive_failures``/``quarantined`` are the health state the
    self-healing lifecycle walks.
    """

    __slots__ = ("index", "name", "device", "devices", "engine", "pending",
                 "dispatched", "completed", "failures",
                 "consecutive_failures", "quarantined", "generation")

    def __init__(self, index: int, device, engine: InferenceEngine,
                 name: Optional[str] = None, devices=None) -> None:
        self.index = index
        self.name = name if name is not None else f"r{index}"
        self.device = device
        self.devices = tuple(devices) if devices is not None else (device,)
        self.engine = engine
        self.pending = 0  # in-flight batches (pool lock)
        self.dispatched = 0  # lifetime batches assigned (pool lock)
        self.completed = 0  # lifetime batches fetched OK (pool lock)
        self.failures = 0  # lifetime attributed errors (pool lock)
        self.consecutive_failures = 0  # reset by any success (pool lock)
        self.quarantined = False  # skipped by dispatch + reload fan-out
        self.generation = 0  # rebuilds of this group


class _PoolHandle:
    """An in-flight batch plus the replica that owns it — and the
    preprocessed rows themselves, so a completion failure can fail the
    batch over to a healthy replica instead of dropping it."""

    __slots__ = ("replica", "inflight", "images")

    def __init__(self, replica: EngineReplica,
                 inflight: _InFlightBatch, images) -> None:
        self.replica = replica
        self.inflight = inflight
        self.images = images


class EnginePool:
    """N engine replicas × N local devices behind a least-loaded
    dispatcher.

    Exposes the same surface the server's handlers and the reload
    watcher use on a bare engine (``preprocess``, ``buckets``,
    ``max_batch``, ``params_epoch``, ``swap_params``), so a pool drops
    in wherever one engine did.
    """

    def __init__(
        self,
        apply_fn,
        params,
        devices: Optional[Sequence] = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        input_shape: Tuple[int, ...] = (28, 28, 1),
        serve_log=None,
        params_epoch: Optional[int] = None,
        workers: int = 4,
        serve_mode: str = "replicated",
        mesh_size: int = 1,
        model_name: Optional[str] = None,
        model=None,
        quarantine_after: int = 3,
        auto_regroup: bool = True,
        regroup_retries: int = 3,
        precision: Optional[str] = None,
        name_prefix: str = "",
        fuse: bool = False,
    ) -> None:
        devices = list(devices) if devices is not None \
            else list(jax.local_devices())
        if not devices:
            raise ValueError("EnginePool needs at least one device")
        if quarantine_after < 1:
            raise ValueError(
                f"quarantine_after must be >= 1, got {quarantine_after}")
        self.apply_fn = apply_fn
        self.serve_log = serve_log
        self.serve_mode = serve_mode
        self.mesh_size = mesh_size
        self.model_name = model_name
        # The model CONFIG (not just its apply_fn): modes with a
        # registry engine_factory — MPMD pipeline — build per-stage
        # programs from the model's structure; SPMD modes ignore it.
        self.model = model
        self.input_shape = tuple(input_shape)
        self.workers = workers
        self.n_devices = len(devices)
        # Replica/group name prefix (multi-model serving: ``linear.r0``
        # vs ``cnn.r0``), so N models' replica rows, CompileLog programs,
        # and recompile verdicts stay attributable per model in one
        # process. Empty (the default) keeps every historical name.
        self.name_prefix = name_prefix
        self.quarantine_after = quarantine_after
        self.auto_regroup = auto_regroup
        self.regroup_retries = regroup_retries
        self._buckets = tuple(buckets)
        # The precision plane (serve/programs.py): one precision per
        # pool — every replica/group lowers its bucket programs at it,
        # and the reload fan-out quantizes per engine from the ONE
        # host-side f32 load (install-time quantization; _params_host
        # stays the raw tree a regroup/resize rebuilds from). f32 (the
        # default) resolves to the identity spec and changes nothing.
        from pytorch_distributed_mnist_tpu.serve.programs import (
            get_precision,
        )

        self._precision_spec = get_precision(precision)
        self.precision = self._precision_spec.name
        # Whole-program dispatch plane (fused raw-bytes -> logits bucket
        # programs, donated staging): one setting per pool, threaded to
        # every replica/group engine across boot, regroup, and resize so
        # the fleet never mixes dispatch planes.
        self.fuse = bool(fuse)
        if serve_mode != "replicated":
            from pytorch_distributed_mnist_tpu.serve.programs import (
                staged_mode,
            )

            self.staged = staged_mode(serve_mode)
        else:
            self.staged = False
        self._injected_fault = _parse_serve_fault(
            os.environ.get(SERVE_FAULT_ENV, ""))
        self._lock = threading.Lock()
        # Latest HOST-side params + epoch (the pre-device_put reference
        # every fan-out received): what a regroup/resize builds its
        # fresh engines from, so a rebuilt group can never boot on
        # boot-time params after a hot reload moved the fleet on.
        self._params_host = params
        self._params_host_epoch = params_epoch
        # Swap hooks (ISSUE 19): run under the pool lock AFTER a reload
        # fan-out completes — once every routable replica answers on the
        # new params, a cache generation bump retires every entry whose
        # compute could predate the swap. O(1) arithmetic only.
        self._swap_hooks: List[Callable] = []
        # Topology bookkeeping (pool lock): generation bumps on every
        # quarantine/regroup/resize so /stats can say "the shape
        # changed" without diffing replica rows.
        self._topology_generation = 0
        self._regroups = 0
        self._failovers = 0
        self._resizing = False
        self.replicas: List[EngineReplica] = self._make_replicas(
            devices, mesh_size, params, params_epoch)
        if serve_log is not None:
            serve_log.set_replicas_probe(self.snapshot)

    def _make_replicas(self, devices: List, mesh_size: int, params,
                       params_epoch: Optional[int]) -> List[EngineReplica]:
        """Build one generation of replicas over ``devices`` — the boot
        layout and every :meth:`resize` target go through here, so the
        two can never drift."""
        replicas: List[EngineReplica] = []
        if self.serve_mode != "replicated":
            # Sharded/staged plane: partition chips into mesh groups,
            # one spanning engine per group. serve/programs.py owns the
            # sharding derivation, every validity check, AND the engine
            # construction (build_group_engine routes a registered
            # engine_factory — MPMD pipeline — or the default
            # MeshPlacement lowering), so the pool never special-cases a
            # mode by name.
            from pytorch_distributed_mnist_tpu.serve.programs import (
                build_group_engine,
                group_name,
                partition_groups,
                precision_engine_name,
                validate_serve_mode,
            )

            if self.model_name is None:
                raise ValueError(
                    f"serve_mode {self.serve_mode!r} needs model_name= "
                    f"(the mode's rule table is per model family)")
            validate_serve_mode(self.serve_mode, self.model_name,
                                mesh_size, params)
            groups = partition_groups(devices, mesh_size)
            for i, group in enumerate(groups):
                name = precision_engine_name(
                    self.name_prefix
                    + group_name(self.serve_mode, i, len(groups)),
                    self.precision)
                engine = build_group_engine(
                    self.serve_mode, self.model_name, group, params, name,
                    apply_fn=self.apply_fn, buckets=self._buckets,
                    input_shape=self.input_shape, serve_log=self.serve_log,
                    params_epoch=params_epoch, workers=self.workers,
                    model=self.model, precision=self.precision,
                    fuse=self.fuse)
                replicas.append(EngineReplica(
                    i, group[0], engine, name=name, devices=group))
        else:
            from pytorch_distributed_mnist_tpu.serve.programs import (
                precision_engine_name,
            )

            if mesh_size != 1:
                raise ValueError(
                    "replicated serving runs one engine per chip; a "
                    f"{mesh_size}-device mesh needs a sharded serve_mode")
            for i, device in enumerate(devices):
                name = precision_engine_name(f"{self.name_prefix}r{i}",
                                             self.precision)
                engine = InferenceEngine(
                    self.apply_fn, params, buckets=self._buckets,
                    input_shape=self.input_shape, serve_log=self.serve_log,
                    params_epoch=params_epoch, device=device, name=name,
                    workers=self.workers, precision=self.precision,
                    fuse=self.fuse)
                replicas.append(EngineReplica(
                    i, device, engine, name=name))
        return replicas

    def _build_group_engine(self, devices: Tuple, name: str, params,
                            params_epoch: Optional[int]) -> InferenceEngine:
        """One fresh engine for an existing group's chips — the regroup
        path (the group keeps its name, so its CompileLog programs and
        /stats row stay attributable across rebuilds)."""
        if self.serve_mode != "replicated":
            from pytorch_distributed_mnist_tpu.serve.programs import (
                build_group_engine,
            )

            return build_group_engine(
                self.serve_mode, self.model_name, list(devices), params,
                name, apply_fn=self.apply_fn, buckets=self._buckets,
                input_shape=self.input_shape, serve_log=self.serve_log,
                params_epoch=params_epoch, workers=self.workers,
                model=self.model, precision=self.precision, fuse=self.fuse)
        return InferenceEngine(
            self.apply_fn, params, buckets=self._buckets,
            input_shape=self.input_shape, serve_log=self.serve_log,
            params_epoch=params_epoch, device=devices[0], name=name,
            workers=self.workers, precision=self.precision, fuse=self.fuse)

    # -- engine-compatible surface ----------------------------------------

    @property
    def n_replicas(self) -> int:
        return len(self.replicas)

    @property
    def buckets(self):
        return self.replicas[0].engine.buckets

    @property
    def max_batch(self) -> int:
        return self.replicas[0].engine.max_batch

    @property
    def params_epoch(self) -> Optional[int]:
        """The fleet's serving epoch: replica 0's (the swap fan-out is
        all-or-stale, so replicas only ever disagree for the microseconds
        a fan-out is mid-walk)."""
        return self.replicas[0].engine.params_epoch

    def preprocess(self, images) -> np.ndarray:
        return self.replicas[0].engine.preprocess(images)

    def warmup(self) -> None:
        """AOT-compile every replica's bucket programs, replicas in
        parallel (CompileLog attribution is thread-local, so each
        replica's compiles land under its own ``@r{i}`` program names).
        With a warm persistent cache these are fetches; cold, the
        parallelism overlaps N replicas' compile wall-clock."""
        self._warm(self.replicas)

    @staticmethod
    def _warm(replicas: Sequence[EngineReplica]) -> None:
        errors: List[BaseException] = []

        def _one(replica: EngineReplica) -> None:
            try:
                replica.engine.warmup()
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                errors.append(exc)

        threads = [threading.Thread(target=_one, args=(r,), daemon=True,
                                    name=f"pool-warmup-{r.name}")
                   for r in replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    def swap_params(self, params, epoch: Optional[int] = None,
                    path: Optional[str] = None) -> int:
        """Fan one host-side checkpoint load out to every healthy
        replica (one ``device_put`` per device). Each replica enforces
        the swap-ordering rule under its own lock, so a stale fan-out
        racing a newer one can never downgrade any replica. Quarantined
        replicas are skipped — their rebuild installs the pool's latest
        params anyway (tracked here, under the same ordering rule).
        Returns the number of replicas that installed (0 == stale
        everywhere)."""
        with self._lock:
            stale = (epoch is not None
                     and self._params_host_epoch is not None
                     and epoch < self._params_host_epoch)
            if not stale:
                self._params_host = params
                self._params_host_epoch = epoch
            replicas = [r for r in self.replicas if not r.quarantined]
        if stale:
            # Every replica serves (at least) the host epoch already and
            # would refuse this install per its own ordering rule — skip
            # the fan-out AND the quantization pass it would pay for.
            return 0
        # Quantize ONCE per publish, not once per replica: the engines'
        # install-time quantize is idempotent (QuantLeaf nodes pass
        # through), so fanning the pre-quantized tree out saves
        # (replicas - 1) full host-side quantization passes per reload.
        # Engine-factory (staged) modes are exempt — their engines
        # quantize PER STAGE SLICE after splitting, and the split runs
        # on the f32 tree the stage boundaries are defined over.
        # _params_host stays the RAW tree: regroup/resize rebuild paths
        # derive placements from it, which speaks the f32 layout.
        if not self.staged:
            params = self._precision_spec.quantize(params,
                                                   workers=self.workers)
        installed = 0
        for replica in replicas:
            if replica.engine.swap_params(params, epoch=epoch, path=path):
                installed += 1
        # Generation bump AFTER the whole fan-out (under the pool lock):
        # an entry inserted mid-fan-out captured the pre-bump generation
        # and is dropped at put; anything probed after this bump
        # computes on replicas that all hold the new params.
        with self._lock:
            for hook in self._swap_hooks:
                hook(epoch)
        return installed

    def add_swap_hook(self, hook: Callable) -> None:
        """Register ``hook(epoch)`` to run under the pool lock after
        each reload fan-out (the response cache's ``bump_generation``
        seam — O(1) arithmetic only)."""
        with self._lock:
            self._swap_hooks.append(hook)

    # -- dispatch / complete ----------------------------------------------

    def dispatch(self, images) -> _PoolHandle:
        """Assign one formed batch to the least-loaded HEALTHY replica
        and enqueue it there (JAX async dispatch: returns immediately;
        the bounded in-flight window lives in the batcher, which is the
        only caller that can overrun the fleet). A replica whose
        dispatch raises is attributed and excluded, and the batch fails
        over to the next healthy replica — the caller sees an error only
        when no healthy replica remains."""
        return self._dispatch_excluding(images, set())

    def _dispatch_excluding(self, images, exclude: set) -> _PoolHandle:
        while True:
            with self._lock:
                candidates = [r for r in self.replicas
                              if not r.quarantined and r not in exclude]
                if not candidates:
                    quarantined = [r.name for r in self.replicas
                                   if r.quarantined]
                    raise RuntimeError(
                        f"no healthy replica/mesh group to dispatch to "
                        f"({len(self.replicas)} group(s), quarantined "
                        f"{quarantined}"
                        + (f", {len(exclude)} failed for this batch"
                           if exclude else "")
                        + "); regroup in progress — retry")
                replica = min(candidates,
                              key=lambda r: (r.pending, r.index))
                replica.pending += 1
                replica.dispatched += 1
                injected = (
                    self._injected_fault is not None
                    and replica.generation == 0
                    and replica.index == self._injected_fault[0]
                    and replica.dispatched > self._injected_fault[1])
            try:
                if injected:
                    raise RuntimeError(
                        f"injected serve-group fault on {replica.name} "
                        f"({SERVE_FAULT_ENV}) — this group's chips are "
                        f"'dead' until the regroup rebuilds it")
                inflight = replica.engine.dispatch_logits(images)
            except BaseException as exc:  # noqa: BLE001 - attributed below
                with self._lock:
                    replica.pending -= 1
                if _is_input_error(exc):
                    raise  # the request's fault: no attribution, no failover
                self._note_failure(replica, exc, "dispatch")
                exclude.add(replica)
                with self._lock:
                    self._failovers += 1
                continue
            return _PoolHandle(replica, inflight, images)

    def complete(self, handle: _PoolHandle) \
            -> Tuple[np.ndarray, Optional[int]]:
        """Block on one dispatched batch's results; returns
        ``(logits (N, classes), epoch)`` with the epoch captured at that
        batch's dispatch on its replica. A completion failure (the
        fetch surfacing a dead group) is attributed to the replica and
        the batch FAILS OVER — re-dispatched whole on a healthy replica
        — so an in-flight request on a dying group is answered, never
        dropped; only with no healthy replica left does the error reach
        the caller (a per-request error, by the batcher's contract)."""
        current = handle
        exclude: set = set()
        while True:
            try:
                out = current.inflight.complete()
            except BaseException as exc:  # noqa: BLE001 - attributed below
                with self._lock:
                    current.replica.pending -= 1
                if _is_input_error(exc):
                    raise
                self._note_failure(current.replica, exc, "complete")
                exclude.add(current.replica)
                with self._lock:
                    self._failovers += 1
                # This re-dispatch runs on the COMPLETION thread and may
                # race the batcher's dispatch worker on the same healthy
                # engine. That is safe: an engine's per-batch dispatch
                # state is function-local (chunks/buffers) or
                # lock-protected (params capture, staging free-list) —
                # the one-dispatch-thread convention is a contention
                # guideline, not a correctness invariant (engine.py
                # documents both).
                current = self._dispatch_excluding(handle.images, exclude)
                continue
            with self._lock:
                current.replica.pending -= 1
                current.replica.completed += 1
                current.replica.consecutive_failures = 0
            return out

    def predict_complete(self, handle: _PoolHandle) \
            -> Tuple[np.ndarray, Optional[int]]:
        """``complete`` + host-side argmax: ``(labels (N,), epoch)``."""
        logits, epoch = self.complete(handle)
        return np.argmax(logits, axis=-1), epoch

    # -- self-healing ------------------------------------------------------

    def _note_failure(self, replica: EngineReplica, exc: BaseException,
                      stage: str) -> None:
        """Attribute one dispatch/completion error to its replica and
        walk the quarantine threshold. Counter mutation under the pool
        lock; logging, sink events, and the rebuild thread start all
        outside it."""
        with self._lock:
            replica.failures += 1
            replica.consecutive_failures += 1
            quarantine = (not replica.quarantined
                          and replica.consecutive_failures
                          >= self.quarantine_after)
            if quarantine:
                replica.quarantined = True
                self._topology_generation += 1
        print(f"serve pool: {stage} failed on {replica.name} "
              f"({replica.consecutive_failures} consecutive): {exc!r}",
              flush=True)
        if not quarantine:
            return
        print(f"serve pool: QUARANTINED {replica.name} after "
              f"{self.quarantine_after} consecutive failures; "
              f"dispatch skips it"
              + ("; rebuilding it from its chips in the background"
                 if self.auto_regroup else ""), flush=True)
        if self.serve_log is not None:
            self.serve_log.record_pool_event(
                "serve_quarantine", group=replica.name,
                consecutive_failures=replica.consecutive_failures,
                error=repr(exc)[:300])
        if self.auto_regroup:
            threading.Thread(
                target=self._regroup, args=(replica,), daemon=True,
                name=f"pool-regroup-{replica.name}").start()

    def _regroup(self, replica: EngineReplica) -> None:
        """Background rebuild of one quarantined group from its own
        chips: fresh engine (fresh mesh placement on the sharded
        plane), AOT warm, atomic install under the pool lock — traffic
        keeps flowing on the healthy groups throughout. Retries with
        backoff; an unhealable group stays quarantined, loudly."""
        for attempt in range(self.regroup_retries):
            try:
                with self._lock:
                    params = self._params_host
                    epoch = self._params_host_epoch
                engine = self._build_group_engine(
                    replica.devices, replica.name, params, epoch)
                engine.warmup()
            except BaseException as exc:  # noqa: BLE001 - retried, never fatal
                print(f"serve pool: regroup of {replica.name} failed "
                      f"(attempt {attempt + 1}/{self.regroup_retries}): "
                      f"{exc!r}", flush=True)
                time.sleep(0.2 * (attempt + 1))
                continue
            with self._lock:
                replica.engine = engine
                replica.quarantined = False
                replica.consecutive_failures = 0
                replica.generation += 1
                self._regroups += 1
                self._topology_generation += 1
                generation = replica.generation
                if (self._injected_fault is not None
                        and replica.index == self._injected_fault[0]):
                    # The injected 'group death' is spent the moment its
                    # group is rebuilt: without this, a later resize's
                    # fresh generation-0 replica at the same index would
                    # 're-die' (the fault models ONE boot-engine death).
                    self._injected_fault = None
            # A hot reload may have landed during the build/warm: the
            # stale-rejecting swap makes this catch-up idempotent.
            with self._lock:
                params = self._params_host
                epoch = self._params_host_epoch
            engine.swap_params(params, epoch=epoch)
            print(f"serve pool: REGROUPED {replica.name} (generation "
                  f"{generation}) from its {len(replica.devices)} "
                  f"chip(s); back in dispatch", flush=True)
            if self.serve_log is not None:
                self.serve_log.record_pool_event(
                    "serve_regroup", group=replica.name,
                    generation=generation)
            return
        print(f"serve pool: giving up on {replica.name} after "
              f"{self.regroup_retries} rebuild attempts; it stays "
              f"quarantined (resize or restart to recover its chips)",
              flush=True)

    # -- resize ------------------------------------------------------------

    def resize(self, n_devices: Optional[int] = None,
               mesh_size: Optional[int] = None,
               devices: Optional[Sequence] = None) -> dict:
        """Re-shape the pool under live traffic: add/remove replicas
        (``n_devices``; 0 = all local devices) and/or change the mesh
        group size on the sharded plane (``mesh_size``). The new layout
        is built and AOT-warmed in full while the OLD replicas keep
        serving; the swap is one atomic replica-list install under the
        pool lock. In-flight batches hold handles to their old replicas
        and complete on them untouched — zero dropped requests by
        construction. Returns ``{"old": topology, "new": topology}``.

        One resize at a time (a concurrent call raises); the serve mode
        itself is fixed at boot (a mode change means different param
        shardings AND a different layout-gate contract — restart for
        that, deliberately)."""
        with self._lock:
            if self._resizing:
                raise RuntimeError("a resize is already in progress")
            self._resizing = True
            params = self._params_host
            epoch = self._params_host_epoch
            old = self._topology_locked()
        try:
            local = list(devices) if devices is not None \
                else list(jax.local_devices())
            n = self.n_devices if n_devices is None else int(n_devices)
            if n == 0:
                n = len(local)
            if n < 1 or n > len(local):
                raise ValueError(
                    f"resize to {n} device(s): this host has "
                    f"{len(local)} local device(s)")
            sharded = self.serve_mode != "replicated"
            mesh = self.mesh_size if mesh_size is None else int(mesh_size)
            if sharded:
                from pytorch_distributed_mnist_tpu.serve.programs import (
                    validate_serve_mode,
                )

                if mesh == 0:
                    mesh = n
                if n % mesh:
                    raise ValueError(
                        f"serve_mesh {mesh} must divide serve_devices "
                        f"{n} (the pool runs one spanning engine per "
                        f"mesh group)")
                validate_serve_mode(self.serve_mode, self.model_name,
                                    mesh, params)
            else:
                if mesh not in (0, 1):
                    raise ValueError(
                        "replicated serving has no mesh to resize; "
                        "serve_mesh must stay 1")
                mesh = 1
            new_replicas = self._make_replicas(local[:n], mesh, params,
                                               epoch)
            self._warm(new_replicas)
            with self._lock:
                self.replicas = new_replicas
                self.n_devices = n
                self.mesh_size = mesh
                self._topology_generation += 1
                # The injection hook targets the BOOT layout; a resized
                # pool's fresh generation-0 replicas must not inherit it.
                self._injected_fault = None
                new = self._topology_locked()
            # Latest-params catch-up, same as regroup: a reload may have
            # raced the warm; the stale-rejecting swap is idempotent.
            with self._lock:
                params = self._params_host
                epoch = self._params_host_epoch
            for replica in new_replicas:
                replica.engine.swap_params(params, epoch=epoch)
            print(f"serve pool: RESIZED {old['groups']} group(s) x "
                  f"{old['mesh_devices']} -> {new['groups']} group(s) x "
                  f"{new['mesh_devices']} (topology generation "
                  f"{new['topology_generation']}); in-flight batches "
                  f"drain on the old engines", flush=True)
            if self.serve_log is not None:
                self.serve_log.record_pool_event(
                    "serve_resize", old=old, new=new)
            return {"old": old, "new": new}
        finally:
            with self._lock:
                self._resizing = False

    # -- observability -----------------------------------------------------

    def _topology_locked(self) -> dict:
        quarantined = [r.name for r in self.replicas if r.quarantined]
        topo = {
            "topology_generation": self._topology_generation,
            "serve_mode": self.serve_mode,
            "serve_precision": self.precision,
            "fused": self.fuse,
            "serve_devices": self.n_devices,
            "mesh_devices": self.mesh_size,
            "groups": len(self.replicas),
            "active_groups": len(self.replicas) - len(quarantined),
            "quarantined_groups": quarantined,
            "regroups": self._regroups,
            "failovers": self._failovers,
        }
        if self.staged:
            # A staged group's mesh axis is a pipeline CHAIN of this
            # many per-chip stage programs; /stats surfaces it as
            # pipeline_stages and loadgen --expect-stages asserts it.
            topo["pipeline_stages"] = self.mesh_size
        if self.serve_mode != "replicated":
            # Slice-alignment warning (field present only when a DCN
            # slice topology exists): groups whose chips straddle
            # slices run every intra-mesh collective over the slow
            # cross-slice axis — partition_groups prefers one slice
            # per group, so a non-empty list means the mesh size
            # cannot fit in a slice. loadgen --smoke carries it.
            from pytorch_distributed_mnist_tpu.parallel.mesh import (
                device_slice_map,
            )

            straddling = None
            for r in self.replicas:
                smap = device_slice_map(r.devices)
                if smap is None:
                    continue
                straddling = [] if straddling is None else straddling
                if len(set(smap)) > 1:
                    straddling.append(r.name)
            if straddling is not None:
                topo["slice_straddling_groups"] = straddling
        return topo

    def topology(self) -> dict:
        """The pool's shape + self-healing counters — the ``/stats``
        block ``loadgen --expect-groups`` asserts against."""
        with self._lock:
            return self._topology_locked()

    def fused_staging_retired(self) -> dict:
        """Donated-and-dropped fused staging buffers per bucket, summed
        across every replica (the donation lifecycle's pool-wide
        observable; empty when the fused plane is off)."""
        with self._lock:
            replicas = list(self.replicas)
        totals: dict = {}
        for r in replicas:
            for bucket, n in r.engine.fused_staging_retired().items():
                totals[bucket] = totals.get(bucket, 0) + n
        return totals

    def snapshot(self) -> dict:
        """Per-replica rows for ``/stats`` and the JSONL sink: device,
        serving epoch, in-flight and lifetime dispatch counts. Sharded
        (mesh-group) rows additionally carry the group's full device
        span and the serve mode; replicated rows keep the exact pre-mesh
        schema, with health fields (``quarantined``, rebuild
        ``generation``, ``failures``) appearing only once they are
        true/nonzero."""
        sharded = self.serve_mode != "replicated"
        with self._lock:
            rows = {}
            replicas = list(self.replicas)
            for r in replicas:
                row = {"device": str(r.device),
                       "pending": r.pending,
                       "dispatched": r.dispatched}
                if sharded:
                    row["mode"] = self.serve_mode
                    row["devices"] = [str(d) for d in r.devices]
                    if self.staged:
                        # A staged group is a CHAIN: stage k on chip k.
                        row["stages"] = len(r.devices)
                if r.quarantined:
                    row["quarantined"] = True
                if r.generation:
                    row["generation"] = r.generation
                if r.failures:
                    row["failures"] = r.failures
                rows[r.name] = row
        for replica in replicas:
            rows[replica.name]["params_epoch"] = replica.engine.params_epoch
        return rows
