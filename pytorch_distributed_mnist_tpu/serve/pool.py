"""Multi-chip serving data plane: one engine replica per local device.

A single :class:`~pytorch_distributed_mnist_tpu.serve.engine.
InferenceEngine` drives exactly one chip; on an 8-chip host that leaves
7 idle. The pool owns one :class:`EngineReplica` per local device — each
replica is a full engine pinned to its device (params ``device_put``
there, every bucket program AOT-compiled for it through the same
``precompile``/``CompileLog`` path, so compile stats and the
zero-recompile invariant stay per replica) — behind a dispatcher that
hands each formed batch to the least-loaded replica. MNIST inference is
embarrassingly parallel across batches, so replica fan-out is the whole
scaling story: no cross-chip collective runs on the serve path.

Dispatch is two-phase, mirroring the engine's dispatch/complete split:
``dispatch`` picks a replica, enqueues the device execution (JAX async
dispatch — returns immediately), and tracks the replica's in-flight
count; ``complete`` blocks on that batch's fetch and releases the
count. The pipelined batcher calls dispatch from its form/dispatch
worker and complete from its completion worker, so up to
``max_inflight`` batches execute concurrently across replicas while
host-side staging for the next batch proceeds.

Checkpoint hot-reload fans out: the watcher loads the checkpoint from
disk ONCE on the host, then ``swap_params`` installs it per replica
(one ``device_put`` per device). Each replica applies the engine's
swap-ordering rule — epochs compared under the replica's lock, an older
checkpoint never installs over a newer one — and each batch still
reports the epoch of the params that ACTUALLY computed it, captured
under the owning replica's lock.

**Sharded plane** (``serve_mode`` != replicated): a sharded engine
SPANS a mesh, so the pool partitions its chips into ``mesh_size``-chip
mesh GROUPS instead of one-replica-per-device — 8 chips at mesh 2 = 4
two-chip tensor/expert-parallel engines, each built from a
:class:`~pytorch_distributed_mnist_tpu.serve.programs.MeshPlacement`
(``serve/programs.py`` derives the shardings from the training rule
tables). Everything above the engine is group-agnostic: least-loaded
dispatch picks among groups, the hot-reload fan-out installs the ONE
host-side load per group with that group's ``NamedSharding`` tree, and
per-group ``CompileLog`` names (``serve_forward_b{b}@{mode}.g{i}``;
just ``@{mode}`` when one group spans the whole pool) keep the
zero-recompile verdict attributable.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

from pytorch_distributed_mnist_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    InferenceEngine,
    _InFlightBatch,
)


class EngineReplica:
    """One pinned (or mesh-group) engine + the pool's dispatch
    bookkeeping for it.

    ``pending`` (batches dispatched, not yet completed) is owned by the
    POOL's lock, not the replica: dispatch-time placement decisions need
    a consistent view across all replicas. ``device`` is the one pinned
    device on the replicated plane; ``devices`` is the full span (a
    1-tuple there, the mesh group on the sharded plane).
    """

    __slots__ = ("index", "name", "device", "devices", "engine", "pending",
                 "dispatched")

    def __init__(self, index: int, device, engine: InferenceEngine,
                 name: Optional[str] = None, devices=None) -> None:
        self.index = index
        self.name = name if name is not None else f"r{index}"
        self.device = device
        self.devices = tuple(devices) if devices is not None else (device,)
        self.engine = engine
        self.pending = 0  # in-flight batches (pool lock)
        self.dispatched = 0  # lifetime batches assigned (pool lock)


class _PoolHandle:
    """An in-flight batch plus the replica that owns it."""

    __slots__ = ("replica", "inflight")

    def __init__(self, replica: EngineReplica,
                 inflight: _InFlightBatch) -> None:
        self.replica = replica
        self.inflight = inflight


class EnginePool:
    """N engine replicas × N local devices behind a least-loaded
    dispatcher.

    Exposes the same surface the server's handlers and the reload
    watcher use on a bare engine (``preprocess``, ``buckets``,
    ``max_batch``, ``params_epoch``, ``swap_params``), so a pool drops
    in wherever one engine did.
    """

    def __init__(
        self,
        apply_fn,
        params,
        devices: Optional[Sequence] = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        input_shape: Tuple[int, ...] = (28, 28, 1),
        serve_log=None,
        params_epoch: Optional[int] = None,
        workers: int = 4,
        serve_mode: str = "replicated",
        mesh_size: int = 1,
        model_name: Optional[str] = None,
    ) -> None:
        devices = list(devices) if devices is not None \
            else list(jax.local_devices())
        if not devices:
            raise ValueError("EnginePool needs at least one device")
        self.serve_log = serve_log
        self.serve_mode = serve_mode
        self.mesh_size = mesh_size
        self.n_devices = len(devices)
        self._lock = threading.Lock()
        self.replicas: List[EngineReplica] = []
        if serve_mode != "replicated":
            # Sharded plane: partition chips into mesh groups, one
            # spanning engine per group (serve/programs.py owns the
            # mesh/sharding derivation and every validity check).
            from pytorch_distributed_mnist_tpu.serve.programs import (
                build_group_placements,
            )

            if model_name is None:
                raise ValueError(
                    f"serve_mode {serve_mode!r} needs model_name= (the "
                    f"mode's rule table is per model family)")
            placements = build_group_placements(
                serve_mode, model_name, devices, mesh_size, params)
            for i, placement in enumerate(placements):
                engine = InferenceEngine(
                    apply_fn, params, buckets=buckets,
                    input_shape=input_shape, serve_log=serve_log,
                    params_epoch=params_epoch, placement=placement,
                    name=placement.name, workers=workers)
                self.replicas.append(EngineReplica(
                    i, placement.devices[0], engine, name=placement.name,
                    devices=placement.devices))
        else:
            if mesh_size != 1:
                raise ValueError(
                    "replicated serving runs one engine per chip; a "
                    f"{mesh_size}-device mesh needs a sharded serve_mode")
            for i, device in enumerate(devices):
                name = f"r{i}"
                engine = InferenceEngine(
                    apply_fn, params, buckets=buckets,
                    input_shape=input_shape, serve_log=serve_log,
                    params_epoch=params_epoch, device=device, name=name,
                    workers=workers)
                self.replicas.append(EngineReplica(i, device, engine))
        if serve_log is not None:
            serve_log.set_replicas_probe(self.snapshot)

    # -- engine-compatible surface ----------------------------------------

    @property
    def n_replicas(self) -> int:
        return len(self.replicas)

    @property
    def buckets(self):
        return self.replicas[0].engine.buckets

    @property
    def max_batch(self) -> int:
        return self.replicas[0].engine.max_batch

    @property
    def params_epoch(self) -> Optional[int]:
        """The fleet's serving epoch: replica 0's (the swap fan-out is
        all-or-stale, so replicas only ever disagree for the microseconds
        a fan-out is mid-walk)."""
        return self.replicas[0].engine.params_epoch

    def preprocess(self, images) -> np.ndarray:
        return self.replicas[0].engine.preprocess(images)

    def warmup(self) -> None:
        """AOT-compile every replica's bucket programs, replicas in
        parallel (CompileLog attribution is thread-local, so each
        replica's compiles land under its own ``@r{i}`` program names).
        With a warm persistent cache these are fetches; cold, the
        parallelism overlaps N replicas' compile wall-clock."""
        errors: List[BaseException] = []

        def _warm(replica: EngineReplica) -> None:
            try:
                replica.engine.warmup()
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                errors.append(exc)

        threads = [threading.Thread(target=_warm, args=(r,), daemon=True,
                                    name=f"pool-warmup-{r.name}")
                   for r in self.replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    def swap_params(self, params, epoch: Optional[int] = None,
                    path: Optional[str] = None) -> int:
        """Fan one host-side checkpoint load out to every replica (one
        ``device_put`` per device). Each replica enforces the
        swap-ordering rule under its own lock, so a stale fan-out racing
        a newer one can never downgrade any replica. Returns the number
        of replicas that installed (0 == stale everywhere)."""
        installed = 0
        for replica in self.replicas:
            if replica.engine.swap_params(params, epoch=epoch, path=path):
                installed += 1
        return installed

    # -- dispatch / complete ----------------------------------------------

    def dispatch(self, images) -> _PoolHandle:
        """Assign one formed batch to the least-loaded replica and
        enqueue it there (JAX async dispatch: returns immediately; the
        bounded in-flight window lives in the batcher, which is the only
        caller that can overrun the fleet)."""
        with self._lock:
            replica = min(self.replicas, key=lambda r: (r.pending, r.index))
            replica.pending += 1
            replica.dispatched += 1
        try:
            inflight = replica.engine.dispatch_logits(images)
        except BaseException:
            with self._lock:
                replica.pending -= 1
            raise
        return _PoolHandle(replica, inflight)

    def complete(self, handle: _PoolHandle) \
            -> Tuple[np.ndarray, Optional[int]]:
        """Block on one dispatched batch's results; returns
        ``(logits (N, classes), epoch)`` with the epoch captured at that
        batch's dispatch on its replica."""
        try:
            return handle.inflight.complete()
        finally:
            with self._lock:
                handle.replica.pending -= 1

    def predict_complete(self, handle: _PoolHandle) \
            -> Tuple[np.ndarray, Optional[int]]:
        """``complete`` + host-side argmax: ``(labels (N,), epoch)``."""
        logits, epoch = self.complete(handle)
        return np.argmax(logits, axis=-1), epoch

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        """Per-replica rows for ``/stats`` and the JSONL sink: device,
        serving epoch, in-flight and lifetime dispatch counts. Sharded
        (mesh-group) rows additionally carry the group's full device
        span and the serve mode; replicated rows keep the exact pre-mesh
        schema."""
        sharded = self.serve_mode != "replicated"
        with self._lock:
            rows = {}
            for r in self.replicas:
                row = {"device": str(r.device),
                       "pending": r.pending,
                       "dispatched": r.dispatched}
                if sharded:
                    row["mode"] = self.serve_mode
                    row["devices"] = [str(d) for d in r.devices]
                rows[r.name] = row
        for replica in self.replicas:
            rows[replica.name]["params_epoch"] = replica.engine.params_epoch
        return rows
