"""Attention ops: dense reference + blockwise online-softmax building block.

The reference repo has no attention anywhere (its model is a single
``Linear(784, 10)``, ``/root/reference/multi_proc_single_gpu.py:119-126``;
SURVEY.md section 2c marks every sequence-parallel strategy ABSENT). This
framework carries attention as a first-class op family anyway, because
long-context is first-class in the TPU design: the sequence-parallel
machinery in ``parallel/ring.py`` / ``parallel/ulysses.py`` is built on the
blockwise kernel here, and the ``vit`` model (``models/attention.py``)
exercises it end to end.

Layout convention throughout: ``(B, T, H, D)`` — batch, tokens, heads, head
dim. TPU notes: scores are computed in float32 (softmax is the numerically
delicate reduction; the MXU matmuls feeding it may be bf16), and the
blockwise form is exactly the online-softmax recurrence XLA:TPU fuses well —
no materialized (T, T) matrix bigger than one (T_q_block, T_k_block) tile.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp


NEG_INF = -1e30  # softmax mask value; avoids -inf NaN propagation in exp


def full_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Dense softmax attention, ``(B, T, H, D)`` in and out.

    The single-device reference semantics that the ring / Ulysses
    sequence-parallel paths must reproduce exactly (their tests assert
    allclose against this).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    # (B, H, Tq, Tk)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    if causal:
        # A fully-masked row (possible when Tq > Tk) must output zeros, not
        # the uniform mean of V — match the blockwise op's guard below.
        p = jnp.where(mask, p, 0.0)
    p = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


class OnlineSoftmaxState(NamedTuple):
    """Carry of the blockwise (flash-style) attention recurrence.

    ``o``: unnormalized output accumulator, (B, Tq, H, D) float32;
    ``m``: running row max of scores, (B, H, Tq) float32;
    ``l``: running softmax normalizer, (B, H, Tq) float32.
    """

    o: jnp.ndarray
    m: jnp.ndarray
    l: jnp.ndarray


def online_softmax_init(q: jnp.ndarray) -> OnlineSoftmaxState:
    b, tq, h, d = q.shape
    return OnlineSoftmaxState(
        o=jnp.zeros((b, tq, h, d), jnp.float32),
        m=jnp.full((b, h, tq), NEG_INF, jnp.float32),
        l=jnp.zeros((b, h, tq), jnp.float32),
    )


def online_softmax_block(
    state: OnlineSoftmaxState,
    q: jnp.ndarray,
    k_blk: jnp.ndarray,
    v_blk: jnp.ndarray,
    *,
    scale: Optional[float] = None,
    mask: Optional[jnp.ndarray] = None,
) -> OnlineSoftmaxState:
    """Fold one K/V block into the running attention state.

    ``mask``: optional (Tq, Tk_blk) or (B, H, Tq, Tk_blk) boolean, True =
    attend. This is the standard streaming-softmax update: rescale the old
    accumulator by ``exp(m_old - m_new)``, add the new block's contribution.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k_blk.astype(jnp.float32)
    ) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(state.m, jnp.max(s, axis=-1))
    # exp(NEG_INF - NEG_INF) must be 0, not 1: a fully-masked-so-far row has
    # m == NEG_INF; guard the correction term.
    corr = jnp.where(state.m <= NEG_INF / 2, 0.0, jnp.exp(state.m - m_new))
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = state.l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
    # corr is (B, H, Tq); o is (B, Tq, H, D) -> align axes.
    o_new = state.o * corr.transpose(0, 2, 1)[..., None] + pv
    return OnlineSoftmaxState(o=o_new, m=m_new, l=l_new)


def online_softmax_finish(state: OnlineSoftmaxState, dtype=jnp.float32) -> jnp.ndarray:
    """Normalize the accumulator: ``o / l`` (safe where l == 0)."""
    l = state.l.transpose(0, 2, 1)[..., None]  # (B, Tq, H, 1)
    return (state.o / jnp.maximum(l, 1e-30)).astype(dtype)
