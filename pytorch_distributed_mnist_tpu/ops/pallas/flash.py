"""Flash attention (Pallas TPU): fused forward AND backward kernels.

Blockwise online-softmax attention: scores are computed tile-by-tile in
VMEM and never materialized as a (T, T) matrix in HBM — in either pass.
The forward kernel additionally emits the per-row logsumexp; the backward
is the standard two-pass flash recipe over that residual:

  delta_i = rowsum(dO_i * O_i)                       (tiny elementwise, XLA)
  P_ij    = exp(scale * q_i.k_j - lse_i)             (recomputed per tile)
  dV_j    = sum_i P_ij^T dO_i
  dS_ij   = P_ij * (dO_i.V_j - delta_i)
  dQ_i    = scale * sum_j dS_ij K_j                  (kernel 1: grid over i)
  dK_j    = scale * sum_i dS_ij^T Q_i                (kernel 2: grid over j)

so gradients also run at flash memory cost — no ``jax.vjp`` of a dense
reference anywhere (earlier revisions recomputed a (T, T) matrix in the
backward, which forfeited the memory win for training). Oracle for all
three kernels: ``full_attention`` under ``jax.vjp``, asserted in interpret
mode by tests/test_pallas_kernels.py.

The reference repo has no attention at all
(``/root/reference/multi_proc_single_gpu.py:119-126``; SURVEY.md section 2c
marks every sequence strategy ABSENT) — this op family exists because
long-context is first-class in the TPU design: ``ring_attention_local``
(parallel/ring.py) accepts any per-block attention update, and this kernel
is what a production config uses inside each ring step.

Layout: ``(B, T, H, D)``; kernels run per (batch*head) with both matmuls
per tile on the MXU in f32 accumulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_mnist_tpu.ops.attention import NEG_INF

__all__ = ["flash_attention", "sharded_flash_attention"]


def _keep_mask(iq, jk, block_q, block_k, t_real, causal):
    """(BQ, BK) validity: in-range q row, in-range k col, causal triangle.

    The causal form is start-aligned (qi >= ki), identical to the dense
    oracle's end-aligned tril only when Tq == Tk — which ``flash_attention``
    asserts, since the same residuals/padding already require it."""
    qi = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    ki = jk * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    keep = (qi < t_real) & (ki < t_real)
    if causal:
        keep &= qi >= ki
    return keep


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool, scale: float, block_q: int, t_real: int):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    Emits both the normalized output block and the row logsumexp
    ``lse = m + log(l)`` — the single residual the backward kernels need to
    reconstruct any P tile.
    """
    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)
    t = k_ref.shape[1]
    nk = t // block_k
    iq = pl.program_id(1)
    masked = causal or t_real < t

    def body(j, carry):
        o, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if masked:
            s = jnp.where(
                _keep_mask(iq, j, block_q, block_k, t_real, causal), s, NEG_INF
            )
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked:
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o * corr + pv, m_new, l

    d = q_ref.shape[-1]
    o = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, nk, body, (o, m, l))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # lse is carried as (BQ, 1): Mosaic requires the last two block dims be
    # (8, 128)-tile friendly or equal to the array dims, which a flat (1, BQ)
    # row block violates on real TPU (BQ lands in the sublane slot).
    lse_ref[0] = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)


def _block_sizes(t: int, block: int | None = None):
    # Pad T up to a tile-friendly block multiple (never shrink the block to
    # a divisor of T — a prime T would degrade to block 1); padded K
    # positions are masked inside the kernels, padded Q rows sliced off.
    # Default block 128 = the MXU tile. No flash-vs-dense ratio is
    # currently established at any T: the round-3 capture that timed
    # this config was invalidated (sync returned early; BASELINE.md,
    # tools/captured/kernels_r3_invalid.json). Bigger tiles at long T
    # are a plausible win (amortized loop/pipeline overhead; s/p
    # scratch is block^2 f32, 256 KB at 256 — well inside VMEM) but
    # UNMEASURED: the on-chip sweep (tools/sweep_flash.py, queued in
    # tools/tpu_watch_r4.sh) exists to decide it. Until a valid
    # flash_sweep.json lands, the default stays the MXU tile and the
    # hypothesis is reachable via the explicit ``block=`` override.
    if block is None:
        block = 128 if t >= 128 else ((t + 7) // 8) * 8
    t_pad = ((t + block - 1) // block) * block
    return block, t_pad


def _to_heads(x, b, t, h, d, t_pad):
    """(B, T, H, D) -> (B*H, Tp, D): one grid row per batch-head pair."""
    x = x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    if t_pad != t:
        x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
    return x


def _from_heads(x, b, t, h, d):
    return x[:, :t].reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_forward(q, k, v, causal: bool, scale: float, interpret: bool,
                   block_override: int | None = None):
    b, t, h, d = q.shape
    block, t_pad = _block_sizes(t, block_override)
    qh = _to_heads(q, b, t, h, d, t_pad)
    kh = _to_heads(k, b, t, h, d, t_pad)
    vh = _to_heads(v, b, t, h, d, t_pad)
    kernel = functools.partial(
        _fwd_kernel, block_k=block, causal=causal,
        scale=scale, block_q=block, t_real=t,
    )
    # NOTE: each program holds the full (Tp, D) K and V in VMEM, which caps
    # the sequence around T ~ 16k at D=64 f32 (~16 MB VMEM budget). Past
    # that, stream K/V through a third grid dimension — the online-softmax
    # carry already supports it; the ring (parallel/ring.py) also divides T
    # by the seq-axis size per device before this kernel sees it.
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t_pad // block),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block, 1), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, t_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t_pad, 1), jnp.float32),
        ),
        interpret=interpret,
    )(qh, kh, vh)
    return _from_heads(out, b, t, h, d), out, lse


# --------------------------------------------------------------------------
# Backward
# --------------------------------------------------------------------------


def _dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dq_ref, *,
               block_k: int, causal: bool, scale: float, block_q: int,
               t_real: int):
    """Grid (B*H, q-block): stream K/V, accumulate this q-block's dQ."""
    q = q_ref[0].astype(jnp.float32)          # (BQ, D)
    do = do_ref[0].astype(jnp.float32)        # (BQ, D)
    lse = lse_ref[0]                          # (BQ, 1)
    delta = delta_ref[0]                      # (BQ, 1)
    t = k_ref.shape[1]
    nk = t // block_k
    iq = pl.program_id(1)

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        keep = _keep_mask(iq, j, block_q, block_k, t_real, causal)
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    d = q_ref.shape[-1]
    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (scale * dq).astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_k: int, causal: bool, scale: float,
                block_q: int, t_real: int):
    """Grid (B*H, k-block): stream Q/dO rows, accumulate dK and dV."""
    k_blk = k_ref[0].astype(jnp.float32)      # (BK, D)
    v_blk = v_ref[0].astype(jnp.float32)      # (BK, D)
    t = q_ref.shape[1]
    nq = t // block_q
    jk = pl.program_id(1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]      # (BQ, 1)
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]  # (BQ, 1)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        keep = _keep_mask(i, jk, block_q, block_k, t_real, causal)
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BK, D)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BK, D)
        return dk, dv

    d = k_ref.shape[-1]
    zero = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (zero, zero))
    dk_ref[0] = (scale * dk).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, o_heads, lse, g, causal: bool, scale: float,
                    interpret: bool, block_override: int | None = None):
    b, t, h, d = q.shape
    block, t_pad = _block_sizes(t, block_override)
    qh = _to_heads(q, b, t, h, d, t_pad)
    kh = _to_heads(k, b, t, h, d, t_pad)
    vh = _to_heads(v, b, t, h, d, t_pad)
    doh = _to_heads(g, b, t, h, d, t_pad)
    # delta = rowsum(dO * O): tiny elementwise op, fine in XLA. o_heads is
    # the forward kernel's padded (B*H, Tp, D) output, reused as-is. Kept
    # as (B*H, Tp, 1) like lse so row blocks are Mosaic-tileable.
    delta = jnp.sum(doh * o_heads.astype(jnp.float32), axis=-1,
                    keepdims=True)  # (B*H, Tp, 1)

    common = dict(block_k=block, causal=causal, scale=scale,
                  block_q=block, t_real=t)
    seq_spec = pl.BlockSpec((1, block, d), lambda i, j: (i, j, 0),
                            memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, block, 1), lambda i, j: (i, j, 0),
                            memory_space=pltpu.VMEM)
    full_spec = pl.BlockSpec((1, t_pad, d), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    full_row = pl.BlockSpec((1, t_pad, 1), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    grid = (b * h, t_pad // block)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=grid,
        in_specs=[seq_spec, seq_spec, row_spec, row_spec, full_spec, full_spec],
        out_specs=seq_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, t_pad, d), q.dtype),
        interpret=interpret,
    )(qh, doh, lse, delta, kh, vh)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=grid,
        in_specs=[seq_spec, seq_spec, full_spec, full_spec, full_row, full_row],
        out_specs=(seq_spec, seq_spec),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, t_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_pad, d), v.dtype),
        ),
        interpret=interpret,
    )(kh, vh, qh, doh, lse, delta)

    return (
        _from_heads(dq, b, t, h, d),
        _from_heads(dk, b, t, h, d),
        _from_heads(dv, b, t, h, d),
    )


# --------------------------------------------------------------------------
# custom_vjp plumbing
# --------------------------------------------------------------------------


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, block):
    out, _, _ = _flash_forward(
        q, k, v, causal, scale, _interpret_default(), block)
    return out


def _flash_fwd(q, k, v, causal, scale, block):
    out, o_heads, lse = _flash_forward(
        q, k, v, causal, scale, _interpret_default(), block
    )
    return out, (q, k, v, o_heads, lse)


def _flash_bwd(causal, scale, block, residuals, g):
    q, k, v, o_heads, lse = residuals
    return _flash_backward(
        q, k, v, o_heads, lse, g, causal, scale, _interpret_default(), block
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: float | None = None, block: int | None = None):
    """Flash attention on ``(B, T, H, D)``; drop-in for ``full_attention``.

    Fully differentiable with fused Pallas forward and backward kernels
    (no (T, T) materialization in either pass); off-TPU the kernels run in
    interpreter mode so tests are hermetic. Self-attention shapes only:
    Tq must equal Tk (the kernel's start-aligned causal mask and the dense
    oracle's end-aligned mask agree exactly there).

    ``block`` overrides the q/k tile edge (multiple of 8; default 128 —
    the MXU tile and the configuration all captured measurements used.
    The override exists for the on-chip block sweep,
    tools/sweep_flash.py, which decides whether long sequences get a
    bigger default).
    """
    if q.shape[1] != k.shape[1]:
        raise ValueError(
            f"flash_attention requires Tq == Tk (self-attention); got "
            f"Tq={q.shape[1]}, Tk={k.shape[1]} — use full_attention for "
            f"cross-attention shapes"
        )
    if block is not None and (block < 8 or block % 8):
        raise ValueError(f"block must be a multiple of 8, got {block}")
    if block is not None and block > 512:
        # VMEM-derived cap: the bwd kernel's f32 scratch grows as block^2
        # (s/p tiles — 1 MB each at 512) plus several block x D operands;
        # past 512 the working set approaches the ~16 MB/core VMEM and
        # Mosaic fails with an opaque allocation error rather than this
        # message. The sweep (tools/sweep_flash.py) tops out at 512 too.
        raise ValueError(
            f"block must be <= 512 (block^2 f32 scratch exceeds VMEM "
            f"beyond that), got {block}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash(q, k, v, causal, float(scale), block)


def sharded_flash_attention(q, k, v, *, mesh, batch_axis=None,
                            head_axis=None, causal: bool = False,
                            scale: float | None = None):
    """Flash attention embedded in a GSPMD program via nested shard_map.

    Attention is embarrassingly parallel over batch AND heads, so on a
    ``data x model`` mesh each device runs the kernel on its local
    ``(B/dp, T, H/tp, D)`` block — no gather, no cross-device softmax.
    This is how ``--attention flash`` composes with ``--tensor-parallel``
    (the CLI passes ``head_axis='model'``): the Megatron rule table
    shards the qkv/proj weights on heads, and this wrapper keeps the
    kernel's view consistent with that layout. Head count must divide the
    head-axis size (the same requirement the TP rules impose).
    """
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, None, head_axis, None)
    fn = functools.partial(flash_attention, causal=causal, scale=scale)
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
